"""The ordering-service lambda pipeline, in-proc.

Re-creates the reference routerlicious topology (SURVEY.md §2.5) over
the in-memory message log:

    alfred → [rawdeltas] → deli → [deltas] → {scriptorium, broadcaster,
                                              scribe}

- `AlfredIngress` — WS front door (lambdas/src/alfred/index.ts:211):
  admits connections, validates submissions (size cap), forwards to
  the rawdeltas topic, routes nacks/ops back to sockets.
- `DeliLambda` — the sequencer (lambdas/src/deli/lambda.ts:215,
  ticket :818): stamps seq/MSN via DocumentSequencer, nacks invalid
  submissions, checkpoints (offset + sequencer state) like
  checkpointContext.ts.
- `ScriptoriumLambda` — durable op log (scriptorium/lambda.ts:35),
  serving the delta-storage catch-up reads.
- `BroadcasterLambda` — per-doc fan-out to connected sockets
  (broadcaster/lambda.ts:49).
- `ScribeLambda` — protocol-op folding + summary ack/nack
  (scribe/lambda.ts:56,252): maintains ProtocolOpHandler per doc,
  validates client summaries against the content-addressed store, and
  emits summaryAck/summaryNack control messages back through deli.

The same production lambdas run under the in-proc pump exactly as the
reference's LocalOrderer runs the real lambda classes over LocalKafka
(memory-orderer/src/localOrderer.ts:95,245).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Union

from ..protocol.messages import (
    DocumentMessage,
    MessageType,
    NackMessage,
    SequencedMessage,
    trace_stage_once,
    trace_submit_ts,
)
from ..protocol.quorum import ProtocolOpHandler
from ..utils.events import BufferedListener
from ..utils.metrics import get_registry
from .castore import ContentAddressedStore
from .log import LogConsumer, MessageLog
from .queue import partition_of, partition_suffix, split_by_partition
from .sequencer import DocumentSequencer

SYSTEM_CLIENT = -1  # server-originated control messages (scribe acks)
MAX_OP_BYTES = 768 * 1024  # alfred's op-size nack threshold
_historian_seq = 0  # distinct metrics label per LocalServer historian


# --------------------------------------------------------------------------
# deli
# --------------------------------------------------------------------------


class DeliLambda:
    """Sequences the rawdeltas stream into the deltas stream.

    Scalar reference implementation; the device-batched drop-in is
    `deli_kernel.KernelDeliLambda` (LocalServer `deli_impl="kernel"` /
    env ``FLUID_DELI=kernel``), for which this class is the oracle and
    fallback. Output is buffered per pump and flushed with ONE
    `append_many` (one journal write) instead of a locked/flushed
    append per record."""

    def __init__(self, log: MessageLog, checkpoint: Optional[dict] = None,
                 max_pump: int = 8192, raw_topic: str = "rawdeltas"):
        """`raw_topic` names the ingress topic: the sharded LocalServer
        (``n_partitions>1``) runs one deli per ``rawdeltas-p{k}``
        partition topic, all emitting into the one deltas stream (a
        doc lives in exactly one partition, so per-doc order holds)."""
        self.log = log
        self.sequencers: Dict[str, DocumentSequencer] = {}
        self.max_pump = max_pump
        offset = 0
        if checkpoint:
            from .supervisor import unwrap_ranged_state

            offset = checkpoint["offset"]
            # Tolerate the elastic fabric's ranged checkpoint envelope
            # (doc map + predecessor cursors): the doc states restore
            # identically on every frontend.
            docs = unwrap_ranged_state(checkpoint["docs"])
            for doc_id, state in (docs or {}).items():
                self.sequencers[doc_id] = DocumentSequencer.restore(state)
        self.consumer = LogConsumer(log.topic(raw_topic), offset)
        self.deltas = log.topic("deltas")
        m = get_registry()
        self._m_pump = m.histogram(
            "deli_pump_records",
            buckets=(1, 4, 16, 64, 256, 1024, 4096, 16384),
            impl="scalar",
        )
        self._m_nacks = m.counter("deli_nacks_total", impl="scalar")
        self._m_stage = m.histogram("op_stage_ms", stage="submit_to_stamp")

    def _doc(self, doc_id: str) -> DocumentSequencer:
        if doc_id not in self.sequencers:
            self.sequencers[doc_id] = DocumentSequencer(doc_id)
        return self.sequencers[doc_id]

    def pump(self, max_count: Optional[int] = None) -> int:
        """Drain up to `max_count` raw records (micro-batch cap: a deep
        backlog yields to the caller between pumps — supervisor
        heartbeats stay live, process_all loops until quiescent)."""
        cap = self.max_pump if max_count is None else max_count
        raws = self.consumer.poll(cap)
        out: List[dict] = []
        for raw in raws:
            self._handle(raw, out)
        if out:
            self.deltas.append_many(out)
        if raws:
            self._m_pump.observe(len(raws))
        return len(raws)

    def _handle(self, raw: dict, out: List[dict]) -> None:
        if not isinstance(raw, dict) or not raw.get("doc"):
            return  # journal LOST_RECORD placeholder / foreign junk
        doc = self._doc(raw["doc"])
        kind = raw["kind"]
        if kind == "join":
            msg = doc.join(raw["client"])
            msg.traces.append(("stamp", time.time()))
            out.append({"doc": raw["doc"], "kind": "op", "msg": msg})
        elif kind == "leave":
            msg = doc.leave(raw["client"])
            if msg is not None:
                msg.traces.append(("stamp", time.time()))
                out.append({"doc": raw["doc"], "kind": "op", "msg": msg})
        elif kind == "control":
            # Server-side control (summary ack/nack from scribe): stamp
            # bypassing client validation (deli's system-message path).
            msg = doc._stamp(
                client_id=SYSTEM_CLIENT,
                client_seq=0,
                ref_seq=doc.seq,
                type_=raw["type"],
                contents=raw["contents"],
            )
            msg.traces.append(("stamp", time.time()))
            out.append({"doc": raw["doc"], "kind": "op", "msg": msg})
        elif kind == "boxcar":
            # Boxcarred submission (services-core pendingBoxcar.ts):
            # one log record carrying several client ops, ticketed
            # back-to-back so the batch sequences atomically. A nack
            # aborts the REST of the boxcar — sequencing a partial
            # "atomic" batch would both break batch atomicity for
            # receivers and desync the sender's pending FIFO.
            for msg in raw["msgs"]:
                if not self._ticket(raw["doc"], doc, raw["client"], msg, out):
                    break
        else:  # client op
            self._ticket(raw["doc"], doc, raw["client"], raw["msg"], out)

    def _ticket(self, doc_id: str, doc: DocumentSequencer, client: int,
                msg: DocumentMessage, out: List[dict]) -> bool:
        res = doc.sequence(client, msg)
        if isinstance(res, NackMessage):
            self._m_nacks.inc()
            out.append(
                {"doc": doc_id, "kind": "nack", "client": client, "msg": res}
            )
            return False
        # Op-lifecycle trace: carry the client submit stamp forward and
        # add the deli stamp instant (ISequencedDocumentMessage.traces
        # role). Traces are in-memory observability only — excluded
        # from journal encoding and every digest/bit-identity form.
        now = time.time()
        sub = trace_submit_ts(msg.metadata)
        if sub is not None:
            res.traces.append(("submit", sub))
            self._m_stage.observe((now - sub) * 1000.0)
        res.traces.append(("stamp", now))
        out.append({"doc": doc_id, "kind": "op", "msg": res})
        return True

    def checkpoint(self) -> dict:
        """Resumable state (deli checkpointContext.ts → Mongo)."""
        return {
            "offset": self.consumer.checkpoint(),
            "docs": {d: s.checkpoint() for d, s in self.sequencers.items()},
        }


# --------------------------------------------------------------------------
# scriptorium
# --------------------------------------------------------------------------


class ScriptoriumLambda:
    """Writes sequenced ops to the durable per-doc op store."""

    def __init__(self, log: MessageLog, checkpoint: Optional[dict] = None):
        self.store: Dict[str, List[SequencedMessage]] = {}
        offset = 0
        if checkpoint:
            offset = checkpoint["offset"]
        self.consumer = LogConsumer(log.topic("deltas"), offset)
        self._m_stage = get_registry().histogram(
            "op_stage_ms", stage="stamp_to_durable"
        )
        if checkpoint is None:
            self.store = {}
        # On restore, replay the log from 0 to rebuild the store (the
        # reference restores from Mongo; our "Mongo" is rebuilt from
        # the log, which is equivalent because the log is durable).
        if checkpoint:
            for m in log.topic("deltas").read(0, offset):
                self._apply(m)

    def _apply(self, entry: dict) -> None:
        if entry["kind"] == "op":
            msg = entry["msg"]
            # Trace the durable-append instant once per message: a
            # restart replays history through _apply, and those
            # messages already carry their original "durable" stamp
            # (trace_stage_once's no-op path).
            if msg.traces:
                now = time.time()
                stamp = trace_stage_once(msg.traces, "durable", now)
                if stamp is not None:
                    self._m_stage.observe((now - stamp) * 1000.0)
            self.store.setdefault(entry["doc"], []).append(msg)

    def pump(self, max_count: Optional[int] = None) -> int:
        n = 0
        for entry in self.consumer.poll(max_count):
            self._apply(entry)
            n += 1
        return n

    def ops_from(self, doc_id: str, from_seq: int) -> List[SequencedMessage]:
        return [
            m for m in self.store.get(doc_id, [])
            if m.sequence_number > from_seq
        ]

    def checkpoint(self) -> dict:
        return {"offset": self.consumer.checkpoint()}


# --------------------------------------------------------------------------
# broadcaster
# --------------------------------------------------------------------------


class BroadcasterLambda:
    """Fans sequenced ops out to connected sockets per doc."""

    def __init__(self, log: MessageLog):
        self.consumer = LogConsumer(log.topic("deltas"))
        # doc -> list of (socket) where socket has deliver(msg)/nack(msg)
        self.rooms: Dict[str, List[Any]] = {}
        self._m_stage = get_registry().histogram(
            "op_stage_ms", stage="stamp_to_broadcast"
        )

    def join_room(self, doc_id: str, socket: Any) -> None:
        self.rooms.setdefault(doc_id, []).append(socket)

    def leave_room(self, doc_id: str, socket: Any) -> None:
        if socket in self.rooms.get(doc_id, []):
            self.rooms[doc_id].remove(socket)

    def pump(self, max_count: Optional[int] = None) -> int:
        n = 0
        failed = []
        pending: Dict[str, List[Any]] = {}

        def flush(doc: str) -> None:
            msgs = pending.pop(doc, None)
            if not msgs:
                return
            memo: Dict[str, Any] = {}
            for sock in list(self.rooms.get(doc, [])):
                self._deliver_safe(
                    doc, sock, "deliver_batch", (msgs, memo), failed
                )

        now = time.time()  # one clock read per pump, not per record
        for entry in self.consumer.poll(max_count):
            doc = entry["doc"]
            if entry["kind"] == "op":
                # Batch per doc per pump (broadcaster/lambda.ts:49's
                # per-tick batching); flushed before any nack so
                # per-client ordering holds.
                msg = entry["msg"]
                # Trace the broadcast instant once per message: a
                # restarted server's fresh broadcaster re-polls shared
                # log objects that already carry their original stamp
                # (trace_stage_once's no-op path).
                if msg.traces:
                    stamp = trace_stage_once(msg.traces, "broadcast", now)
                    if stamp is not None:
                        self._m_stage.observe((now - stamp) * 1000.0)
                pending.setdefault(doc, []).append(msg)
            elif entry["kind"] == "nack":
                flush(doc)
                for sock in list(self.rooms.get(doc, [])):
                    if sock.client_id == entry["client"]:
                        self._deliver_safe(doc, sock, "nack", entry["msg"], failed)
            n += 1
        for doc in list(pending):
            flush(doc)
        # Disconnect failures only AFTER the polled batch is fully
        # delivered: disconnect() pumps the pipeline re-entrantly
        # (leave sequencing), and doing that mid-batch would deliver
        # newer ops to healthy sockets before the rest of this batch —
        # out-of-order delivery.
        for sock in failed:
            try:
                sock.disconnect()
            except Exception:
                pass
        return n

    def _deliver_safe(self, doc: str, sock: Any, meth: str, msg: Any,
                      failed: list) -> None:
        """Per-socket error isolation: a dead/stalled transport (full
        TCP buffer, closed pipe) must neither starve the rest of the
        room nor surface an error to the submitter for an op that WAS
        sequenced. Evict the failing socket only; it reconnects and
        catches up from storage (alfred's room-eviction behavior,
        alfred/index.ts:211)."""
        try:
            if meth == "deliver_batch":
                sock.deliver_batch(*msg)
            else:
                getattr(sock, meth)(msg)
        except Exception as exc:
            # Loud eviction: an application error in a replica's
            # listener must stay visible, or divergence debugging
            # loses its stack trace. Transport failures (closed pipe,
            # full buffer) are the routine eviction case and log as
            # one line.
            if isinstance(exc, (ConnectionError, OSError, TimeoutError)):
                import sys

                print(
                    f"broadcaster: evicting socket on transport error "
                    f"({exc!r})", file=sys.stderr,
                )
            else:
                import traceback

                traceback.print_exc()
            self.leave_room(doc, sock)
            failed.append(sock)


# --------------------------------------------------------------------------
# scribe
# --------------------------------------------------------------------------


class ScribeLambda:
    """Protocol-state keeper + summary validator/acker."""

    def __init__(
        self,
        log: MessageLog,
        storage: ContentAddressedStore,
        checkpoint: Optional[dict] = None,
        raw_router: Optional[Callable[[List[dict]], None]] = None,
    ):
        """`raw_router` is the control-record sink (summary ack/nack
        back through deli): default is the single `rawdeltas` topic;
        the sharded LocalServer passes its partition router so each
        control lands in its doc's partition."""
        self.log = log
        self.storage = storage
        self.protocol: Dict[str, ProtocolOpHandler] = {}
        offset = 0
        if checkpoint:
            offset = checkpoint["offset"]
            for doc_id, snap in checkpoint["protocol"].items():
                self.protocol[doc_id] = ProtocolOpHandler.from_snapshot(snap)
        self.consumer = LogConsumer(log.topic("deltas"), offset)
        self._route_raw = raw_router or log.topic("rawdeltas").append_many

    def _doc(self, doc_id: str) -> ProtocolOpHandler:
        if doc_id not in self.protocol:
            self.protocol[doc_id] = ProtocolOpHandler()
        return self.protocol[doc_id]

    def pump(self, max_count: Optional[int] = None) -> int:
        n = 0
        controls: List[dict] = []
        for entry in self.consumer.poll(max_count):
            if entry["kind"] != "op":
                n += 1
                continue
            doc_id = entry["doc"]
            msg: SequencedMessage = entry["msg"]
            handler = self._doc(doc_id)
            handler.process_message(msg)
            if msg.type == MessageType.SUMMARIZE:
                self._handle_summarize(doc_id, msg, controls)
            n += 1
        if controls:
            # One flush per pump for the ack/nack control records
            # (same per-pump batching as the deli output path).
            self._route_raw(controls)
        return n

    def _handle_summarize(self, doc_id: str, msg: SequencedMessage,
                          controls: List[dict]) -> None:
        """Validate the client summary and ack/nack it through deli
        (scribe/lambda.ts:252-266)."""
        handle = (msg.contents or {}).get("handle")
        if handle and self.storage.contains(handle):
            self.storage.set_ref(doc_id, handle)
            controls.append(
                {
                    "doc": doc_id,
                    "kind": "control",
                    "type": MessageType.SUMMARY_ACK,
                    "contents": {
                        "handle": handle,
                        "summaryProposal": {"summarySequenceNumber": msg.sequence_number},
                    },
                }
            )
        else:
            controls.append(
                {
                    "doc": doc_id,
                    "kind": "control",
                    "type": MessageType.SUMMARY_NACK,
                    "contents": {
                        "message": f"unknown summary handle {handle!r}",
                        "summaryProposal": {"summarySequenceNumber": msg.sequence_number},
                    },
                }
            )

    def latest_summary(self, doc_id: str) -> Optional[str]:
        return self.storage.get_ref(doc_id)

    def checkpoint(self) -> dict:
        return {
            "offset": self.consumer.checkpoint(),
            "protocol": {d: h.snapshot() for d, h in self.protocol.items()},
        }


# --------------------------------------------------------------------------
# alfred + the assembled service
# --------------------------------------------------------------------------


class _Socket(BufferedListener):
    """One client's connection through alfred (the shape ContainerRuntime
    expects: submit/listener/nack_listener/client_id/catch_up/disconnect)."""

    def __init__(self, server: "LocalServer", doc_id: str, client_id: int):
        super().__init__()
        self.server = server
        self.doc_id = doc_id
        self.client_id = client_id
        self.nack_listener: Optional[Callable[[NackMessage], None]] = None
        # Transport "disconnect" event surfaced to the runtime
        # (connectionManager.ts:170); fires for both locally and
        # server/driver-initiated disconnects. Assigned by
        # ContainerRuntime.connect.
        self.disconnect_listener: Optional[Callable[[], None]] = None
        self.connected = True
        self.join_seq = 0
        # Optional batched delivery sink (the TCP front end sets it:
        # one pre-encoded frame per broadcaster pump instead of one
        # per op — the reference broadcaster's per-tick batching,
        # broadcaster/lambda.ts:49).
        self.batch_listener: Optional[Callable] = None

    # broadcaster side
    def deliver_batch(self, msgs: List[SequencedMessage],
                      memo: Optional[dict] = None) -> None:
        """Deliver a run of sequenced ops. Per-socket join/seq
        filtering still applies; sockets that accept the FULL batch
        share `memo` so the transport encodes the frame once per
        room."""
        if (self.connected and self.join_seq
                and msgs[0].sequence_number > self.join_seq):
            out = msgs  # steady state: the whole batch is deliverable
        else:
            out = []
            for m in msgs:
                if self._filter_own_join(m):
                    continue
                if (not self.connected or self.join_seq == 0
                        or m.sequence_number <= self.join_seq):
                    continue
                out.append(m)
            if not out:
                return
        if self.batch_listener is not None:
            self.batch_listener(
                out, memo if len(out) == len(msgs) else None
            )
        else:
            for m in out:
                self._dispatch(m)

    def _filter_own_join(self, msg: SequencedMessage) -> bool:
        if self.join_seq == 0 and msg.type == MessageType.CLIENT_JOIN:
            cid = msg.contents if not isinstance(msg.contents, dict) else msg.contents.get("clientId")
            if cid == self.client_id:
                self.join_seq = msg.sequence_number
                return True  # own join: surfaced via catch_up, not live
        return False

    def deliver(self, msg: SequencedMessage) -> None:
        if self._filter_own_join(msg):
            return
        if not self.connected or msg.sequence_number <= self.join_seq or self.join_seq == 0:
            return
        self._dispatch(msg)

    def nack(self, msg: NackMessage) -> None:
        if self.connected and self.nack_listener is not None:
            self.nack_listener(msg)

    # client side
    def submit(self, msg: DocumentMessage) -> None:
        if not self.connected:
            raise RuntimeError("socket closed")
        self.server.alfred_submit(self.doc_id, self.client_id, msg)

    def submit_batch(self, msgs: List[DocumentMessage]) -> None:
        """Boxcarred submit: the whole batch rides one ingress record
        and sequences atomically (pendingBoxcar.ts role)."""
        if not self.connected:
            raise RuntimeError("socket closed")
        self.server.alfred_submit_batch(self.doc_id, self.client_id, msgs)

    def catch_up(self, from_seq: int) -> List[SequencedMessage]:
        return [
            m
            for m in self.server.scriptorium.ops_from(self.doc_id, from_seq)
            if m.sequence_number <= self.join_seq
        ]

    def disconnect(self) -> None:
        if self.connected:
            self.connected = False
            self.server.alfred_disconnect(self)
            if self.disconnect_listener is not None:
                self.disconnect_listener()


class LocalServer:
    """The full pipeline in one object (the tinylicious/LocalOrderer
    role): production lambdas over in-proc topics, synchronous pump."""

    def __init__(
        self,
        storage: Optional[ContentAddressedStore] = None,
        deferred: bool = False,
        checkpoints: Optional[dict] = None,
        log: Optional[MessageLog] = None,
        persist_dir: Optional[str] = None,
        historian_budget: Optional[int] = None,
        deli_impl: Optional[str] = None,
        log_format: Optional[str] = None,
        n_partitions: int = 1,
        deli_devices: Optional[int] = None,
    ):
        """Restart contract: pass the previous instance's `log` (the
        durable substrate, as Kafka retains topics across lambda
        crashes), `storage`, and `checkpoints()`; every lambda resumes
        from its checkpointed offset/state.

        `persist_dir` makes the contract hold across PROCESS restarts
        (the gitrest+Kafka durability, SURVEY.md §2.5): blob store and
        topic journals live on disk there, lambda checkpoints write to
        <dir>/checkpoints.json after every pump, and a fresh
        LocalServer(persist_dir=same) resumes the documents.

        `deli_impl` picks the sequencer: "scalar" (default) or
        "kernel" (the vmap'd batch sequencer,
        `deli_kernel.KernelDeliLambda`); env ``FLUID_DELI`` sets the
        default. Checkpoints are interchangeable across impls, so a
        restart may switch.

        `log_format` picks the persisted journal wire form: "json"
        (JSONL lines) or "columnar" (binary record-batch frames,
        `protocol.record_batch`); env ``FLUID_LOG_FORMAT`` sets the
        default. Replay reads both, so a restart may switch formats
        over the same persist_dir mid-journal.

        `deli_devices` (kernel impl only) shards the kernel deli's
        `[D, C]` doc-slot pool across an N-device mesh
        (`server.deli_kernel` over `parallel.mesh` — one doc slab per
        device inside a single compiled sequencer call). Checkpoints
        stay in the `DocumentSequencer` shape, so scalar ⇄ kernel ⇄
        sharded restores interop; a restart may change N freely.

        `n_partitions` shards the ordering stage in-proc (the
        `server.shard_fabric` slicing, LocalOrderer-sized): ingress
        routes each doc to its consistent-hash partition topic
        (``rawdeltas-p{k}``, `queue.partition_of`), one deli per
        partition sequences it, and all partitions emit into the one
        deltas stream — per-doc total order is untouched because a doc
        lives in exactly one partition. Checkpoints key per partition
        (``deli-p{k}``), so a restart must keep `n_partitions` (change
        it only across a drained server)."""
        from .columnar_log import default_log_format

        self.log_format = default_log_format(log_format)
        self.persist_dir = persist_dir
        if persist_dir is not None:
            import os

            os.makedirs(persist_dir, exist_ok=True)
            if log is None:
                log = MessageLog(os.path.join(persist_dir, "topics"),
                                 log_format=self.log_format)
            if storage is None:
                storage = ContentAddressedStore(
                    directory=os.path.join(persist_dir, "store")
                )
            if checkpoints is None:
                cp_path = os.path.join(persist_dir, "checkpoints.json")
                if os.path.exists(cp_path):
                    with open(cp_path) as f:
                        checkpoints = json.load(f)
        self.log = log if log is not None else MessageLog()
        self.storage = storage if storage is not None else ContentAddressedStore()
        if historian_budget:
            # Caching tier in front of storage (the historian role,
            # server/historian): immutable blobs LRU-cache; the
            # durable store underneath stays authoritative. Pays off
            # over disk-backed/native stores; the pure in-memory store
            # is already a dict lookup. Never double-wrap on restart
            # (the restart contract passes the previous storage).
            from .historian import HistorianCache

            if not isinstance(self.storage, HistorianCache):
                global _historian_seq
                _historian_seq += 1
                # Distinct metrics label per server instance: several
                # LocalServers in one process (tests, benches) must
                # not clobber one another's historian gauges.
                self.storage = HistorianCache(
                    self.storage, blob_budget_bytes=historian_budget,
                    name=f"local{_historian_seq}",
                )
        cp = checkpoints or {}
        self.metrics = get_registry()
        self._m_ingress_nacks = self.metrics.counter("ingress_nacks_total")
        self._monitor = None
        import os as _os

        self.deli_impl = deli_impl or _os.environ.get("FLUID_DELI", "scalar")
        from .supervisor import DELI_IMPLS

        if self.deli_impl not in DELI_IMPLS:
            # Loud, like the supervisor: a typo'd impl silently running
            # the scalar path would invalidate benches/chaos runs.
            raise ValueError(
                f"deli_impl {self.deli_impl!r} not in {DELI_IMPLS}"
            )
        self.n_partitions = int(n_partitions)
        if self.n_partitions < 1:
            raise ValueError(f"n_partitions must be >= 1: {n_partitions}")
        self.deli_devices = (
            int(deli_devices) if deli_devices is not None else None
        )
        deli_kw = {}
        if self.deli_devices is not None and self.deli_devices > 1:
            if self.deli_impl != "kernel":
                # Loud: a scalar server silently ignoring the device
                # axis would invalidate any scaling claim made of it.
                raise ValueError(
                    f"deli_devices={self.deli_devices} needs "
                    f"deli_impl='kernel' (the scalar deli has no "
                    f"device axis); got {self.deli_impl!r}"
                )
            deli_kw["deli_devices"] = self.deli_devices
        if self.deli_impl == "kernel":
            from .deli_kernel import KernelDeliLambda as _deli_cls
        else:
            _deli_cls = DeliLambda
        if self.n_partitions == 1:
            self.delis = [_deli_cls(self.log, cp.get("deli"), **deli_kw)]
        else:
            self.delis = [
                _deli_cls(self.log,
                          cp.get(partition_suffix("deli", k)),
                          raw_topic=partition_suffix("rawdeltas", k),
                          **deli_kw)
                for k in range(self.n_partitions)
            ]
        # Back-compat alias: single-partition callers (and tests) keep
        # addressing "the" deli; partition 0 is as good a face as any.
        self.deli = self.delis[0]
        self.scriptorium = ScriptoriumLambda(self.log, cp.get("scriptorium"))
        self.broadcaster = BroadcasterLambda(self.log)
        if cp:
            # Fresh broadcaster on restart: no sockets exist yet, so
            # skip history (reconnecting sockets catch up via storage).
            self.broadcaster.consumer.offset = self.log.topic("deltas").head
        self.scribe = ScribeLambda(self.log, self.storage, cp.get("scribe"),
                                   raw_router=self._route_raw)
        self.deferred = deferred
        self._next_client: Dict[str, int] = {}
        if persist_dir is not None:
            # Never re-issue a client id from a previous life: replay
            # the journaled joins (stale ids would collide with the
            # dead clients' ops during catch-up).
            for name in self._raw_topic_names():
                for entry in self.log.topic(name).read(0):
                    if isinstance(entry, dict) and entry.get("kind") == "join":
                        doc = entry["doc"]
                        self._next_client[doc] = max(
                            self._next_client.get(doc, 1), entry["client"] + 1
                        )
        # Broadcaster must lag scriptorium so catch_up is complete by
        # the time a live op arrives; pump order below guarantees it.

    # ----------------------------------------------------- shard routing

    def _raw_topic_names(self) -> List[str]:
        if self.n_partitions == 1:
            return ["rawdeltas"]
        return [partition_suffix("rawdeltas", k)
                for k in range(self.n_partitions)]

    def _raw_topic(self, doc_id: str):
        """The ingress topic `doc_id`'s records belong to (the
        `ShardRouter` rule, in-proc)."""
        if self.n_partitions == 1:
            return self.log.topic("rawdeltas")
        return self.log.topic(partition_suffix(
            "rawdeltas", partition_of(doc_id, self.n_partitions)
        ))

    def _route_raw(self, records: List[dict]) -> None:
        """Batch-append raw records to their partitions (scribe's
        control sink; order preserved within each partition)."""
        if self.n_partitions == 1:
            self.log.topic("rawdeltas").append_many(records)
            return
        for p, recs in split_by_partition(records,
                                          self.n_partitions).items():
            self.log.topic(
                partition_suffix("rawdeltas", p)
            ).append_many(recs)

    # ---------------------------------------------------- observability

    def serve_metrics(self, host: str = "127.0.0.1", port: int = 0):
        """Start the live ops endpoint (`/metrics` Prometheus text,
        `/metrics.json` snapshot, `/healthz`) over this process's
        registry; returns the `monitor.MetricsServer` (its `.url` has
        the bound port). Idempotent per server instance."""
        if self._monitor is None:
            from .monitor import MetricsServer

            self._monitor = MetricsServer(
                registry=self.metrics,
                health=lambda: {
                    "status": "ok",
                    "deli_impl": self.deli_impl,
                    "docs": len(self.scriptorium.store),
                },
                host=host, port=port,
            ).start()
        return self._monitor

    def stop_metrics(self) -> None:
        if self._monitor is not None:
            self._monitor.stop()
            self._monitor = None

    # ------------------------------------------------------------- pump

    def process_all(self, doc_id: Optional[str] = None) -> int:
        """Drain the whole pipeline to quiescence."""
        n = 0
        while True:
            moved = sum(d.pump() for d in self.delis)
            moved += self.scriptorium.pump()
            moved += self.scribe.pump()
            moved += self.broadcaster.pump()
            if moved == 0:
                if n and self.persist_dir is not None:
                    self._persist_checkpoints()
                return n
            n += moved

    def _persist_checkpoints(self) -> None:
        import os

        # Durability order: the journals the checkpoint offsets refer
        # to must reach disk BEFORE the checkpoint that cites them —
        # else a crash replays a log with holes.
        self.log.sync()
        path = os.path.join(self.persist_dir, "checkpoints.json")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.checkpoints(), f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _auto_pump(self) -> None:
        if not self.deferred:
            self.process_all()

    # ----------------------------------------------------------- alfred

    def connect(self, doc_id: str, client_id: Optional[int] = None) -> _Socket:
        """The connect_document handshake (alfred/index.ts:595)."""
        if client_id is None:
            client_id = self._next_client.get(doc_id, 1)
        self._next_client[doc_id] = max(self._next_client.get(doc_id, 1), client_id + 1)
        if any(
            s.client_id == client_id and s.connected
            for s in self.broadcaster.rooms.get(doc_id, [])
        ):
            raise ValueError(f"client {client_id} already connected to {doc_id}")
        sock = _Socket(self, doc_id, client_id)
        self.broadcaster.join_room(doc_id, sock)
        self._raw_topic(doc_id).append(
            {"doc": doc_id, "kind": "join", "client": client_id}
        )
        # The join must be sequenced before the socket is usable (the
        # reference handshake awaits the join roundtrip).
        self.process_all()
        assert sock.join_seq > 0
        return sock

    def alfred_submit(self, doc_id: str, client_id: int, msg: DocumentMessage) -> None:
        # Ingress validation (alfred/index.ts:228): size cap nack.
        try:
            size = len(json.dumps(msg.contents, default=str))
        except Exception:
            size = 0
        if size > MAX_OP_BYTES:
            self._m_ingress_nacks.inc()
            self.log.topic("deltas").append(
                {
                    "doc": doc_id,
                    "kind": "nack",
                    "client": client_id,
                    "msg": NackMessage(client_id, msg.client_seq, 413, "op too large"),
                }
            )
        else:
            self._raw_topic(doc_id).append(
                {"doc": doc_id, "kind": "op", "client": client_id, "msg": msg}
            )
        self._auto_pump()

    def alfred_submit_batch(
        self, doc_id: str, client_id: int, msgs: List[DocumentMessage]
    ) -> None:
        """Boxcarred ingress: size-validate each op, then append ONE
        rawdeltas record for the whole batch (pendingBoxcar.ts)."""
        for msg in msgs:
            try:
                size = len(json.dumps(msg.contents, default=str))
            except Exception:
                size = 0
            if size > MAX_OP_BYTES:
                self._m_ingress_nacks.inc()
                self.log.topic("deltas").append(
                    {
                        "doc": doc_id,
                        "kind": "nack",
                        "client": client_id,
                        "msg": NackMessage(
                            client_id, msg.client_seq, 413, "op too large"
                        ),
                    }
                )
                self._auto_pump()
                return
        self._raw_topic(doc_id).append(
            {"doc": doc_id, "kind": "boxcar", "client": client_id,
             "msgs": list(msgs)}
        )
        self._auto_pump()

    def alfred_disconnect(self, sock: _Socket) -> None:
        self.broadcaster.leave_room(sock.doc_id, sock)
        self._raw_topic(sock.doc_id).append(
            {"doc": sock.doc_id, "kind": "leave", "client": sock.client_id}
        )
        self._auto_pump()

    # ------------------------------------------------------- storage API

    def ops_from(self, doc_id: str, from_seq: int,
                 to_seq: Optional[int] = None) -> List[SequencedMessage]:
        ops = self.scriptorium.ops_from(doc_id, from_seq)
        if to_seq is not None:
            ops = [m for m in ops if m.sequence_number <= to_seq]
        return ops

    @staticmethod
    def summary_base_seq(wire: Optional[str]) -> int:
        """The sequence number a runtime summary wire covers (0 when
        none / not a runtime summary) — where a catch-up tail starts.
        Reads the same ``.metadata`` blob `ContainerRuntime.load`
        boots from."""
        if wire is None:
            return 0
        from ..runtime.summary import SummaryTree

        try:
            meta = json.loads(
                SummaryTree.from_json(wire).get_blob(".metadata")
            )
            return int(meta.get("sequenceNumber", 0))
        except (KeyError, ValueError, TypeError, AssertionError):
            return 0

    def catchup(self, doc_id: str, from_seq: int = 0) -> dict:
        """Answer a cold join with **nearest summary + op tail** instead
        of the full log (the summary service's read shape, SURVEY §3.4
        joins): the newest summary wire (None when the doc has none),
        the sequence number it covers, and only the ops past
        ``max(from_seq, summary seq)`` — a million-op doc costs its
        summary plus the collab-window tail, not its history."""
        wire = self.download_summary(doc_id)
        base = self.summary_base_seq(wire)
        return {
            "summary": wire,
            "summarySeq": base,
            "ops": self.ops_from(doc_id, max(from_seq, base)),
        }

    def upload_summary(self, wire: str) -> str:
        """Client summary upload (the storage.uploadSummaryWithContext
        role): returns the handle to cite in the summarize op.

        Summaries are stored SHREDDED (the gitrest tree-structure /
        shreddedSummaryDocumentStorageService role): every blob leaf
        becomes its own content-addressed object and the manifest
        references them by hash. Content addressing dedups across
        summaries automatically, so an incremental summary (one dirty
        channel re-serialized) stores only that channel's new blob +
        a small manifest — unchanged channels are not rewritten."""
        shredded = self._shred(json.loads(wire))
        return self.storage.put(
            json.dumps({"shredded": 1, "tree": shredded}).encode()
        )

    def _shred(self, node: Any) -> Any:
        if isinstance(node, dict) and node.get("type") == "blob":
            raw = json.dumps(node, sort_keys=True).encode()
            return {"type": "blobref", "key": self.storage.put(raw)}
        if isinstance(node, dict):
            return {k: self._shred(v) for k, v in node.items()}
        return node

    def _unshred(self, node: Any) -> Any:
        if isinstance(node, dict) and node.get("type") == "blobref":
            return json.loads(self.storage.get(node["key"]).decode())
        if isinstance(node, dict):
            return {k: self._unshred(v) for k, v in node.items()}
        return node

    def download_summary(self, doc_id: str) -> Optional[str]:
        key = self.storage.get_ref(doc_id)
        if key is None:
            return None
        data = json.loads(self.storage.get(key).decode())
        if isinstance(data, dict) and data.get("shredded"):
            return json.dumps(self._unshred(data["tree"]))
        return json.dumps(data)

    # -------------------------------------------------------- lifecycle

    def checkpoints(self) -> dict:
        """All lambdas' resumable state (crash/restart contract,
        SURVEY.md §5 failure detection)."""
        cp: Dict[str, Any] = {
            "scriptorium": self.scriptorium.checkpoint(),
            "scribe": self.scribe.checkpoint(),
        }
        if self.n_partitions == 1:
            cp["deli"] = self.deli.checkpoint()
        else:
            for k, d in enumerate(self.delis):
                cp[partition_suffix("deli", k)] = d.checkpoint()
        return cp
