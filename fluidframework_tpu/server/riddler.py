"""Tenant and token management: the riddler role.

Mirrors the reference's tenant manager + alfred token validation
(server/routerlicious/packages/routerlicious-base/src/riddler/
tenantManager.ts; token check at lambdas/src/alfred/index.ts:595):
every tenant owns a shared signing key; clients present a signed
token scoped to (tenant, document, scopes, expiry); the front door
validates before any connect/submit/storage access.

Tokens are compact HMAC-SHA256 JWTs (header.payload.signature,
base64url) — the reference signs with jsonwebtoken/HS256; this is the
same construction from the standard library.
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import hmac
import json
import secrets
import time
from typing import Dict, List, Optional

SCOPE_READ = "doc:read"
SCOPE_WRITE = "doc:write"

# Command -> required scope at the socket front door. Anything not
# listed requires a valid token with any scope.
WRITE_CMDS = {"create_document", "upload_blob", "submit", "submit_batch",
              "connect"}
READ_CMDS = {"load_document", "ops_from", "read_blob", "catch_up"}


class AuthError(Exception):
    """Token/tenant validation failure (alfred nacks these)."""


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(data: str) -> bytes:
    pad = "=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(data + pad)


def sign_token(
    key: str,
    tenant_id: str,
    document_id: str,
    scopes: List[str],
    user: Optional[dict] = None,
    lifetime_s: float = 3600.0,
    now: Optional[float] = None,
) -> str:
    """HS256 JWT for (tenant, document) — the reference's
    generateToken (services-utils) shape."""
    now = time.time() if now is None else now
    header = {"alg": "HS256", "typ": "JWT"}
    payload = {
        "tenantId": tenant_id,
        "documentId": document_id,
        "scopes": list(scopes),
        "user": user or {"id": "anonymous"},
        "iat": int(now),
        "exp": int(now + lifetime_s),
    }
    signing = (
        _b64(json.dumps(header, sort_keys=True).encode())
        + "."
        + _b64(json.dumps(payload, sort_keys=True).encode())
    )
    sig = hmac.new(key.encode(), signing.encode(), hashlib.sha256).digest()
    return signing + "." + _b64(sig)


class TenantManager:
    """Tenant registry + token validation (riddler/tenantManager.ts)."""

    def __init__(self):
        self._tenants: Dict[str, str] = {}

    def create_tenant(self, tenant_id: str, key: Optional[str] = None) -> str:
        """Register a tenant; returns its signing key."""
        if tenant_id in self._tenants:
            raise ValueError(f"tenant {tenant_id!r} exists")
        key = key or secrets.token_hex(16)
        self._tenants[tenant_id] = key
        return key

    def get_key(self, tenant_id: str) -> str:
        try:
            return self._tenants[tenant_id]
        except KeyError:
            raise AuthError(f"unknown tenant {tenant_id!r}") from None

    def has_tenant(self, tenant_id: str) -> bool:
        return tenant_id in self._tenants

    def validate_token(
        self,
        token: str,
        tenant_id: str,
        document_id: Optional[str] = None,
        now: Optional[float] = None,
    ) -> dict:
        """Verify signature, tenant binding, document binding, and
        expiry; returns the claims (alfred/index.ts:595 +
        verifyToken, services-utils/src/auth.ts)."""
        key = self.get_key(tenant_id)
        parts = token.split(".")
        if len(parts) != 3:
            raise AuthError("malformed token")
        signing = parts[0] + "." + parts[1]
        want = hmac.new(
            key.encode(), signing.encode(), hashlib.sha256
        ).digest()
        try:
            got = _unb64(parts[2])
        except (ValueError, binascii.Error):
            # Malformed base64 in the signature segment is an auth
            # failure, not an internal error — callers catch AuthError
            # (the documented auth-nack contract).
            raise AuthError("malformed token") from None
        if not hmac.compare_digest(want, got):
            raise AuthError("bad token signature")
        try:
            claims = json.loads(_unb64(parts[1]))
        except (ValueError, binascii.Error):
            raise AuthError("malformed token payload") from None
        if not isinstance(claims, dict):
            # A signed-but-malformed payload (non-object JSON) is
            # still an auth failure, not an internal error.
            raise AuthError("malformed token payload")
        if claims.get("tenantId") != tenant_id:
            raise AuthError("token tenant mismatch")
        if document_id is not None and claims.get("documentId") != document_id:
            raise AuthError("token document mismatch")
        now = time.time() if now is None else now
        try:
            exp = float(claims.get("exp", 0))
        except (TypeError, ValueError):
            raise AuthError("malformed token expiry") from None
        if now >= exp:
            raise AuthError("token expired")
        return claims

    def authorize_command(
        self,
        cmd: str,
        token: Optional[str],
        tenant_id: Optional[str],
        document_id: Optional[str],
    ) -> dict:
        """Front-door gate for one socket command: validates the token
        and checks its scopes cover the command's access class."""
        if not token or not tenant_id:
            raise AuthError("missing tenant credentials")
        claims = self.validate_token(token, tenant_id, document_id)
        scopes = set(claims.get("scopes") or ())
        if cmd in WRITE_CMDS and SCOPE_WRITE not in scopes:
            raise AuthError(f"scope {SCOPE_WRITE} required for {cmd}")
        if cmd in READ_CMDS and not scopes & {SCOPE_READ, SCOPE_WRITE}:
            raise AuthError(f"scope {SCOPE_READ} required for {cmd}")
        return claims
