"""Batched TPU deli: the vmap'd sequencer kernel wired into the LIVE
ordering pipeline.

The scalar deli (`lambdas.DeliLambda` in-proc, `supervisor.DeliRole`
in the supervised farm) tickets one raw record at a time through a
per-document `DocumentSequencer`. This module re-expresses that hot
loop the way BASELINE config 5 demands (10k docs x 64 clients batched
per kernel call): a pump drains the raw topic in micro-batches, maps
string doc-ids to dense document slots, packs the submissions into
columnar `SeqBatch` arrays, runs the vmap'd
`ops.sequencer_kernel.sequence_batch` over the document axis on
device, and scatters the stamped messages / nacks back out via ONE
`append_many` per pump.

Division of labor (the correctness spine):

- **Decisions on device** — stamp/nack/skip verdicts (including boxcar
  aborts and resubmission dedup) come from the kernel, bit-identical
  to the scalar oracle by the differential gates
  (tests/test_sequencer_kernel.py, tests/test_deli_kernel.py).
- **Bookkeeping from results** — the host keeps a per-doc mirror
  (head seq, MSN, connected clients' ref/client seqs) updated ONLY
  from kernel verdicts, never by re-deriving decisions. The mirror
  makes checkpoints pure host work (no [D, C] device pulls) in the
  SAME format as `DocumentSequencer.checkpoint()`, so scalar and
  kernel delis restore each other's checkpoints — the scalar path is
  both the oracle and the fallback.

Doc slots grow by doubling and evict for free: parking a document just
frees its slot (the mirror is authoritative for parked docs); touching
it again scatters the state row back in before the next kernel call.

Two frontends wrap the shared `PackedDeliCore`:

- `KernelDeliLambda` — drop-in for the in-proc `DeliLambda`
  (`LocalServer(deli_impl="kernel")` or env `FLUID_DELI=kernel`):
  same deltas entries (`SequencedMessage`/`NackMessage`), same
  checkpoint shape, boxcar atomicity and the system-message control
  path included.
- `KernelDeliRole` — drop-in for the supervised farm's `DeliRole`
  (`--impl kernel`): same wire records with per-record `inOff`, so PR
  1's fenced exactly-once recovery (scan the output topic for the
  durable prefix, silently replay the gap) composes unchanged — a
  supervisor restart mid-batch must not re-stamp, and the chaos
  harness proves it converges bit-identical to the scalar golden.

This module imports jax at import time by design; the scalar paths
(`lambdas`, `supervisor`) import it lazily so scalar farms never pay
the cost.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..ops import sequencer_kernel as _sk
from ..ops.sequencer_kernel import (
    NO_GROUP,
    SUB_JOIN,
    SUB_LEAVE,
    SUB_OP,
    SUB_SYSTEM,
)
from ..protocol import record_batch as _rb
from ..protocol.messages import (
    MessageType,
    NackMessage,
    SequencedMessage,
    trace_submit_ts,
)
from ..utils.metrics import get_registry
from .log import LogConsumer, MessageLog
from .sequencer import (
    NACK_FUTURE_REFSEQ,
    NACK_STALE_REFSEQ,
    NACK_UNKNOWN_CLIENT,
    future_refseq_reason,
    out_of_order_reason,
    stale_refseq_reason,
)
from .supervisor import _Role

__all__ = [
    "KernelDeliLambda",
    "KernelDeliRole",
    "PackedDeliCore",
    "SeqPool",
]

SYSTEM_CLIENT = -1  # mirrors lambdas.SYSTEM_CLIENT (import would cycle)


def _pow2(n: int, lo: int = 8) -> int:
    p = lo
    while p < n:
        p <<= 1
    return p


def _mul_of(n: int, m: int) -> int:
    """n rounded UP to a multiple of m (the doc-axis shard constraint:
    every device owns the same number of slot rows)."""
    return n if m <= 1 else ((n + m - 1) // m) * m


def mesh_for_devices(deli_devices: Optional[int]):
    """The device mesh a `deli_devices=N` seam resolves to: None for
    the single-device pool (N absent / 1), else the process-wide
    shared 1-D docs mesh over N devices (falling back to forced-host
    virtual CPU devices exactly as `parallel.mesh.make_docs_mesh`
    does, and raising loudly when N devices simply do not exist)."""
    if deli_devices is None or int(deli_devices) <= 1:
        return None
    from ..parallel.mesh import shared_docs_mesh

    return shared_docs_mesh(int(deli_devices))


def mesh_for_plane(device_plane, plane_column: Optional[int] = None,
                   partition_key=None, env: bool = False):
    """The sequencer's TYPED SLICE of a 2-D device plane
    (`parallel.device_plane.DevicePlane`): a 1-D docs mesh over one
    model column — one partition = one worker = one mesh slice. The
    column is explicit (`plane_column`), derived from the partition
    key (stable hash), or 0; `env=True` lets farm children inherit
    the supervisor's plane from ``FLUID_DEVICE_PLANE`` with no argv
    plumbing. Returns None when no plane is configured."""
    from ..parallel.device_plane import plane_column_of, resolve_plane

    plane = resolve_plane(device_plane, env=env)
    if plane is None:
        return None
    if plane_column is None:
        plane_column = (plane_column_of(partition_key, plane.model)
                        if partition_key is not None else 0)
    return plane.seq_mesh(plane_column)


def _nack_reason(code: int, ref: int, msn: int, head: int, cseq: int,
                 expected: Optional[int]) -> str:
    """The scalar sequencer's nack wording (shared helpers in
    server/sequencer.py), reconstructed from the kernel verdict + host
    mirror (codes are the contract; text is for humans)."""
    if code == NACK_UNKNOWN_CLIENT:
        return "unknown client"
    if code == NACK_STALE_REFSEQ:
        return stale_refseq_reason(ref, msn)
    if code == NACK_FUTURE_REFSEQ:
        return future_refseq_reason(ref, head)
    if expected is not None:
        return out_of_order_reason(cseq, expected)
    return f"clientSeq {cseq} out of order"


class SeqPool:
    """Dense [D, C] kernel-state pool with doc-slot grow/evict and
    scalar-format checkpoints.

    The device state is authoritative for VERDICTS; `docs` is the host
    mirror (seq head, MSN, per-client ref/client seqs) maintained from
    verdicts, authoritative for CHECKPOINTS and for parked (evicted)
    documents. Slots are recycled: parking costs nothing (the row is
    overwritten on the next load), touching a parked doc queues a row
    scatter that flushes in one batched write before the next run.
    """

    def __init__(self, n_docs: int = 8, n_clients: int = 8,
                 max_resident: Optional[int] = None, mesh=None):
        """`mesh` (a 1-D `jax.sharding.Mesh` over a ``docs`` axis, see
        `parallel.mesh.make_docs_mesh`/`shared_docs_mesh`) shards the
        `[D, C]` pool across its devices: `n_docs` is kept a multiple
        of ``mesh.size`` (every device owns an equal slab of slot
        rows), the kernel call is the shard_map'd
        `ops.sequencer_kernel.sharded_sequence_fn`, and verdicts
        gather once per chunk. The host mirror, slot allocation,
        grow/evict/park, and checkpoint format are IDENTICAL to the
        single-device pool — sharding only changes where slot rows
        live."""
        self.mesh = mesh
        self._n_shards = int(mesh.size) if mesh is not None else 1
        self.n_docs = _mul_of(max(1, n_docs), self._n_shards)
        self.n_clients = _pow2(max(2, n_clients), lo=2)
        self.state = _sk.make_state(self.n_docs, self.n_clients)
        self._placed = False  # host-side state edits re-place lazily
        # Logical slot -> physical state row. Identity until a PLACED
        # grow: doubling a sharded pool in place keeps every existing
        # row on its shard (each device pads its own slab locally — no
        # host round-trip, no cross-device traffic), which renumbers
        # the row space per shard; the mirror/free-list keep stable
        # LOGICAL slots and this map translates at the kernel
        # boundary (pack + row scatter).
        self._phys = np.arange(self.n_docs, dtype=np.int64)
        self.max_resident = max_resident
        # doc_id -> {"slot": int|None, "seq", "min_seq",
        #            "clients": {cid: [ref_seq, client_seq]}, "t": lru}
        self.docs: Dict[str, dict] = {}
        self.slot_owner: Dict[int, str] = {}
        self.free: List[int] = list(range(self.n_docs - 1, -1, -1))
        self._loads: List[Tuple[int, dict]] = []
        self._need_clients = self.n_clients
        self._clock = 0
        self._active: set = set()
        # Pool instrumentation (per-event counters here; occupancy
        # gauges are refreshed once per kernel pump by the core).
        m = get_registry()
        self._m_grows = m.counter("deli_pool_grows_total")
        self._m_evicts = m.counter("deli_pool_evictions_total")
        # ROADMAP (e)/(c) observability: which policy picked each
        # eviction victim, how cold the resident set looked at decision
        # time, and how many client columns compaction reclaimed.
        self._m_evict_policy = {
            p: m.counter("deli_pool_evictions_by_policy_total", policy=p)
            for p in ("msn_cold", "lru")
        }
        self._m_cold = m.gauge("deli_pool_cold_resident_docs")
        self._m_reclaims = m.counter("deli_pool_col_reclaims_total")
        self._m_compactions = m.counter("deli_pool_compactions_total")

    # ------------------------------------------------------------ slots

    def begin(self) -> None:
        self._active.clear()

    def touch(self, doc_id: str) -> dict:
        """Resident host-mirror entry for `doc_id` (its `"slot"` is the
        kernel row; `"cmap"` maps client ids to dense columns —
        column 0 is the never-connected SCRATCH column that ops from
        unknown/foreign client ids address, so any id — negative,
        huge — gets the oracle's unknown-client verdict without
        aliasing a real client's state)."""
        h = self.docs.get(doc_id)
        if h is None:
            h = {"slot": None, "seq": 0, "min_seq": 0, "clients": {},
                 "cmap": {}, "t": 0}
            self.docs[doc_id] = h
        elif len(h["cmap"]) > 2 * len(h["clients"]) + 8:
            # Live compaction trigger (ROADMAP (c)): a high-churn doc
            # whose column map has outgrown its live clients reclaims
            # departed clients' columns. Safe here — touch() runs once
            # per doc per pump, BEFORE any of this pump's submissions
            # read the map.
            self.compact_doc(doc_id)
        if h["slot"] is None:
            slot = self._alloc()
            h["slot"] = slot
            self.slot_owner[slot] = doc_id
            self._loads.append((slot, h))
        self._clock += 1
        h["t"] = self._clock
        self._active.add(doc_id)
        return h

    def col_of_join(self, h: dict, cid) -> int:
        """The client's dense column, assigned on first join (columns
        are per-doc-monotone, like the scalar per-doc client dict)."""
        cmap = h["cmap"]
        col = cmap.get(cid)
        if col is None:
            col = cmap[cid] = len(cmap) + 1  # col 0 is scratch
        return col

    def _alloc(self) -> int:
        # Soft resident budget: once resident docs reach max_resident,
        # every new residency first tries to park the coldest doc not
        # touched this pump and reuse its slot — the cap holds except
        # when a single pump's active set exceeds it (actives can't be
        # parked; the pool then grows to cover the pump).
        if (self.max_resident is not None
                and len(self.slot_owner) >= self.max_resident):
            # Victim pick is hot/cold by MSN progress (ROADMAP (e)):
            # a doc whose MSN has caught its head seq is quiescent —
            # every connected client acked everything (or none remain)
            # — and is evicted ahead of any still-lagging doc; LRU by
            # pump breaks ties and is the fallback when nothing is
            # cold. The mirror already tracks both numbers, so the
            # scan costs nothing extra.
            victim = None
            victim_key = None
            cold_resident = 0
            for doc_id, h in self.docs.items():
                if h["slot"] is None:
                    continue
                cold = h["min_seq"] >= h["seq"]
                if cold:
                    cold_resident += 1
                if doc_id in self._active:
                    continue
                key = (not cold, h["t"])
                if victim_key is None or key < victim_key:
                    victim, victim_key = doc_id, key
            self._m_cold.set(cold_resident)
            if victim is not None:
                self.park(
                    victim,
                    policy="lru" if victim_key[0] else "msn_cold",
                )
        if not self.free:
            old = self.n_docs
            self.n_docs = _mul_of(max(8, old * 2), self._n_shards)
            self.free.extend(range(self.n_docs - 1, old - 1, -1))
            self._m_grows.inc()
        return self.free.pop()

    def park(self, doc_id: str, policy: Optional[str] = None) -> None:
        """Evict a document's slot. Free: the host mirror is already
        complete, so the stale device row is simply abandoned until the
        slot's next occupant scatters over it. `policy` records which
        rule picked the victim (msn_cold / lru) for the pool gauges."""
        h = self.docs[doc_id]
        slot = h["slot"]
        if slot is None:
            return
        h["slot"] = None
        self.slot_owner.pop(slot, None)
        self.free.append(slot)
        if self._loads:
            # Drop any queued reload for the freed slot: the slot's
            # NEXT occupant queues its own load, and a stale one would
            # race it in the batched scatter (duplicate indices with
            # unspecified update order — the evicted doc's state could
            # overwrite the new occupant's row).
            self._loads = [(s, hh) for s, hh in self._loads if s != slot]
        self._m_evicts.inc()
        if policy is not None:
            self._m_evict_policy[policy].inc()

    # ------------------------------------------------- column compaction

    def compact_doc(self, doc_id: str) -> int:
        """Reclaim departed clients' columns in this doc's client-id →
        dense-column map (ROADMAP (c)): the map is rebuilt over LIVE
        clients only (relative column order preserved, so the rebuild
        is deterministic), and a resident doc queues a full row reload
        so the device row matches the new layout before the next
        kernel call. Returns the number of columns reclaimed."""
        h = self.docs.get(doc_id)
        if h is None:
            return 0
        cmap = h["cmap"]
        live = h["clients"]
        reclaimed = len(cmap) - len(live)
        if reclaimed <= 0:
            return 0
        h["cmap"] = {
            cid: i + 1  # col 0 stays the never-connected scratch column
            for i, cid in enumerate(sorted(live, key=cmap.__getitem__))
        }
        if h["slot"] is not None:
            self._loads.append((h["slot"], h))
        self._m_reclaims.inc(reclaimed)
        self._m_compactions.inc()
        return reclaimed

    def compact_all(self) -> int:
        """Checkpoint-time sweep: compact every doc's column map (the
        restart-free form of the checkpoint/restore compaction)."""
        return sum(self.compact_doc(d) for d in list(self.docs))

    def resident_docs(self) -> int:
        return len(self.slot_owner)

    def note_client(self, client_id: int) -> None:
        if client_id >= self._need_clients:
            self._need_clients = client_id + 1

    # -------------------------------------------------------- device ops

    def _place(self, state):
        """Lay every per-doc array out across the mesh (leading docs
        axis sharded, everything else replicated per row)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        sh = NamedSharding(self.mesh, PartitionSpec("docs"))
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, sh), state
        )

    def _grow_placed(self, old_d: int, old_c: int, new_c: int) -> bool:
        """Grow an ALREADY-PLACED sharded pool in place (the deferred
        GROW scatter — PR 6 follow-up closed): each device pads ITS
        OWN slab with fresh empty rows/columns (`jnp.pad` on the
        shard's committed buffer runs device-local), and the assembled
        array reuses those buffers — no host round-trip, no
        cross-device transfer, no full-pool re-place. The row space
        renumbers per shard (shard s owns rows [s*r1, (s+1)*r1) after
        the grow), so `_phys` remaps every logical slot to its new
        physical row ON ITS OLD SHARD — untouched rows never move.
        Returns False when the layout can't do it (not placed, shards
        not addressable) and the caller falls back to the classic
        grow_state + full re-place."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        S = self._n_shards
        new_d = self.n_docs
        r0, r1 = old_d // S, new_d // S
        sh = NamedSharding(self.mesh, PartitionSpec("docs"))
        new_fields = {}
        for name in self.state._fields:
            arr = getattr(self.state, name)
            try:
                shards = list(arr.addressable_shards)
            except AttributeError:
                return False  # host array: not actually placed
            if len(shards) != S:
                return False
            parts: List[Any] = [None] * S
            for s in shards:
                row0 = (s.index[0].start or 0) if s.index else 0
                widths = [(0, r1 - r0)]
                if arr.ndim > 1:
                    widths.append((0, new_c - old_c))
                parts[row0 // r0] = jnp.pad(s.data, widths)
            if any(p is None for p in parts):
                return False
            new_fields[name] = jax.make_array_from_single_device_arrays(
                (new_d,) + arr.shape[1:] if arr.ndim == 1
                else (new_d, new_c) + arr.shape[2:], sh, parts
            )
        # Remap: logical slot l at old physical row p (shard p//r0,
        # local p%r0) keeps its shard at row (p//r0)*r1 + p%r0; the
        # NEW logical ids [old_d, new_d) fill each shard's fresh
        # locals [r0, r1).
        phys = self._phys[:old_d]
        new_phys = np.empty(new_d, np.int64)
        new_phys[:old_d] = (phys // r0) * r1 + (phys % r0)
        grow_per = r1 - r0
        for s in range(S):
            base_l = old_d + s * grow_per
            new_phys[base_l: base_l + grow_per] = np.arange(
                s * r1 + r0, s * r1 + r1
            )
        self._phys = new_phys
        self.state = _sk.SequencerState(**new_fields)
        return True

    def _scatter_rows_placed(self, idx, updates) -> bool:
        """Scoped re-place (PR-6 follow-up (b)): scatter the loaded
        rows into an ALREADY-PLACED pool per shard, rebuilding only
        the device slabs that own a touched row and reusing every
        other shard's buffer as-is (`make_array_from_single_device_
        arrays` keeps untouched buffers by identity — nothing is
        re-transferred). Returns False when the layout doesn't allow
        it (not placed yet, or a shard's rows aren't host-addressable)
        and the caller falls back to the full `_place`."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        rows = self.n_docs // self._n_shards
        by_shard: Dict[int, List[int]] = {}
        for i, slot in enumerate(idx):
            by_shard.setdefault(int(slot) // rows, []).append(i)
        sh = NamedSharding(self.mesh, PartitionSpec("docs"))
        new_fields = {}
        for name, vals in updates.items():
            arr = getattr(self.state, name)
            try:
                shards = list(arr.addressable_shards)
            except AttributeError:
                return False  # host array: not placed yet
            if len(shards) != self._n_shards:
                return False  # partial addressability: full re-place
            parts = []
            for s in shards:
                row0 = (s.index[0].start or 0) if s.index else 0
                sel = by_shard.get(row0 // rows)
                if not sel:
                    parts.append(s.data)  # reused by identity
                    continue
                local = np.array(s.data)  # pull ONE shard, not the pool
                for i in sel:
                    local[int(idx[i]) - row0] = vals[i]
                parts.append(jax.device_put(local, s.device))
            new_fields[name] = jax.make_array_from_single_device_arrays(
                arr.shape, sh, parts
            )
        self.state = self.state._replace(**new_fields)
        return True

    def prepare(self) -> None:
        """Grow the packed state to the logical (D, C), flush queued
        doc-row loads in one batched scatter, and (sharded pools)
        place the result across the mesh — the kernel's in/out specs
        then keep it sharded between pumps for free. An already-placed
        pool takes the SCOPED scatter path: only the shards owning a
        grown/restored row are rebuilt, the rest keep their buffers
        (growth still re-places everything — a new shape means new
        buffers no matter what)."""
        import jax.numpy as jnp

        need_c = _pow2(self._need_clients, self.n_clients)
        d, c = self.state.connected.shape
        if self.n_docs != d or need_c != c:
            if not (self.mesh is not None and self._placed
                    and self._grow_placed(d, c, need_c)):
                # Classic path (scalar, or first placement still
                # pending): zero-pad on the host and re-place below;
                # the appended rows are the new physical tail, so the
                # logical map extends as identity.
                self.state = _sk.grow_state(self.state, self.n_docs,
                                            need_c)
                self._placed = False
                if len(self._phys) < self.n_docs:
                    self._phys = np.concatenate([
                        self._phys,
                        np.arange(len(self._phys), self.n_docs,
                                  dtype=np.int64),
                    ])
            self.n_clients = need_c
        if not self._loads:
            if self.mesh is not None and not self._placed:
                self.state = self._place(self.state)
                self._placed = True
            return
        n, C = len(self._loads), self.n_clients
        idx = np.empty(n, np.int64)
        seqv = np.empty(n, np.int32)
        minv = np.empty(n, np.int32)
        conn = np.zeros((n, C), bool)
        ref = np.zeros((n, C), np.int32)
        cseq = np.zeros((n, C), np.int32)
        for i, (slot, h) in enumerate(self._loads):
            idx[i] = slot
            seqv[i] = h["seq"]
            minv[i] = h["min_seq"]
            cmap = h["cmap"]
            for cid, (r, cs) in h["clients"].items():
                col = cmap[cid]
                conn[i, col] = True
                ref[i, col] = r
                cseq[i, col] = cs
        self._loads = []
        idx = self._phys[idx]  # logical slots -> physical state rows
        updates = {"seq": seqv, "min_seq": minv, "connected": conn,
                   "ref_seq": ref, "client_seq": cseq}
        if (self.mesh is not None and self._placed
                and self._scatter_rows_placed(idx, updates)):
            return
        jidx = jnp.asarray(idx)
        self.state = self.state._replace(
            seq=self.state.seq.at[jidx].set(jnp.asarray(seqv)),
            min_seq=self.state.min_seq.at[jidx].set(jnp.asarray(minv)),
            connected=self.state.connected.at[jidx].set(jnp.asarray(conn)),
            ref_seq=self.state.ref_seq.at[jidx].set(jnp.asarray(ref)),
            client_seq=self.state.client_seq.at[jidx].set(jnp.asarray(cseq)),
        )
        if self.mesh is not None:
            # The host-side scatter loses the docs layout; re-place
            # before the next kernel call (one batched transfer).
            self.state = self._place(self.state)
            self._placed = True

    def run_chunk(self, kind, client, cseq, ref, groups, dedup: bool,
                  aborted=None):
        """One device call; `aborted` threads the boxcar-abort tracker
        across a pump's chunks. Returns (SeqResult as numpy, tracker).
        Sharded pools run the shard_map'd kernel — same abort/dedup
        semantics, doc rows resident on their owning device — and the
        verdict gather is the single device_get below."""
        import jax
        import jax.numpy as jnp

        if aborted is None:
            aborted = _sk.no_aborts(self.n_docs)
        batch = _sk.SeqBatch(
            kind=jnp.asarray(kind), client=jnp.asarray(client),
            client_seq=jnp.asarray(cseq), ref_seq=jnp.asarray(ref),
        )
        if self.mesh is not None:
            fn = _sk.sharded_sequence_fn(self.mesh, dedup=bool(dedup))
            self.state, aborted, res = fn(
                self.state, aborted, batch, jnp.asarray(groups)
            )
        else:
            self.state, aborted, res = _sk.sequence_batch_grouped(
                self.state, batch, jnp.asarray(groups), dedup, aborted
            )
        return jax.device_get(res), aborted

    # ---------------------------------------------------- verdict mirror

    def head(self, doc_id: str) -> int:
        return self.docs[doc_id]["seq"]

    def connected_clients(self, doc_id: str) -> set:
        h = self.docs.get(doc_id)
        return set(h["clients"]) if h else set()

    def expected_cseq(self, doc_id: str, client_id: int) -> Optional[int]:
        st = self.docs[doc_id]["clients"].get(client_id)
        return st[1] + 1 if st is not None else None

    def apply_join(self, doc_id: str, cid: int, seq: int, msn: int) -> None:
        h = self.docs[doc_id]
        h["clients"][cid] = [seq - 1, 0]
        h["seq"], h["min_seq"] = seq, msn

    def apply_leave(self, doc_id: str, cid: int, seq: int, msn: int) -> None:
        h = self.docs[doc_id]
        h["clients"].pop(cid, None)
        h["seq"], h["min_seq"] = seq, msn

    def apply_op(self, doc_id: str, cid: int, seq: int, msn: int,
                 cseq: int, ref: int) -> None:
        h = self.docs[doc_id]
        h["clients"][cid] = [ref, cseq]
        h["seq"], h["min_seq"] = seq, msn

    def apply_stamp(self, doc_id: str, seq: int, msn: int) -> None:
        h = self.docs[doc_id]
        h["seq"], h["min_seq"] = seq, msn

    # -------------------------------------------------------- checkpoint

    def checkpoint_docs(self) -> dict:
        """Per-doc state in `DocumentSequencer.checkpoint()` format —
        scalar and kernel delis restore each other's checkpoints."""
        return {
            doc_id: {
                "doc_id": doc_id,
                "seq": h["seq"],
                "min_seq": h["min_seq"],
                "clients": {
                    str(cid): {
                        "ref_seq": rc[0], "client_seq": rc[1],
                        "last_update": 0.0,
                    }
                    for cid, rc in h["clients"].items()
                },
            }
            for doc_id, h in self.docs.items()
        }

    def restore_docs(self, docs: Optional[dict]) -> None:
        for doc_id, st in (docs or {}).items():
            clients = {
                int(cid): [int(v["ref_seq"]), int(v["client_seq"])]
                for cid, v in st["clients"].items()
            }
            self.docs[doc_id] = {
                "slot": None, "seq": int(st["seq"]),
                "min_seq": int(st["min_seq"]), "clients": clients,
                "cmap": {cid: i + 1 for i, cid in enumerate(clients)},
                "t": 0,
            }
            self.note_client(len(clients) + 1)


class _FlatResults:
    """Kernel verdicts for one pump, aligned with the submission index
    `add()`/`add_columns()` returned. Two shapes share the class: flat
    Python lists (the dict-emission path — one vectorized array→list
    conversion, then plain indexing) or numpy arrays
    (``run(as_arrays=True)`` — the columnar-emission path, where
    verdicts flow into `record_batch.ColumnarRecords` columns as array
    slices without ever becoming per-record Python values)."""

    __slots__ = ("seq", "msn", "nack", "skipped")

    def __init__(self, seq, msn, nack, skipped):
        self.seq = seq
        self.msn = msn
        self.nack = nack
        self.skipped = skipped


class PackedDeliCore:
    """Shared pack → kernel → verdict engine for both deli frontends.

    Per pump: `begin()`, then `touch`/`add` append submissions to flat
    columnar lists (per-record cost: a few list appends); `run()` does
    the rest VECTORIZED — per-doc column assignment via a stable
    argsort cumulative count, [D, B] scatter and verdict gather via
    fancy indexing — executes the chunks in order (the boxcar-abort
    tracker threads across chunks, so groups may span them), and
    returns verdicts aligned with the submission indices."""

    def __init__(self, n_docs: int = 8, n_clients: int = 8,
                 max_resident: Optional[int] = None, max_cols: int = 256,
                 dedup: bool = False, mesh=None):
        self.pool = SeqPool(n_docs, n_clients, max_resident, mesh=mesh)
        self.max_cols = max(8, max_cols)
        self.dedup = dedup
        # Submissions accumulate as ORDERED segments: lists of
        # per-record tuples (`add`) interleaved with pre-columnized
        # (n, 6) arrays (`add_columns` — the bulk ingest surface for
        # producers that already hold columns; the live roles still
        # add() per record because emission needs a per-record plan,
        # see the ROADMAP pre-columnized-emission follow-up). run()
        # concatenates them into the six 1-D columns
        # `ops.sequencer_kernel.pack_submissions` packs from.
        self._segments: List[Any] = []
        self._n_subs = 0
        self._gctr: Dict[int, int] = {}
        # Kernel-path instrumentation: one histogram observation + a
        # handful of gauge/counter updates PER PUMP (never per record —
        # the config-5 overhead guard in tools/bench_configs.py holds
        # the cost under 5%).
        m = get_registry()
        self._m_pump = m.histogram(
            "deli_pump_records",
            buckets=(1, 4, 16, 64, 256, 1024, 4096, 16384),
            impl="kernel",
        )
        self._m_nacks = m.counter("deli_nacks_total", impl="kernel")
        self._m_skips = m.counter("deli_dedup_skips_total", impl="kernel")
        self._m_resident = m.gauge("deli_pool_resident_docs")
        self._m_slots = m.gauge("deli_pool_doc_slots")
        self._m_fill = m.gauge("deli_pool_fill_ratio")
        self._m_cols = m.gauge("deli_pool_client_cols")
        self._m_devices = m.gauge("deli_pool_devices")

    def begin(self) -> None:
        self.pool.begin()
        self._segments = []
        self._n_subs = 0
        self._gctr = {}

    def touch(self, doc_id: str) -> dict:
        """The doc's host-mirror entry (slot + client column map)."""
        return self.pool.touch(doc_id)

    def add(self, slot: int, kind: int, client: int = 0, cseq: int = 0,
            ref: int = 0, group: int = NO_GROUP) -> int:
        """Queue one submission; `client` is the doc's dense COLUMN
        (from the cmap / `col_of_join`, 0 = scratch). Returns the
        submission's verdict index."""
        pool = self.pool
        if client >= pool._need_clients:
            pool._need_clients = client + 1
        segs = self._segments
        if not segs or not isinstance(segs[-1], list):
            segs.append([])
        segs[-1].append((slot, kind, client, cseq, ref, group))
        j = self._n_subs
        self._n_subs = j + 1
        return j

    def add_columns(self, slot, kind, client, cseq, ref,
                    group=NO_GROUP) -> int:
        """Bulk-queue PRE-COLUMNIZED submissions: equal-length 1-D
        sequences (or scalars, broadcast) of doc slots, SUB_* kinds,
        dense client columns, clientSeqs and refSeqs — the shape the
        columnar record-batch codec hands over, appended without
        per-record tuple packing. Returns the first verdict index
        (submission i's verdict is at return + i)."""
        slot = np.asarray(slot, np.int64)
        n = slot.shape[0]
        cols = np.empty((n, 6), np.int64)
        cols[:, 0] = slot
        cols[:, 1] = kind
        cols[:, 2] = client
        cols[:, 3] = cseq
        cols[:, 4] = ref
        cols[:, 5] = group
        if n:
            self.pool.note_client(int(cols[:, 2].max()))
        self._segments.append(cols)
        j = self._n_subs
        self._n_subs = j + n
        return j

    def new_group(self, slot: int) -> int:
        """A fresh boxcar group id, unique per doc within this pump."""
        g = self._gctr.get(slot, 0)
        self._gctr[slot] = g + 1
        return g

    def add_boxcar(self, slot: int, ops: List[Tuple[int, int, int]]):
        """Pack one atomic boxcar: `ops` is [(column, cseq, ref)]; a
        nack masks out the group's tail (an unknown client's op rides
        the scratch column — col 0 — and nacks like the oracle).
        Returns the verdict indices."""
        g = self.new_group(slot)
        add = self.add
        return [add(slot, SUB_OP, col, cs, rf, g) for col, cs, rf in ops]

    def run(self, as_arrays: bool = False) -> _FlatResults:
        pool = self.pool
        pool.prepare()
        n = self._n_subs
        if n == 0:
            if as_arrays:
                z32 = np.zeros(0, np.int32)
                return _FlatResults(z32, z32, z32, np.zeros(0, bool))
            return _FlatResults([], [], [], [])
        parts = [
            np.asarray(s, np.int64).reshape(-1, 6) for s in self._segments
        ]
        cols6 = parts[0] if len(parts) == 1 else np.concatenate(parts)
        self._segments = []
        self._n_subs = 0
        self._gctr = {}
        seq_o = np.empty(n, np.int32)
        msn_o = np.empty(n, np.int32)
        nack_o = np.empty(n, np.int32)
        skip_o = np.empty(n, bool)
        aborted = None
        # Dense [D, B] packing lives with the kernel now
        # (`pack_submissions` accepts the pre-columnized 1-D arrays
        # directly); chunks execute in order so the boxcar-abort
        # tracker threads across them.
        for sel, sl, ic, kind, client, cseq, ref, grp in \
                _sk.pack_submissions(
                    # Logical doc slots -> physical state rows (the
                    # placed-grow renumbering seam; identity on
                    # scalar / never-grown pools).
                    pool._phys[cols6[:, 0]],
                    cols6[:, 1], cols6[:, 2], cols6[:, 3],
                    cols6[:, 4], cols6[:, 5], pool.n_docs, self.max_cols,
                ):
            res, aborted = pool.run_chunk(
                kind, client, cseq, ref, grp, self.dedup, aborted
            )
            seq_o[sel] = res.seq[sl, ic]
            msn_o[sel] = res.min_seq[sl, ic]
            nack_o[sel] = res.nack[sl, ic]
            skip_o[sel] = res.skipped[sl, ic]
        self._m_pump.observe(n)
        nacks = int(np.count_nonzero(nack_o))
        if nacks:
            self._m_nacks.inc(nacks)
        skips = int(np.count_nonzero(skip_o))
        if skips:
            self._m_skips.inc(skips)
        resident = pool.resident_docs()
        self._m_resident.set(resident)
        self._m_slots.set(pool.n_docs)
        self._m_fill.set(resident / pool.n_docs if pool.n_docs else 0.0)
        self._m_cols.set(pool.n_clients)
        self._m_devices.set(pool._n_shards)
        if as_arrays:
            return _FlatResults(seq_o, msn_o, nack_o, skip_o)
        return _FlatResults(
            seq_o.tolist(), msn_o.tolist(), nack_o.tolist(), skip_o.tolist()
        )


# ---------------------------------------------------------------------------
# in-proc frontend (LocalServer)
# ---------------------------------------------------------------------------


class KernelDeliLambda:
    """Drop-in for `lambdas.DeliLambda`: same topics, same deltas
    entries, same checkpoint shape — sequencing decisions on device.

    Select with `LocalServer(deli_impl="kernel")` or `FLUID_DELI=kernel`;
    the scalar `DeliLambda` is the oracle (tests/test_deli_kernel.py
    drives both with identical traffic) and the fallback."""

    def __init__(self, log: MessageLog, checkpoint: Optional[dict] = None,
                 max_pump: int = 8192, n_docs: int = 8, n_clients: int = 8,
                 max_resident: Optional[int] = None, max_cols: int = 256,
                 raw_topic: str = "rawdeltas",
                 deli_devices: Optional[int] = None,
                 device_plane=None, plane_column: Optional[int] = None):
        """`raw_topic` names the ingress topic (the sharded
        LocalServer's per-partition ``rawdeltas-p{k}`` form).
        `deli_devices=N` shards the doc-slot pool across an N-device
        mesh (`LocalServer(deli_devices=N)` passes it through);
        `device_plane` instead takes the sequencer's 1-D slice of the
        shared 2-D plane (`parallel.device_plane`, model column
        `plane_column`). Either way the checkpoint shape is
        topology-free, so restores interop across scalar ⇄
        single-device ⇄ sharded ⇄ plane-sliced freely."""
        if device_plane is not None and deli_devices is not None \
                and int(deli_devices) > 1:
            raise ValueError(
                "deli_devices and device_plane are exclusive: the "
                "plane's seq_mesh IS the deli's device slice"
            )
        mesh = mesh_for_devices(deli_devices)
        if mesh is None:
            mesh = mesh_for_plane(device_plane, plane_column)
        self.core = PackedDeliCore(
            n_docs, n_clients, max_resident, max_cols, dedup=False,
            mesh=mesh,
        )
        offset = 0
        if checkpoint:
            from .supervisor import unwrap_ranged_state

            offset = checkpoint["offset"]
            self.core.pool.restore_docs(
                unwrap_ranged_state(checkpoint["docs"])
            )
        self.consumer = LogConsumer(log.topic(raw_topic), offset)
        self.deltas = log.topic("deltas")
        self.max_pump = max_pump
        self._m_stage = get_registry().histogram(
            "op_stage_ms", stage="submit_to_stamp"
        )

    def pump(self, max_count: Optional[int] = None) -> int:
        """Drain up to `max_count` raw records (micro-batch cap: a deep
        backlog yields between pumps instead of starving the caller)."""
        cap = self.max_pump if max_count is None else max_count
        raws = self.consumer.poll(cap)
        if not raws:
            return 0
        out = self._process(raws)
        if out:
            self.deltas.append_many(out)
        return len(raws)

    def _process(self, raws: List[dict]) -> List[dict]:
        core = self.core
        pool = core.pool
        core.begin()
        touch, add, col_of_join = core.touch, core.add, pool.col_of_join
        docs_cache: Dict[str, tuple] = {}  # touch once per doc per pump
        plan: List[tuple] = []
        append = plan.append
        for raw in raws:
            if not isinstance(raw, dict) or not raw.get("doc"):
                continue  # journal LOST_RECORD placeholder / junk
            doc_id = raw["doc"]
            ent = docs_cache.get(doc_id)
            if ent is None:
                h = touch(doc_id)
                ent = docs_cache[doc_id] = (h["slot"], h)
            slot, h = ent
            cmap = h["cmap"]
            kind = raw["kind"]
            if kind == "join":
                cid = raw["client"]
                append((doc_id, add(slot, SUB_JOIN, col_of_join(h, cid)),
                        "join", cid, None))
            elif kind == "leave":
                cid = raw["client"]
                # Unknown client -> scratch column -> nothing stamped.
                append((doc_id, add(slot, SUB_LEAVE, cmap.get(cid, 0)),
                        "leave", cid, None))
            elif kind == "control":
                append((doc_id, add(slot, SUB_SYSTEM), "sys",
                        raw["type"], raw["contents"]))
            elif kind == "boxcar":
                cid = raw["client"]
                msgs = raw["msgs"]
                col = cmap.get(cid, 0)
                handles = core.add_boxcar(
                    slot, [(col, m.client_seq, m.ref_seq) for m in msgs]
                )
                for hd, m in zip(handles, msgs):
                    append((doc_id, hd, "op", cid, m))
            else:  # client op; unknown -> scratch column -> 403 nack
                cid = raw["client"]
                msg = raw["msg"]
                append((doc_id, add(slot, SUB_OP, cmap.get(cid, 0),
                                    msg.client_seq, msg.ref_seq),
                        "op", cid, msg))
        res = core.run()

        out: List[dict] = []
        emit = out.append
        seqs, msns, nacks, skips = res.seq, res.msn, res.nack, res.skipped
        apply_op = pool.apply_op
        ts = time.time()
        observe_stage = self._m_stage.observe
        for doc_id, handle, tag, a, b in plan:
            if tag == "op":
                if skips[handle]:
                    continue
                seq, msn, nack = seqs[handle], msns[handle], nacks[handle]
                if nack:
                    reason = _nack_reason(
                        nack, b.ref_seq, msn, pool.head(doc_id),
                        b.client_seq, pool.expected_cseq(doc_id, a),
                    )
                    emit({"doc": doc_id, "kind": "nack", "client": a,
                          "msg": NackMessage(a, b.client_seq, nack, reason)})
                    continue
                apply_op(doc_id, a, seq, msn, b.client_seq, b.ref_seq)
                # Same op-lifecycle trace contract as the scalar deli
                # (traces are observability-only: excluded from journal
                # encoding and every digest form).
                tr = [("stamp", ts)]
                sub = trace_submit_ts(b.metadata)
                if sub is not None:
                    tr.insert(0, ("submit", sub))
                    observe_stage((ts - sub) * 1000.0)
                emit({"doc": doc_id, "kind": "op",
                      "msg": SequencedMessage(
                          seq, msn, a, b.client_seq, b.ref_seq,
                          b.type, b.contents, b.metadata, b.address, ts,
                          tr)})
            elif tag == "join":
                seq, msn = seqs[handle], msns[handle]
                pool.apply_join(doc_id, a, seq, msn)
                emit({"doc": doc_id, "kind": "op",
                      "msg": SequencedMessage(
                          seq, msn, a, 0, seq - 1,
                          MessageType.CLIENT_JOIN, a, None, None, ts,
                          [("stamp", ts)])})
            elif tag == "leave":
                seq, msn = seqs[handle], msns[handle]
                if seq == 0:
                    continue  # unknown client: oracle stamps nothing
                pool.apply_leave(doc_id, a, seq, msn)
                emit({"doc": doc_id, "kind": "op",
                      "msg": SequencedMessage(
                          seq, msn, a, 0, seq - 1,
                          MessageType.CLIENT_LEAVE, a, None, None, ts,
                          [("stamp", ts)])})
            else:  # sys
                seq, msn = seqs[handle], msns[handle]
                pool.apply_stamp(doc_id, seq, msn)
                emit({"doc": doc_id, "kind": "op",
                      "msg": SequencedMessage(
                          seq, msn, SYSTEM_CLIENT, 0, seq - 1,
                          a, b, None, None, ts, [("stamp", ts)])})
        return out

    def checkpoint(self) -> dict:
        """Same shape as `DeliLambda.checkpoint()` (offset + per-doc
        `DocumentSequencer` states): restart may switch impls freely.
        Checkpoint time doubles as the column-compaction sweep
        (ROADMAP (c)) — the state written never names departed
        clients, and the pool reclaims their columns on the spot."""
        self.core.pool.compact_all()
        return {
            "offset": self.consumer.checkpoint(),
            "docs": self.core.pool.checkpoint_docs(),
        }


# ---------------------------------------------------------------------------
# supervised-farm frontend (exactly-once recovery)
# ---------------------------------------------------------------------------

# Wire `type` codes the emit columns stamp (the K_SEQ_OP type column).
_TC_OP = _rb._TYPE_CODE["op"]
_TC_JOIN = _rb._TYPE_CODE["join"]
_TC_LEAVE = _rb._TYPE_CODE["leave"]


class _ScalarEmit:
    """Scalar-record accumulator for the columnar emission path: the
    records that still need per-record handling (nacks with their
    reason text, joins/leaves, dict-ingested strays, boxcar members)
    land as COLUMNS in stream order, so one pump's whole output is
    `ColumnarRecords` parts end to end — never a per-record wire
    dict. `flush()` closes the current accumulation into a part
    appended to `out` (called before every vectorized span so parts
    splice back in exact stream order)."""

    __slots__ = ("docs", "doc_of", "kind", "tc", "didx", "client",
                 "cseq", "ref", "seq", "msn", "inoff", "blobs")

    def __init__(self):
        self.docs: List[str] = []
        self.doc_of: Dict[str, int] = {}
        self.kind: List[int] = []
        self.tc: List[int] = []
        self.didx: List[int] = []
        self.client: List[int] = []
        self.cseq: List[int] = []
        self.ref: List[int] = []
        self.seq: List[int] = []
        self.msn: List[int] = []
        self.inoff: List[int] = []
        self.blobs: List[bytes] = []

    def _doc(self, doc: str) -> int:
        di = self.doc_of.get(doc)
        if di is None:
            di = self.doc_of[doc] = len(self.docs)
            self.docs.append(doc)
        return di

    def op(self, doc: str, tc: int, cid: int, cseq: int, ref: int,
           seq: int, msn: int, inoff: int, contents: Any) -> None:
        self.kind.append(_rb.K_SEQ_OP)
        self.tc.append(tc)
        self.didx.append(self._doc(doc))
        self.client.append(cid)
        self.cseq.append(cseq)
        self.ref.append(ref)
        self.seq.append(seq)
        self.msn.append(msn)
        self.inoff.append(inoff)
        self.blobs.append(_rb._dumps(contents))  # JsonBlob rides raw

    def member(self, doc: str, tc: int, cid: int, seq: int, msn: int,
               inoff: int) -> None:
        # join/leave wire shape: clientSeq 0, refSeq seq-1, contents=cid
        self.op(doc, tc, cid, 0, seq - 1, seq, msn, inoff, cid)

    def nack(self, doc: str, cid: int, cseq: int, code: int,
             reason: str, inoff: int) -> None:
        self.kind.append(_rb.K_NACK)
        self.tc.append(_rb._NO_TYPE)
        self.didx.append(self._doc(doc))
        self.client.append(cid)
        self.cseq.append(cseq)
        self.ref.append(0)
        self.seq.append(code)  # code rides the seq column
        self.msn.append(0)
        self.inoff.append(inoff)
        self.blobs.append(_rb._dumps(reason))

    def flush(self, out: List[Any]) -> None:
        n = len(self.kind)
        if not n:
            return
        blob_off = np.zeros(n + 1, np.uint32)
        blob_off[1:] = np.cumsum([len(b) for b in self.blobs])
        out.append(_rb.ColumnarRecords(
            self.docs, self.kind, self.tc, self.didx, self.client,
            self.cseq, self.ref, self.seq, self.msn, self.inoff,
            blob_off, b"".join(self.blobs),
        ))
        self.__init__()


class KernelDeliRole(_Role):
    """Drop-in for `supervisor.DeliRole` with device-batched ticketing.

    `process()` buffers validated records; `flush_batch()` (called by
    the supervision step AND the recovery gap-replay) packs them, runs
    the kernel, and emits the same wire records as the scalar role —
    each carrying its input offset (`inOff`), so the fenced
    exactly-once recovery contract (PR 1) holds unchanged: a restart
    mid-batch scans the durable output prefix and silently replays the
    gap through the same kernel path without re-emitting.

    Over a columnar op-log (`--log-format columnar`) the role ingests
    whole `RecordBatch` frames (`process_batch`): doc ids come from the
    batch dictionary, the int fields straight off the codec's columns,
    and standalone ops' `contents` stay PRE-ENCODED JSON blobs end to
    end when the output topic is columnar too — zero per-record JSON
    decode on the deli hot path (ROADMAP (a)/(d)). Wire boxcar records
    sequence atomically through the kernel's group machinery, matching
    the scalar role's schema-rev semantics bit for bit (their packed
    ops decode once per boxcar — per-op blob pass-through inside a
    boxcar needs a nested-offset codec rev, noted in ROADMAP)."""

    name = "deli"
    in_topic_name = "rawdeltas"
    out_topic_name = "deltas"
    ingest_batches = True  # _Role.step feeds RecordBatch frames whole

    def __init__(self, *a, mesh=None, deli_devices: Optional[int] = None,
                 device_plane=None, plane_column: Optional[int] = None,
                 **kw):
        """`mesh` (a ready 1-D docs mesh) or `deli_devices=N` (resolved
        via the process-wide shared mesh) shards the pool across
        devices; the wire records, `inOff` recovery contract and
        checkpoint format are identical either way, so the fenced
        exactly-once machinery and the shard fabric compose unchanged
        — a fabric partition worker may run each partition's deli over
        its own device slice. `device_plane`/`plane_column` instead
        take the sequencer's 1-D slice of the shared 2-D plane
        (`parallel.device_plane`; the column defaults to a stable
        hash of the partition key — one partition = one mesh slice),
        falling back to the ``FLUID_DEVICE_PLANE`` env so supervised
        children inherit the farm plane."""
        if device_plane is not None and deli_devices is not None \
                and int(deli_devices) > 1:
            raise ValueError(
                "deli_devices and device_plane are exclusive: the "
                "plane's seq_mesh IS the deli's device slice"
            )
        super().__init__(*a, **kw)
        self.mesh = mesh if mesh is not None else \
            mesh_for_devices(deli_devices)
        if self.mesh is None and (deli_devices is None
                                  or int(deli_devices) <= 1):
            self.mesh = mesh_for_plane(
                device_plane, plane_column,
                partition_key=self.partition, env=True,
            )
        self.core = PackedDeliCore(dedup=True, mesh=self.mesh)
        self._pending: List[tuple] = []  # ("rec", off, dict) |
        #                                 ("cols", start_off, RecordBatch)
        # Blob pass-through is only legal when the output topic can
        # carry raw JSON bytes (a columnar sibling); a JSON out topic
        # needs decoded values for its json.dumps.
        from .columnar_log import ColumnarFileTopic

        self.out_columnar = isinstance(self.out_topic, ColumnarFileTopic)

    # ------------------------------------------------------------ state

    def snapshot_state(self) -> Any:
        # Checkpoint time doubles as the column-compaction sweep
        # (ROADMAP (c)): the snapshot never names departed clients.
        self.core.pool.compact_all()
        return self.core.pool.checkpoint_docs()

    def restore_state(self, state: Any) -> None:
        from .supervisor import unwrap_ranged_state

        core = PackedDeliCore(dedup=True, mesh=self.mesh)
        core.pool.restore_docs(unwrap_ranged_state(state))
        self.core = core

    # ------------------------------------------------------------- pump

    def process(self, line_idx: int, rec: Any, out: List[dict]) -> None:
        if not isinstance(rec, dict) or "doc" not in rec:
            return  # foreign/junk record: consume and move on
        if rec.get("kind") not in ("join", "leave", "op", "boxcar"):
            return
        self._pending.append(("rec", line_idx, rec))

    def process_batch(self, start_line: int, batch: Any,
                      out: List[dict]) -> None:
        """Columnar ingest: queue one `RecordBatch` whole (records
        numbered start_line..start_line+n-1)."""
        self._pending.append(("cols", start_line, batch))

    def _plan_op(self, plan, add, line_idx, doc, slot, col, cid, cseq,
                 ref, contents, group=NO_GROUP, sub_ts=None,
                 adm_ts=None):
        # `sub_ts`/`adm_ts` thread the client submit stamp (ingress
        # "tr_sub") and the front door's admission stamp ("tr_adm")
        # through the plan tuple so wire-trace mode can stamp/observe
        # at emit time — the kernel twin of the scalar role's span
        # coverage (PR 9 follow-up b; admit_to_stamp from ISSUE 13).
        plan.append((line_idx, doc, "op",
                     (cid, cseq, ref, contents, sub_ts, adm_ts),
                     add(slot, SUB_OP, col, cseq, ref, group)))

    def flush_batch(self, out: List[dict]) -> None:
        if not self._pending:
            return
        core = self.core
        pool = core.pool
        core.begin()
        touch, add, col_of_join = core.touch, core.add, pool.col_of_join
        docs_cache: Dict[str, tuple] = {}  # touch once per doc per pump
        plan: List[tuple] = []
        shadow: Dict[str, set] = {}
        # Columnar emission (the pre-columnized emit path): legal when
        # the out topic carries raw frames and nothing downstream needs
        # per-record wire dicts — wire tracing adds a side "tr" key
        # (generic schema), recovery's silent replay and the ranged
        # fabric's predecessor drains post-process dict records
        # (inOff filters, inSrc tags).
        emit_cols = (self.out_columnar and not self.trace_wire
                     and not self._recovering and not self._dict_emit)

        def doc_entry(doc):
            ent = docs_cache.get(doc)
            if ent is None:
                h = touch(doc)
                ent = docs_cache[doc] = (h["slot"], h)
            return ent

        def plan_record(line_idx, rec):
            doc = rec["doc"]
            slot, h = doc_entry(doc)
            kind = rec["kind"]
            cid = rec["client"]
            if kind == "op":
                # Unknown/foreign client id -> scratch column -> the
                # oracle's unknown-client nack, no state aliasing.
                self._plan_op(
                    plan, add, line_idx, doc, slot,
                    h["cmap"].get(cid, 0), cid, rec["clientSeq"],
                    rec.get("refSeq", 0), rec.get("contents"),
                    sub_ts=rec.get("tr_sub"),
                    adm_ts=rec.get("tr_adm"),
                )
            elif kind == "boxcar":
                plan_boxcar(line_idx, doc, slot, h, cid, [
                    (op["clientSeq"], op.get("refSeq", 0),
                     op.get("contents"))
                    for op in rec.get("ops") or []
                ], sub_ts=rec.get("tr_sub"),
                    adm_ts=rec.get("tr_adm"))
            elif kind == "join":
                conn = shadow.get(doc)
                if conn is None:
                    conn = shadow[doc] = pool.connected_clients(doc)
                if cid in conn:
                    return  # duplicate join (at-least-once ingress)
                conn.add(cid)
                plan.append((line_idx, doc, "join", cid,
                             add(slot, SUB_JOIN, col_of_join(h, cid))))
            else:  # leave
                conn = shadow.get(doc)
                if conn is None:
                    conn = shadow[doc] = pool.connected_clients(doc)
                conn.discard(cid)
                plan.append((line_idx, doc, "leave", cid,
                             add(slot, SUB_LEAVE, h["cmap"].get(cid, 0))))

        def plan_boxcar(line_idx, doc, slot, h, cid, ops, sub_ts=None,
                        adm_ts=None):
            # One atomic group: a nack masks the group's tail in-kernel
            # (resubmission dedup stays per-op and silent).
            col = h["cmap"].get(cid, 0)
            g = core.new_group(slot)
            for cseq, ref, contents in ops:
                self._plan_op(plan, add, line_idx, doc, slot, col, cid,
                              cseq, ref, contents, group=g,
                              sub_ts=sub_ts, adm_ts=adm_ts)

        passthrough = self.out_columnar
        for ent in self._pending:
            if ent[0] == "rec":
                plan_record(ent[1], ent[2])
                continue
            self._plan_cols(plan, ent[2], ent[1], doc_entry,
                            plan_record, plan_boxcar, passthrough)
        self._pending = []
        res = core.run(as_arrays=emit_cols)
        if emit_cols:
            self._emit_columns(plan, res, out)
        else:
            self._emit_dicts(plan, res, out)

    # ------------------------------------------------- columnar ingest

    # Below this, a K_RAW_OP run takes the per-record tuple path: the
    # per-run fixed cost (unique-doc touch, array builds, one emit
    # part per span) only amortizes over real runs — a join-interleaved
    # stream decomposes into length-1 "runs" that would otherwise pay
    # it per record.
    MIN_OP_RUN = 16

    def _plan_cols(self, plan, rb, base, doc_entry, plan_record,
                   plan_boxcar, passthrough) -> None:
        """Queue one ingested `RecordBatch`: homogeneous K_RAW_OP runs
        (at least `MIN_OP_RUN` long) go through
        `PackedDeliCore.add_columns` as arrays (doc slots via one
        touch per unique doc, dense client columns via one cmap probe
        per record — no plan tuples, no per-record blob handles),
        everything else (joins/leaves/boxcars/generic strays, short op
        runs) through the per-record plan."""
        n = rb.n
        if n == 0:
            return
        docs = rb.docs
        kinds_l = None
        cseqs_l = refs_l = None
        for run_is_op, lo, hi in _sk.mask_runs(rb.kind == _rb.K_RAW_OP):
            if run_is_op and hi - lo >= self.MIN_OP_RUN:
                self._plan_op_run(plan, rb, lo, hi, base, doc_entry)
                continue
            if kinds_l is None:
                kinds_l = rb.kind.tolist()
                doci = rb.doc_idx.tolist()
                clients = rb.client.tolist()
            for i in range(lo, hi):
                k = kinds_l[i]
                if k == _rb.K_RAW_OP:
                    if cseqs_l is None:
                        cseqs_l = rb.client_seq.tolist()
                        refs_l = rb.ref_seq.tolist()
                    doc = docs[doci[i]]
                    slot, h = doc_entry(doc)
                    cid = clients[i]
                    contents: Any = _rb.JsonBlob(rb.blob(i))
                    if not passthrough:
                        contents = contents.value
                    self._plan_op(
                        plan, self.core.add, base + i, doc, slot,
                        h["cmap"].get(cid, 0), cid, cseqs_l[i],
                        refs_l[i], contents,
                    )
                elif k == _rb.K_RAW_BOXCAR:
                    doc = docs[doci[i]]
                    slot, h = doc_entry(doc)
                    # v2 frames: per-op ints off the nested columns,
                    # per-op contents as raw-blob handles end to end.
                    ops = rb.boxcar(i)
                    if not passthrough:
                        ops = [
                            (cs, rf, c.value
                             if isinstance(c, _rb.JsonBlob) else c)
                            for cs, rf, c in ops
                        ]
                    plan_boxcar(base + i, doc, slot, h, clients[i],
                                ops)
                elif k in (_rb.K_RAW_JOIN, _rb.K_RAW_LEAVE):
                    plan_record(base + i, {
                        "kind": "join" if k == _rb.K_RAW_JOIN
                        else "leave",
                        "doc": docs[doci[i]], "client": clients[i],
                    })
                else:
                    # Generic / foreign record inside the frame: decode
                    # this one record and route it the legacy way.
                    rec = rb.record(i)
                    if isinstance(rec, dict) and "doc" in rec and \
                            rec.get("kind") in ("join", "leave", "op",
                                                "boxcar"):
                        plan_record(base + i, rec)

    def _plan_op_run(self, plan, rb, lo, hi, base, doc_entry) -> None:
        """Bulk-queue one contiguous K_RAW_OP run [lo, hi) through
        `add_columns` — the pre-columnized ingest half finally on the
        live path."""
        docs = rb.docs
        doci = rb.doc_idx[lo:hi]
        slot_of: Dict[int, int] = {}
        h_of: Dict[int, dict] = {}
        for d in np.unique(doci).tolist():
            slot, h = doc_entry(docs[d])
            slot_of[d] = slot
            h_of[d] = h
        m = hi - lo
        doci_l = doci.tolist()
        clients_l = rb.client[lo:hi].tolist()
        slots = np.fromiter((slot_of[d] for d in doci_l), np.int64, m)
        cols = np.fromiter(
            (h_of[d]["cmap"].get(c, 0)
             for d, c in zip(doci_l, clients_l)),
            np.int64, m,
        )
        j0 = self.core.add_columns(
            slots, SUB_OP, cols, rb.client_seq[lo:hi],
            rb.ref_seq[lo:hi],
        )
        plan.append((base, None, "run", (j0, rb, lo, hi, h_of), None))

    # ----------------------------------------------------- emission

    def _emit_dicts(self, plan, res, out: List[dict]) -> None:
        """The per-record wire-dict emission (the differential-oracle
        shape, and the path recovery / tracing / ranged drains use)."""
        pool = self.core.pool
        emit = out.append
        seqs, msns, nacks, skips = res.seq, res.msn, res.nack, res.skipped
        apply_op = pool.apply_op
        # Wire-trace stamps: ONE clock read per flush (the kernel
        # role's whole-pump philosophy — KernelDeliLambda stamps the
        # same way), serving both the record stamp and the
        # submit_to_stamp observe so the two surfaces agree exactly.
        trace = self.trace_wire
        now = time.time() if trace else 0.0

        def emit_op(line_idx, doc, cid, cseq, ref, contents, sub_ts,
                    adm_ts, handle):
            if skips[handle]:
                return  # deduped resubmission / aborted boxcar tail
            seq, msn, nack = seqs[handle], msns[handle], nacks[handle]
            if nack:
                emit({"kind": "nack", "doc": doc, "client": cid,
                      "clientSeq": cseq, "code": nack,
                      "reason": _nack_reason(
                          nack, ref, msn, pool.head(doc), cseq,
                          pool.expected_cseq(doc, cid)),
                      "inOff": line_idx})
                return
            apply_op(doc, cid, seq, msn, cseq, ref)
            rec = {"kind": "op", "doc": doc, "seq": seq, "msn": msn,
                   "client": cid, "clientSeq": cseq, "refSeq": ref,
                   "type": "op", "contents": contents,
                   "inOff": line_idx}
            if trace:
                tr = {"stamp": now}
                if isinstance(sub_ts, (int, float)):
                    tr["sub"] = sub_ts
                    if not self._recovering:
                        # Recovery's silent replay must not be
                        # re-observed (crash-spanning durations) —
                        # the scalar role's rule, kernel-side.
                        self._observe_stage(
                            "submit_to_stamp",
                            (now - sub_ts) * 1000.0,
                        )
                if isinstance(adm_ts, (int, float)):
                    # The front door's admission stamp: same flush
                    # clock read, same recovery-silent rule — the
                    # scalar role's admit_to_stamp, kernel-side.
                    tr["adm"] = adm_ts
                    if not self._recovering:
                        self._observe_stage(
                            "admit_to_stamp",
                            (now - adm_ts) * 1000.0,
                        )
                rec["tr"] = tr
            emit(rec)

        for line_idx, doc, tag, payload, handle in plan:
            if tag == "op":
                cid, cseq, ref, contents, sub_ts, adm_ts = payload
                emit_op(line_idx, doc, cid, cseq, ref, contents,
                        sub_ts, adm_ts, handle)
            elif tag == "run":
                j0, rb, lo, hi, _h_of = payload
                docs = rb.docs
                doci = rb.doc_idx
                clients = rb.client
                cseqs = rb.client_seq
                refs = rb.ref_seq
                for i in range(lo, hi):
                    contents: Any = _rb.JsonBlob(rb.blob(i))
                    if not self.out_columnar:
                        contents = contents.value
                    emit_op(line_idx + i, docs[int(doci[i])],
                            int(clients[i]), int(cseqs[i]),
                            int(refs[i]), contents, None, None,
                            j0 + i - lo)
            elif tag == "join":
                seq, msn = seqs[handle], msns[handle]
                pool.apply_join(doc, payload, seq, msn)
                rec = {"kind": "op", "doc": doc, "seq": seq, "msn": msn,
                       "client": payload, "clientSeq": 0,
                       "refSeq": seq - 1,
                       "type": "join", "contents": payload,
                       "inOff": line_idx}
                if trace:
                    rec["tr"] = {"stamp": now}
                emit(rec)
            else:  # leave
                seq, msn = seqs[handle], msns[handle]
                if seq == 0:
                    continue  # unknown client: nothing stamped
                pool.apply_leave(doc, payload, seq, msn)
                rec = {"kind": "op", "doc": doc, "seq": seq, "msn": msn,
                       "client": payload, "clientSeq": 0,
                       "refSeq": seq - 1,
                       "type": "leave", "contents": payload,
                       "inOff": line_idx}
                if trace:
                    rec["tr"] = {"stamp": now}
                emit(rec)

    def _emit_columns(self, plan, res, out: List[Any]) -> None:
        """The pre-columnized emission: verdict arrays flow into
        `ColumnarRecords` parts (ingest blob bytes pass through as
        whole heap spans), appended to `out` in exact stream order —
        `ColumnarFileTopic.append_many` splices them into one frame
        with zero per-record classification. The host mirror updates
        from flat column lists (bookkeeping-from-results, no wire
        dicts); nack reasons stay per-record (rare, text-only)."""
        pool = self.core.pool
        seqs, msns, nacks, skips = res.seq, res.msn, res.nack, res.skipped
        sc = _ScalarEmit()
        for line_idx, doc, tag, payload, handle in plan:
            if tag == "run":
                self._emit_run(payload, res, sc, out, line_idx)
            elif tag == "op":
                if skips[handle]:
                    continue
                seq = int(seqs[handle])
                msn = int(msns[handle])
                nack = int(nacks[handle])
                cid, cseq, ref, contents, _sub, _adm = payload
                if nack:
                    sc.nack(doc, cid, cseq, nack, _nack_reason(
                        nack, ref, msn, pool.head(doc), cseq,
                        pool.expected_cseq(doc, cid)), line_idx)
                    continue
                pool.apply_op(doc, cid, seq, msn, cseq, ref)
                sc.op(doc, _TC_OP, cid, cseq, ref, seq, msn, line_idx,
                      contents)
            elif tag == "join":
                seq = int(seqs[handle])
                msn = int(msns[handle])
                pool.apply_join(doc, payload, seq, msn)
                sc.member(doc, _TC_JOIN, payload, seq, msn, line_idx)
            else:  # leave
                seq = int(seqs[handle])
                msn = int(msns[handle])
                if seq == 0:
                    continue  # unknown client: nothing stamped
                pool.apply_leave(doc, payload, seq, msn)
                sc.member(doc, _TC_LEAVE, payload, seq, msn, line_idx)
        sc.flush(out)

    def _emit_run(self, payload, res, sc: _ScalarEmit, out: List[Any],
                  base: int) -> None:
        """Emit one ingested K_RAW_OP run: contiguous ACCEPTED spans
        become `ColumnarRecords` parts — verdict columns sliced
        straight off the kernel result, contents blobs one heap memcpy
        per span — while nacked records (rare) take the scalar path in
        place, so the output order is exactly the scalar role's."""
        j0, rb, lo, hi, h_of = payload
        m = hi - lo
        seqs = res.seq[j0:j0 + m]
        msns = res.msn[j0:j0 + m]
        nacks = res.nack[j0:j0 + m]
        skips = res.skipped[j0:j0 + m]
        # 0 = dropped (dedup), 1 = accepted, 2 = nacked.
        cat = np.where(skips, 0,
                       np.where(nacks == 0, 1, 2)).astype(np.int8)
        pool = self.core.pool
        for c, a, b in _sk.mask_runs(cat):
            if c == 0:
                continue  # deduped resubmissions: nothing emitted
            rows = slice(lo + a, lo + b)
            if c == 1:
                off = rb._blob_off[lo + a:lo + b + 1]
                heap = bytes(rb._heap[off[0]:off[-1]])
                seq64 = seqs[a:b].astype(np.int64)
                msn64 = msns[a:b].astype(np.int64)
                w = b - a
                part = _rb.ColumnarRecords(
                    rb.docs,
                    np.full(w, _rb.K_SEQ_OP, np.uint8),
                    np.full(w, _TC_OP, np.uint8),
                    rb.doc_idx[rows],
                    rb.client[rows], rb.client_seq[rows],
                    rb.ref_seq[rows],
                    seq64, msn64,
                    np.arange(base + lo + a, base + lo + b,
                              dtype=np.int64),
                    (off - off[0]).astype(np.uint32), heap,
                )
                sc.flush(out)  # strays before this span keep order
                out.append(part)
                # Mirror update from flat columns (last write wins per
                # (doc, client) — order-equivalent within a span of
                # plain ops, and spans run in stream order).
                for d, cl, cs, rf, sq, mn in zip(
                        rb.doc_idx[rows].tolist(),
                        rb.client[rows].tolist(),
                        rb.client_seq[rows].tolist(),
                        rb.ref_seq[rows].tolist(),
                        seq64.tolist(), msn64.tolist()):
                    h = h_of[d]
                    h["clients"][cl] = [rf, cs]
                    h["seq"] = sq
                    h["min_seq"] = mn
            else:
                docs = rb.docs
                for i in range(lo + a, lo + b):
                    j = i - lo
                    doc = docs[int(rb.doc_idx[i])]
                    cid = int(rb.client[i])
                    cseq = int(rb.client_seq[i])
                    ref = int(rb.ref_seq[i])
                    nk = int(nacks[j])
                    msn = int(msns[j])
                    sc.nack(doc, cid, cseq, nk, _nack_reason(
                        nk, ref, msn, pool.head(doc), cseq,
                        pool.expected_cseq(doc, cid)), base + i)
