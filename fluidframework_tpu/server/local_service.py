"""In-process ordering service: sequencer + fan-out.

Plays the role the reference's LocalOrderer + LocalDeltaConnectionServer
play for tests and local dev (memory-orderer/src/localOrderer.ts:95,
local-server/src/localDeltaConnectionServer.ts:63): clients connect,
submit DocumentMessages, and every connected client receives the totally
ordered SequencedMessage stream. A pluggable op store keeps the durable
log (the scriptorium role) so late joiners can catch up.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Dict, List, Optional, Union

from ..protocol.messages import DocumentMessage, NackMessage, SequencedMessage
from ..utils.events import BufferedListener
from .sequencer import DocumentSequencer

Listener = Callable[[SequencedMessage], None]
NackListener = Callable[[NackMessage], None]


class _Connection(BufferedListener):
    def __init__(self, service: "LocalOrderingService", doc_id: str, client_id: int):
        super().__init__()
        self.service = service
        self.doc_id = doc_id
        self.client_id = client_id
        self.nack_listener: Optional[NackListener] = None
        # Invoked (once) when the connection dies — the transport
        # "disconnect" event the reference DeltaManager surfaces to the
        # container (connectionManager.ts:170). Assigned by
        # ContainerRuntime.connect; fires for BOTH locally initiated
        # and server/driver-initiated disconnects.
        self.disconnect_listener: Optional[Callable[[], None]] = None
        self.connected = True
        # Sequence number of this connection's join message: live
        # delivery covers strictly-later messages; everything at/before
        # it is fetched via catch_up (so a joiner never double-receives
        # messages queued before it connected).
        self.join_seq = 0

    def submit(self, msg: DocumentMessage) -> None:
        if not self.connected:
            raise RuntimeError("connection closed")
        self.service._submit(self.doc_id, self.client_id, msg)

    def submit_batch(self, msgs: List[DocumentMessage]) -> None:
        """Boxcar parity with the lambda pipeline's socket: the simple
        orderer sequences back-to-back, which is already atomic. A
        synchronous nack mid-batch disconnects this connection; the
        remainder stays pending client-side for the reconnect replay
        (never raise into the caller's flush)."""
        for msg in msgs:
            if not self.connected:
                return
            self.submit(msg)

    def catch_up(self, from_seq: int) -> List[SequencedMessage]:
        """Ops in (from_seq, join_seq] — the gap between a loaded
        summary/last session and this connection (the
        IDocumentDeltaStorageService fetch of Container.load,
        SURVEY.md §3.4)."""
        return [
            m
            for m in self.service.ops_from(self.doc_id, from_seq)
            if m.sequence_number <= self.join_seq
        ]

    def disconnect(self) -> None:
        if self.connected:
            self.connected = False
            self.service._leave(self.doc_id, self.client_id)
            if self.disconnect_listener is not None:
                self.disconnect_listener()


class LocalOrderingService:
    """All documents' sequencers + their connected clients, in-proc.

    Delivery is synchronous and depth-first by default (submit ->
    everyone's listener runs before submit returns), matching the
    determinism the in-proc reference harness relies on. Set
    `deferred=True` to queue deliveries and drain them explicitly
    (`process_all`), which is how tests interleave op races — the role of
    MockContainerRuntimeFactory.processAllMessages (reference:
    packages/runtime/test-runtime-utils/src/mocks.ts:107).
    """

    def __init__(self, deferred: bool = False):
        self.sequencers: Dict[str, DocumentSequencer] = {}
        self.connections: Dict[str, List[_Connection]] = {}
        self.op_log: Dict[str, List[SequencedMessage]] = {}
        self.deferred = deferred
        self._queue: deque[SequencedMessage] = deque()
        self._doc_queue: Dict[str, deque] = {}
        self._next_client_id: Dict[str, int] = {}
        # doc -> (covered seq, summary wire): the catchup shelf.
        self._summaries: Dict[str, tuple] = {}

    # ------------------------------------------------------ connections

    def connect(self, doc_id: str, client_id: Optional[int] = None) -> _Connection:
        seqr = self.sequencers.setdefault(doc_id, DocumentSequencer(doc_id))
        if client_id is None:
            client_id = self._next_client_id.get(doc_id, 1)
        self._next_client_id[doc_id] = max(
            self._next_client_id.get(doc_id, 1), client_id + 1
        )
        if any(
            c.client_id == client_id for c in self.connections.get(doc_id, [])
        ):
            raise ValueError(
                f"client {client_id} already connected to {doc_id}"
            )
        conn = _Connection(self, doc_id, client_id)
        join = seqr.join(client_id)
        conn.join_seq = join.sequence_number
        self.connections.setdefault(doc_id, []).append(conn)
        self._deliver(doc_id, join)
        return conn

    def _leave(self, doc_id: str, client_id: int) -> None:
        conns = self.connections.get(doc_id, [])
        self.connections[doc_id] = [c for c in conns if c.client_id != client_id]
        seqr = self.sequencers[doc_id]
        leave = seqr.leave(client_id)
        if leave is not None:
            self._deliver(doc_id, leave)

    # ------------------------------------------------------- sequencing

    def _submit(self, doc_id: str, client_id: int, msg: DocumentMessage) -> None:
        seqr = self.sequencers[doc_id]
        result = seqr.sequence(client_id, msg)
        if isinstance(result, NackMessage):
            for conn in self.connections.get(doc_id, []):
                if conn.client_id == client_id and conn.nack_listener:
                    conn.nack_listener(result)
            return
        self._deliver(doc_id, result)

    def _deliver(self, doc_id: str, msg: SequencedMessage) -> None:
        self.op_log.setdefault(doc_id, []).append(msg)
        if self.deferred:
            self._doc_queue.setdefault(doc_id, deque()).append(msg)
        else:
            self._fan_out(doc_id, msg)

    def _fan_out(self, doc_id: str, msg: SequencedMessage) -> None:
        for conn in list(self.connections.get(doc_id, [])):
            if conn.connected and msg.sequence_number > conn.join_seq:
                conn._dispatch(msg)

    # --------------------------------------------------- deferred drain

    def pending_count(self, doc_id: str) -> int:
        return len(self._doc_queue.get(doc_id, ()))

    def process_one(self, doc_id: str) -> bool:
        q = self._doc_queue.get(doc_id)
        if not q:
            return False
        self._fan_out(doc_id, q.popleft())
        return True

    def process_all(self, doc_id: Optional[str] = None) -> int:
        """Drain queued deliveries; returns number delivered.

        Re-lists the queue dict each pass so documents created by
        listeners mid-drain are picked up too."""
        n = 0
        progress = True
        while progress:
            progress = False
            for d in [doc_id] if doc_id else list(self._doc_queue):
                while self.process_one(d):
                    n += 1
                    progress = True
        return n

    # ----------------------------------------------------------- catchup

    def ops_from(self, doc_id: str, from_seq: int,
                 to_seq: Optional[int] = None) -> List[SequencedMessage]:
        """Durable op log read (the scriptorium/deltaStorage role);
        `to_seq` bounds the range (the ranged catch-up read)."""
        return [
            m for m in self.op_log.get(doc_id, [])
            if m.sequence_number > from_seq
            and (to_seq is None or m.sequence_number <= to_seq)
        ]

    def set_summary(self, doc_id: str, seq: int, wire: str) -> None:
        """Record a summary covering ops [1..seq] (the storage-less
        orderer's minimal summary shelf — the embedding app or a
        summarizer agent writes it; `catchup` serves it)."""
        self._summaries[doc_id] = (int(seq), wire)

    def catchup(self, doc_id: str, from_seq: int = 0) -> dict:
        """Nearest summary + op tail (the `LocalServer.catchup` shape,
        so both in-proc services answer a join identically)."""
        seq, wire = self._summaries.get(doc_id, (0, None))
        return {
            "summary": wire,
            "summarySeq": seq,
            "ops": self.ops_from(doc_id, max(from_seq, seq)),
        }
