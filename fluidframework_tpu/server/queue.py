"""Ordering-log seam + partition leases: multi-node coordination.

Two abstractions the reference keeps in `services-core`
(server/routerlicious/packages/services-core/src/queue.ts `IProducer`/
`IConsumer`) and ZooKeeper (partition ownership for the Kafka
consumers, SURVEY.md §2.5 ⚙️):

- **Producer/consumer seam** — lambdas talk to topics only through
  `Producer`/`Consumer`; the in-proc journal (`server.log.MessageLog`)
  is one backend, and `SharedFileTopic` is a CROSS-PROCESS backend
  (multi-writer appends under an OS file lock, consumers tail from a
  checkpointed offset), so two server processes share one ordering
  log the way two routerlicious pods share a Kafka cluster.
- **Lease manager** — partition ownership with expiry-based failover
  (the zookeeper role): a worker acquires leases over document-space
  partitions, renews them while alive, and a peer takes over any
  lease that expires (crashed owner), resuming from the dead worker's
  checkpointed consumer offset.

`tools/partition_worker_main.py` runs a sequencer worker over this
seam; `tests/test_partition_leases.py` kills one of two workers and
proves the survivor takes over its partitions exactly-once.
"""

from __future__ import annotations

import contextlib
import errno
import json
import os
import random
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, \
    Protocol, Tuple


class FencedError(RuntimeError):
    """A write carried a fencing token older than (or tied with but not
    bound to) the one the target has already accepted: the writer is a
    deposed owner and its write must not land (the zookeeper/Kafka
    fencing contract — exactly-once rests on deposed owners being
    REJECTED at the write path, not merely asked to stand down)."""


def _check_fence(cur_fence: int, cur_owner: Optional[str],
                 fence: int, owner: Optional[str], what: str) -> None:
    """THE fence-gate rule, shared by every fenced write path: reject a
    fence below the highest accepted, or an equal fence from a
    different owner than the one it first bound to (ties broken by
    first binder — the guard for the pathological same-fence split)."""
    if fence < cur_fence or (fence == cur_fence and cur_owner is not None
                             and owner != cur_owner):
        raise FencedError(
            f"{what}: fence {fence} ({owner}) rejected; already bound "
            f"to {cur_fence} ({cur_owner})"
        )


@contextlib.contextmanager
def flock_exclusive(f, lock_timeout_s: Optional[float],
                    path: str) -> Iterator[None]:
    """Exclusive flock on `f` for one append critical section. With a
    timeout, acquisition is bounded (LOCK_NB polling) so a takeover
    successor never wedges behind a stalled — e.g. SIGSTOPped —
    writer's lock: it times out, has the zombie killed (the
    supervisor's stale-heartbeat role), and retries. Shared by every
    topic flavor so the takeover protocol cannot fork."""
    import fcntl

    if lock_timeout_s is None:
        fcntl.flock(f.fileno(), fcntl.LOCK_EX)
    else:
        deadline = time.time() + lock_timeout_s
        while True:
            try:
                fcntl.flock(f.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError:
                if time.time() > deadline:
                    raise TimeoutError(
                        f"append lock on {path} held past "
                        f"{lock_timeout_s}s"
                    )
                time.sleep(0.005)
    try:
        yield
    finally:
        fcntl.flock(f.fileno(), fcntl.LOCK_UN)


# ---------------------------------------------------------------------------
# storage-fault seam (the chaos harness's disk fault class)
# ---------------------------------------------------------------------------

# Path of a JSON fault-spec file; when set, every durable write path
# (topic append, checkpoint save) consults it right before fsync. The
# chaos harness points CHILD processes at a spec it toggles mid-run:
#   {"mode": "enospc"}                -> the write raises OSError(ENOSPC)
#   {"mode": "stall", "stall_s": S}   -> the fsync stalls S seconds
#   optional "kinds": ["topic", ...]  -> restrict to those write paths
# Unset (production) the check is a single dict lookup.
DISK_FAULT_ENV = "FLUID_DISK_FAULT"


def check_disk_fault(kind: str) -> None:
    """Injection point for the storage failure classes a real deli farm
    meets (volume full, device write stall): called with the write
    about to go durable, so an injected ENOSPC aborts BEFORE bytes land
    — exactly where the real error surfaces — and a stall sits where a
    slow fsync would."""
    spec_path = os.environ.get(DISK_FAULT_ENV)
    if not spec_path:
        return
    try:
        with open(spec_path) as f:
            spec = json.load(f)
    except (OSError, ValueError):
        return  # no/garbled spec: no fault
    if not isinstance(spec, dict):
        return
    kinds = spec.get("kinds")
    if kinds and kind not in kinds:
        return
    mode = spec.get("mode")
    if mode == "enospc":
        raise OSError(errno.ENOSPC, f"injected ENOSPC on {kind} write")
    if mode == "stall":
        time.sleep(float(spec.get("stall_s", 0.25)))


def retry_durable(fn: Callable[[], Any], attempts: int = 8,
                  base_s: float = 0.02, cap_s: float = 0.5,
                  on_retry: Optional[Callable[[int, BaseException, float],
                                              None]] = None) -> Any:
    """Bounded-retry jittered backoff for DURABLE writes (topic append,
    checkpoint save) under transient storage failure — ENOSPC, EIO, a
    stalled volume. Graceful degradation, not masking: `on_retry` fires
    per attempt so the caller can flag itself degraded (heartbeat,
    metrics) while it waits, and once the budget is spent the error
    surfaces (hard-fail — the supervisor's restart is the next line of
    defense). `FencedError` is a RuntimeError, not an OSError, so a
    deposed writer is never retried back to life."""
    k = 0
    while True:
        try:
            return fn()
        except OSError as exc:
            if k >= attempts - 1:
                raise
            delay = min(cap_s, base_s * (1 << k))
            delay *= 0.5 + random.random() * 0.5  # jitter: desync peers
            if on_retry is not None:
                on_retry(k, exc, delay)
            time.sleep(delay)
            k += 1


class Producer(Protocol):
    """services-core/src/queue.ts IProducer role."""

    def send(self, message: Any) -> int: ...


class Consumer(Protocol):
    """services-core/src/queue.ts IConsumer role: an offset-owning
    reader whose position is the caller's checkpoint state."""

    offset: int

    def poll(self, max_count: Optional[int] = None) -> List[Any]: ...


class JournalProducer:
    """Producer over an in-proc `server.log.LogTopic`."""

    def __init__(self, topic):
        self.topic = topic

    def send(self, message: Any) -> int:
        return self.topic.append(message)


class JournalConsumer:
    """Consumer over an in-proc `server.log.LogTopic`."""

    def __init__(self, topic, offset: int = 0):
        self.topic = topic
        self.offset = offset

    def poll(self, max_count: Optional[int] = None) -> List[Any]:
        msgs = self.topic.read(self.offset, max_count)
        self.offset += len(msgs)
        return msgs


def fsync_file(f, kind: str = "topic") -> None:
    """One counted fsync: every durable-write fsync on the hot path
    routes through here so `topic_fsyncs_total{kind=}` reports the
    per-record durability floor the fused-hop work attacks (the
    bench's fsyncs-per-record evidence)."""
    os.fsync(f.fileno())
    from ..utils.metrics import get_registry

    get_registry().counter("topic_fsyncs_total", kind=kind).inc()


class SharedFileTopic:
    """A cross-process topic over one JSONL file.

    Appends take an OS file lock (multi-writer safe); consumers tail
    the file from a LINE offset, re-reading anything new on each poll
    — the minimal faithful form of a shared Kafka partition. Entries
    are plain JSON values.

    Robustness contract (the chaos-harness substrate):

    - **Torn tail** — a reader never consumes a final line lacking its
      trailing newline (an append in progress, or a writer that died
      mid-write); the line is re-read complete on the next poll. The
      next append SEALS a crash-torn tail with a newline first, so the
      junk remnant becomes one unparseable line instead of corrupting
      the following record; readers skip (but still count) lines that
      fail to parse.
    - **Fencing** — appends may carry a ``fence`` token (+ owner). The
      topic remembers the highest accepted (fence, owner) in a sidecar
      file, updated under the same append lock; a lower fence — or an
      equal fence from a different owner than the one it first bound
      to — raises :class:`FencedError` and writes nothing. This is
      what makes a deposed lease holder's post-takeover writes
      *demonstrably rejected* rather than merely discouraged.
    """

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if not os.path.exists(path):
            with open(path, "a"):
                pass
        # Doorbell producer state: cached write fds into registered
        # consumer bells (re-listed per ring — see _ring_doorbells).
        self._bell_wfds: Dict[str, int] = {}

    def __del__(self):
        # Short-lived topic objects (probes, one-shot appenders) must
        # not leak the ring fds they cached.
        for fd in (getattr(self, "_bell_wfds", None) or {}).values():
            try:
                os.close(fd)
            except Exception:
                pass

    # -------------------------------------------------------- doorbells

    def _ring_doorbells(self) -> None:
        """Wake every doorbell-registered consumer of this topic (one
        byte per bell). Costs a single failed stat when no consumer
        ever registered; rings AFTER the append went durable, outside
        the append lock, so waking consumers never contend with the
        writer. Purely advisory — any failure here degrades to the
        consumer's bounded-timeout poll."""
        d = self.path + ".bells"
        try:
            names = {n for n in os.listdir(d) if not n.startswith(".")}
        except OSError:
            return  # no consumer ever registered: one failed syscall
        # Re-list per ring rather than caching on the dir mtime: write
        # fds are still cached (the per-ring cost is one listdir next
        # to an append that already paid open+flock+fsync), but
        # DISCOVERY never trusts directory attributes — network/
        # passthrough filesystems (v9fs CI containers) cache those
        # across processes, and a bell registered after the first scan
        # would stay invisible to the ringer forever.
        cache = self._bell_wfds
        for name in list(cache):
            if name not in names:
                try:
                    os.close(cache.pop(name))
                except OSError:
                    cache.pop(name, None)
        for name in names:
            if name in cache:
                continue
            try:
                cache[name] = os.open(
                    os.path.join(d, name),
                    os.O_WRONLY | os.O_NONBLOCK,
                )
            except OSError as exc:
                # ENXIO alone means "no live reader" — the consumer
                # died (its O_RDWR fd vanished with it); reap the bell
                # so a churned farm can't accumulate dead FIFOs. Any
                # OTHER error (EMFILE, EACCES...) is a PRODUCER-side
                # problem: unlinking would permanently sever a live
                # consumer with no re-registration path — leave it for
                # a later ring to open.
                if exc.errno == errno.ENXIO:
                    try:
                        os.unlink(os.path.join(d, name))
                    except OSError:
                        pass
        for name, fd in list(cache.items()):
            try:
                os.write(fd, b"!")
            except BlockingIOError:
                pass  # pipe full: a wake is already pending
            except OSError:
                # Reader went away since we opened (EPIPE): drop the
                # fd; the next ring's listing reaps the file.
                try:
                    os.close(fd)
                except OSError:
                    pass
                cache.pop(name, None)

    # ------------------------------------------------------------ fence

    def _fence_path(self) -> str:
        return self.path + ".fence"

    def latest_fence(self) -> Tuple[int, Optional[str]]:
        """The highest (fence, owner) this topic has accepted."""
        try:
            with open(self._fence_path()) as f:
                d = json.load(f)
            return int(d.get("fence", 0)), d.get("owner")
        except (OSError, ValueError):
            return 0, None

    def _gate_fence(self, fence: Optional[int],
                    owner: Optional[str]) -> None:
        """Check-and-advance the fence sidecar. Caller holds the
        append lock, so read-modify-write is race-free."""
        if fence is None:
            return
        cur, cur_owner = self.latest_fence()
        _check_fence(cur, cur_owner, fence, owner,
                     os.path.basename(self.path))
        if fence > cur or cur_owner is None:
            tmp = self._fence_path() + f".tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"fence": fence, "owner": owner}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._fence_path())

    # ----------------------------------------------------------- append

    def append(self, message: Any, fence: Optional[int] = None,
               owner: Optional[str] = None) -> int:
        return self.append_many([message], fence=fence, owner=owner)

    def append_many(self, messages: List[Any],
                    fence: Optional[int] = None,
                    owner: Optional[str] = None,
                    lock_timeout_s: Optional[float] = None,
                    fsync: bool = True) -> int:
        """Append a batch under the OS lock; returns the payload bytes
        written (the byte-based checkpoint-cadence signal).

        ``fsync=False`` skips the data fsync: the append is ordered
        and torn-tail-safe (readers never consume an incomplete line)
        but not crash-durable — for DERIVED feeds whose records are
        deterministically regenerable from an upstream durable topic
        (the fused hop's broadcast leg), where exactly-once recovery
        re-emits anything the page cache lost."""
        # An empty batch still gates: a deposed owner must learn it is
        # deposed even when it has nothing to write.
        payload = b"".join(
            json.dumps(m).encode() + b"\n" for m in messages
        )
        with open(self.path, "r+b") as f:
            with flock_exclusive(f, lock_timeout_s, self.path):
                self._gate_fence(fence, owner)
                f.seek(0, os.SEEK_END)
                pos = f.tell()
                if pos > 0:
                    f.seek(pos - 1)
                    if f.read(1) != b"\n":
                        # A writer died mid-append: seal its torn line
                        # so our record starts on a fresh line and the
                        # remnant parses (and is skipped) as one junk
                        # line.
                        f.write(b"\n")
                check_disk_fault("topic")
                f.write(payload)
                f.flush()
                if fsync:
                    fsync_file(f, "topic")
        if messages:
            self._ring_doorbells()
        return len(payload)

    # ------------------------------------------------------------- read

    def read_entries(self, offset: int,
                     max_count: Optional[int] = None
                     ) -> Tuple[List[Tuple[int, Any]], int]:
        """Parse lines from line-index `offset`. Returns
        ``([(line_index, value), ...], next_offset)``.

        A final line without a trailing newline is NOT consumed (it is
        an append in progress — complete on the next poll); a complete
        line that fails to parse (sealed torn remnant) is skipped but
        still counted, so offsets stay stable across all readers.

        `max_count` caps the PARSED entries taken (micro-batch bound:
        consumers yield between batches instead of swallowing a whole
        backlog); next_offset then resumes right after the last entry
        taken, with skipped junk lines staying counted."""
        with open(self.path, "rb") as f:
            data = f.read()
        if not data:
            return [], offset
        lines = data.split(b"\n")
        # Drop the final element either way: it is the '' split
        # artifact of a newline-terminated file, or a torn
        # (in-progress) line that must be re-read complete next poll.
        lines.pop()
        out: List[Tuple[int, Any]] = []
        for i in range(offset, len(lines)):
            if max_count is not None and len(out) >= max_count:
                return out, (out[-1][0] + 1 if out else offset)
            line = lines[i].strip()
            if not line:
                continue
            try:
                out.append((i, json.loads(line)))
            except ValueError:
                continue  # sealed junk from a crashed writer
        return out, max(offset, len(lines))

    def read_from(self, offset: int) -> List[Any]:
        return [v for _, v in self.read_entries(offset)[0]]


# ---------------------------------------------------------------------------
# topic doorbells (event-driven new-records wakeup)
# ---------------------------------------------------------------------------

# Kill switch: FLUID_DOORBELL=0 keeps every consumer on the pure poll
# loop (the latency bench's baseline variant; also the escape hatch on
# a platform where FIFOs misbehave).
DOORBELL_ENV = "FLUID_DOORBELL"

_bell_seq = 0


def doorbells_enabled() -> bool:
    """Whether event-driven topic wakeups are available AND wanted.
    Doorbells are advisory only — with them off (or unsupported: no
    ``os.mkfifo``), every consumer falls back to the bounded-timeout
    poll loop it always had, so fencing/torn-read semantics never
    depend on this answer."""
    return (os.environ.get(DOORBELL_ENV, "1").lower()
            not in ("0", "off", "no")
            and hasattr(os, "mkfifo"))


class TopicDoorbell:
    """One consumer's wakeup line for one topic.

    A FIFO under ``<topic path>.bells/``: `append_many` writes one
    byte into every registered bell after its records are durable, and
    the consumer waits on its bell with a BOUNDED timeout — so the
    idle-poll interval stack that dominates low-load end-to-end
    latency collapses to an event wake, while the timeout keeps poll
    as the correctness fallback (a bell rung between the consumer's
    empty poll and its wait, a lost FIFO, a disabled platform: all
    degrade to exactly the old behavior).

    The consumer holds the FIFO open O_RDWR (nonblocking): the
    always-present reader means a producer's O_WRONLY|O_NONBLOCK open
    succeeds while the consumer lives (ENXIO = consumer died, the
    producer garbage-collects the bell), and the always-present writer
    means the read end never signals EOF-readable to select — no busy
    wake. The FIFO is created under a dot-name and renamed into place
    only after the read end is open, so a producer can never observe a
    bell without a live reader and wrongly reap it."""

    def __init__(self, topic_path: str):
        global _bell_seq
        self.dir = topic_path + ".bells"
        os.makedirs(self.dir, exist_ok=True)
        _bell_seq += 1
        name = f"{os.getpid()}-{_bell_seq}.bell"
        tmp = os.path.join(self.dir, f".{name}.tmp")
        self.path = os.path.join(self.dir, name)
        os.mkfifo(tmp)
        self._fd = os.open(tmp, os.O_RDWR | os.O_NONBLOCK)
        os.rename(tmp, self.path)

    def fileno(self) -> int:
        return self._fd

    def drain(self) -> bool:
        """Consume pending ring bytes; True iff any were pending."""
        rang = False
        try:
            while os.read(self._fd, 4096):
                rang = True
        except (BlockingIOError, OSError):
            pass
        return rang

    def wait(self, timeout_s: float) -> bool:
        return wait_doorbells([self], timeout_s)

    def close(self) -> None:
        if self._fd is not None:
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None
        try:
            os.unlink(self.path)
        except OSError:
            pass


def wait_doorbells(bells: List["TopicDoorbell"],
                   timeout_s: float) -> bool:
    """Sleep until ANY of `bells` rings or `timeout_s` elapses (the
    poll fallback); returns whether a ring woke us. Rings that arrived
    while the consumer was busy processing are still pending in the
    pipe, so the next wait returns immediately — a wakeup is never
    lost, only (harmlessly) early."""
    import select

    fds = [b._fd for b in bells if b is not None and b._fd is not None]
    if not fds:
        time.sleep(timeout_s)
        return False
    try:
        ready, _, _ = select.select(fds, [], [], timeout_s)
    except OSError:
        time.sleep(timeout_s)
        return False
    if not ready:
        return False
    for b in bells:
        if b is not None and b._fd in ready:
            b.drain()
    return True


class TailReader:
    """Incremental reader over a `SharedFileTopic`: remembers the byte
    position of the last fully-consumed line, so each poll reads only
    NEW bytes instead of re-reading (and re-splitting) the whole file —
    `read_entries` is O(file) per call, which makes a long-lived
    consumer O(file²) over its lifetime; the lambda roles and the
    pipeline bench tail topics through this instead.

    Same robustness contract as `read_entries`: a final line without
    its trailing newline is not consumed (byte position stays before
    it), junk lines are skipped but still counted, and line indices
    (`next_line`) stay identical to `read_entries` offsets — so
    checkpointed line offsets and `inOff` bookkeeping are unchanged."""

    def __init__(self, topic: SharedFileTopic, line_offset: int = 0):
        self.topic = topic
        self.next_line = line_offset
        self._pos = 0
        # Lines the caller's offset is AHEAD of the file (a checkpoint
        # taken against a longer topic): consumed silently as they
        # appear, never delivered — matching read_entries(offset),
        # which returns nothing below the requested offset.
        self._behind = 0
        if line_offset > 0:
            # One O(file) skip to translate the line offset into a byte
            # position; everything after is incremental.
            with open(topic.path, "rb") as f:
                data = f.read()
            lines = data.split(b"\n")
            lines.pop()
            take = min(line_offset, len(lines))
            self._pos = sum(len(l) + 1 for l in lines[:take])
            self._behind = line_offset - take

    def poll(self, max_count: Optional[int] = None
             ) -> List[Tuple[int, Any]]:
        """Parse up to `max_count` new complete entries; returns
        [(line_index, value), ...] and advances past them."""
        with open(self.topic.path, "rb") as f:
            f.seek(self._pos)
            data = f.read()
        if not data:
            return []
        end = data.rfind(b"\n")
        if end < 0:
            return []  # torn tail only: re-read complete next poll
        lines = data[:end].split(b"\n")
        out: List[Tuple[int, Any]] = []
        pos = self._pos
        line_no = self.next_line
        loads = json.loads
        for raw in lines:
            if self._behind:
                # Below the requested offset: swallow without delivery
                # (next_line already accounts for these lines).
                self._behind -= 1
                pos += len(raw) + 1
                continue
            if max_count is not None and len(out) >= max_count:
                break
            pos += len(raw) + 1
            line = raw.strip()
            if line:
                try:
                    out.append((line_no, loads(line)))
                except ValueError:
                    pass  # sealed junk from a crashed writer
            line_no += 1
        self._pos = pos
        self.next_line = line_no
        return out


class SharedFileProducer:
    def __init__(self, topic: SharedFileTopic):
        self.topic = topic

    def send(self, message: Any) -> int:
        self.topic.append(message)
        return -1  # offsets are consumer-side for file topics


class SharedFileConsumer:
    def __init__(self, topic: SharedFileTopic, offset: int = 0):
        self.topic = topic
        self.offset = offset

    def poll(self, max_count: Optional[int] = None) -> List[Any]:
        # The cap threads into the read itself (micro-batch bound);
        # max_count=0 takes nothing and leaves the offset alone.
        entries, next_offset = self.topic.read_entries(self.offset, max_count)
        self.offset = next_offset
        return [v for _, v in entries]


# ---------------------------------------------------------------------------
# Lease manager (zookeeper role)
# ---------------------------------------------------------------------------


class _ClaimBusy(Exception):
    """Another worker holds the arbitration claim right now."""


class LeaseManager:
    """Expiry-based partition leases over a shared directory.

    A lease is a JSON file `<dir>/<partition>.lease` holding
    ``{"owner", "expires", "fence"}``. All mutations — acquire, renew,
    release — are arbitrated under an ``O_CREAT|O_EXCL`` claim file
    (`<partition>.lease.claim`): exactly one worker can create it, so
    the read-decide-write sequence is a critical section and two
    workers racing for an expired lease can no longer both "win" with
    the same fence (the round-5 ADVICE.md medium race — the old
    read-back arbitration let racer A read back before racer B renamed
    over it).

    Fences are allocated from a monotonic counter file
    (`<partition>.lease.fencecounter`) updated only inside the claim,
    so every ownership change gets a strictly larger token even if the
    lease file itself is deleted. Claimant liveness is carried by a
    kernel flock held on the claim fd for the whole critical section:
    a crashed claimant's claim is broken immediately (its lock died
    with it), while a live-but-stalled claimant's claim is never
    broken — so two arbitrators can't coexist and fences can't split.
    Belt-and-braces, the WRITE path still enforces tokens: fenced
    topics/checkpoints bind each fence value to the first owner that
    uses it and reject any other (`SharedFileTopic` appends /
    `FencedCheckpointStore.save` raise `FencedError`).
    """

    def __init__(self, directory: str, owner: str, ttl_s: float = 2.0,
                 claim_ttl_s: float = 1.0,
                 fence_scope: Optional[str] = None):
        """`fence_scope` names a SHARED monotonic fence counter all of
        this manager's partitions allocate from (file
        ``<dir>/<scope>.fencecounter``) instead of the default
        per-partition counter. The elastic fabric needs it: after a
        range split/merge the successor binds its fence on the
        PREDECESSOR's topics, so fences must be comparable across
        lease keys — one fabric-wide counter makes every ownership
        change anywhere strictly newer than everything before it."""
        self.dir = directory
        self.owner = owner
        self.ttl_s = ttl_s
        self.claim_ttl_s = claim_ttl_s
        self.fence_scope = fence_scope
        os.makedirs(directory, exist_ok=True)

    def _path(self, partition: str) -> str:
        return os.path.join(self.dir, f"{partition}.lease")

    def _read(self, partition: str) -> Optional[dict]:
        try:
            with open(self._path(partition)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _write(self, partition: str, lease: dict) -> None:
        tmp = self._path(partition) + f".tmp.{self.owner}.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(lease, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path(partition))

    # -------------------------------------------------------- the claim

    @contextlib.contextmanager
    def _claim(self, partition: str,
               timeout_s: Optional[float] = None) -> Iterator[None]:
        """O_CREAT|O_EXCL mutual exclusion for lease arbitration.
        Raises `_ClaimBusy` if the claim stays foreign past
        `timeout_s` (default: claim_ttl_s).

        Holder liveness is probed through a kernel flock the claimant
        holds on its claim fd for the whole critical section: a dead
        claimant's lock vanishes with the process (so its claim is
        safely broken by whichever single breaker wins the lock), a
        live-but-stopped claimant's lock persists (so its claim is
        NEVER broken and the two-winners split cannot happen, unlike
        mtime-staleness breaking). It also makes release trivially
        safe: our claim can only have been broken if this process
        died, so the final unlink is always our own file."""
        import fcntl

        path = self._path(partition) + ".claim"
        deadline = time.time() + (
            self.claim_ttl_s if timeout_s is None else timeout_s
        )
        fd: Optional[int] = None
        while True:
            try:
                fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR)
            except FileExistsError:
                try:
                    probe = os.open(path, os.O_RDWR)
                except OSError:
                    continue  # released between EEXIST and open; retry
                try:
                    try:
                        fcntl.flock(probe, fcntl.LOCK_EX | fcntl.LOCK_NB)
                    except OSError:
                        # A live holder (possibly stopped) — wait.
                        if time.time() > deadline:
                            raise _ClaimBusy(partition)
                        time.sleep(0.002)
                        continue
                    # Lock acquired: the holder died before releasing.
                    # Racing breakers serialize on this lock, and while
                    # we hold it no new claimant can unlink the path,
                    # so break it only if it still names our inode.
                    try:
                        if os.stat(path).st_ino == os.fstat(probe).st_ino:
                            os.unlink(path)
                    except OSError:
                        pass
                finally:
                    os.close(probe)
                continue
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                # A prober grabbed the lock on our just-created claim
                # before we could (and will break it as holderless).
                # Stand down and retry.
                os.close(fd)
                fd = None
                if time.time() > deadline:
                    raise _ClaimBusy(partition)
                continue
            # Close the create→flock window: a breaker that saw our
            # claim unlocked may have unlinked it; if the path no
            # longer names our inode, stand down and retry.
            try:
                same = os.stat(path).st_ino == os.fstat(fd).st_ino
            except OSError:
                same = False
            if not same:
                os.close(fd)
                fd = None
                continue
            break
        try:
            os.write(fd, f"{self.owner} {os.getpid()}".encode())
            yield
        finally:
            try:
                os.unlink(path)
            except OSError:
                pass
            os.close(fd)  # releases the liveness lock

    def _next_fence(self, partition: str, cur: Optional[dict]) -> int:
        """Allocate the next fencing token from the monotonic counter
        (called only inside the claim). max() with the lease's own
        fence heals a lost/stale counter file.

        A scoped (shared) counter is serialized by its own flock: the
        per-partition claim no longer covers it, and two DIFFERENT
        keys' claims racing the read-modify-write could mint TIED
        fences — which the write-path tie rule would then reject for
        whichever owner binds second, livelocking a legitimate
        successor (the split-children race)."""
        if self.fence_scope is not None:
            cpath = os.path.join(
                self.dir, f"{self.fence_scope}.fencecounter"
            )
            lock = open(cpath + ".lock", "a+")
        else:
            cpath = self._path(partition) + ".fencecounter"
            lock = None
        try:
            if lock is not None:
                with flock_exclusive(lock, None, cpath):
                    return self._bump_fence(cpath, cur)
            return self._bump_fence(cpath, cur)
        finally:
            if lock is not None:
                lock.close()

    def _bump_fence(self, cpath: str, cur: Optional[dict]) -> int:
        try:
            with open(cpath) as f:
                counter = int(f.read().strip() or 0)
        except (OSError, ValueError):
            counter = 0
        fence = max(counter, int(cur.get("fence", 0)) if cur else 0) + 1
        tmp = cpath + f".tmp.{self.owner}.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(str(fence))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, cpath)
        return fence

    # ------------------------------------------------------- operations

    def try_acquire(self, partition: str,
                    now: Optional[float] = None) -> Optional[int]:
        """Acquire `partition` if unowned, expired, or already ours.
        Returns the fencing token on success, None otherwise."""
        now = time.time() if now is None else now
        try:
            with self._claim(partition):
                cur = self._read(partition)
                if cur is not None:
                    if cur.get("owner") == self.owner:
                        return int(cur.get("fence", 0))
                    if float(cur.get("expires", 0)) > now:
                        return None  # live foreign lease
                fence = self._next_fence(partition, cur)
                self._write(partition, {
                    "owner": self.owner, "expires": now + self.ttl_s,
                    "fence": fence,
                })
                return fence
        except _ClaimBusy:
            return None  # a peer is arbitrating; try again next sweep

    def renew(self, partition: str,
              now: Optional[float] = None) -> bool:
        now = time.time() if now is None else now
        try:
            with self._claim(partition):
                cur = self._read(partition)
                if cur is None or cur.get("owner") != self.owner:
                    return False  # deposed
                self._write(partition, {**cur, "expires": now + self.ttl_s})
                return True
        except _ClaimBusy:
            # Can't prove ownership right now; claiming failure is the
            # safe answer (the worker stands down, fencing protects
            # anything it had in flight).
            return False

    def release(self, partition: str) -> None:
        try:
            with self._claim(partition):
                cur = self._read(partition)
                if cur is not None and cur.get("owner") == self.owner:
                    self._write(partition, {**cur, "expires": 0})
        except _ClaimBusy:
            pass  # lease will expire on its own

    def owner_of(self, partition: str,
                 now: Optional[float] = None) -> Optional[str]:
        info = self.lease_info(partition, now)
        return info["owner"] if info is not None else None

    def lease_info(self, partition: str,
                   now: Optional[float] = None) -> Optional[dict]:
        """The live lease as ``{"owner", "fence", "expires"}`` (None if
        unowned/expired). The fence is what lets a READER tell a stale
        pre-takeover (or pre-split) owner from the live one — owner
        strings alone cannot, since a restarted worker reuses its
        slot name while the fence strictly advances."""
        now = time.time() if now is None else now
        cur = self._read(partition)
        if cur is None or float(cur.get("expires", 0)) <= now:
            return None
        return {"owner": cur.get("owner"),
                "fence": int(cur.get("fence", 0)),
                "expires": float(cur.get("expires", 0))}


class FencedCheckpointStore:
    """Durable lambda checkpoints whose writes REJECT deposed owners.

    The reference's deli checkpoints to Mongo with the partition
    epoch as the fencing token; here each key is a JSON file
    ``{"fence", "owner", "state"}`` and `save` is a read-gate-write
    critical section under an OS file lock. A writer carrying a fence
    lower than the stored one — or an equal fence under a different
    owner than the one that first bound it — gets `FencedError`, so a
    deposed lease holder can never roll a successor's checkpoint back
    (the exactly-once recovery contract of ISSUE round 1).
    """

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, f"{key}.ckpt.json")

    def load(self, key: str) -> Optional[dict]:
        """The checkpoint envelope ({"fence", "owner", "state"}) or
        None."""
        try:
            with open(self._path(key)) as f:
                d = json.load(f)
            return d if isinstance(d, dict) and "state" in d else None
        except (OSError, ValueError):
            return None

    def save(self, key: str, state: Any, fence: int,
             owner: Optional[str] = None,
             lock_timeout_s: Optional[float] = None) -> int:
        """Fenced write; returns the serialized envelope size in bytes
        (the checkpoint-bytes metric's source)."""
        import fcntl

        lock_path = self._path(key) + ".lock"
        with open(lock_path, "a+") as lk:
            if lock_timeout_s is None:
                fcntl.flock(lk.fileno(), fcntl.LOCK_EX)
            else:
                deadline = time.time() + lock_timeout_s
                while True:
                    try:
                        fcntl.flock(
                            lk.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB
                        )
                        break
                    except OSError:
                        if time.time() > deadline:
                            raise TimeoutError(
                                f"checkpoint lock {key!r} held past "
                                f"{lock_timeout_s}s"
                            )
                        time.sleep(0.005)
            try:
                cur = self.load(key)
                if cur is not None:
                    _check_fence(
                        int(cur.get("fence", 0)), cur.get("owner"),
                        fence, owner, f"checkpoint {key!r}",
                    )
                tmp = self._path(key) + f".tmp.{os.getpid()}"
                payload = json.dumps(
                    {"fence": fence, "owner": owner, "state": state}
                )
                check_disk_fault("checkpoint")
                with open(tmp, "w") as f:
                    f.write(payload)
                    f.flush()
                    fsync_file(f, "checkpoint")
                os.replace(tmp, self._path(key))
            finally:
                fcntl.flock(lk.fileno(), fcntl.LOCK_UN)
        return len(payload)


HASH_SPACE = 1 << 32  # the document hash ring [0, 2^32)


def doc_hash(doc_id: str) -> int:
    """A document's stable position on the hash ring — the single
    hashing rule both placement schemes derive from (modulo-N
    `partition_of`, and the elastic hash-range leases)."""
    import hashlib

    h = hashlib.sha256(doc_id.encode()).digest()
    return int.from_bytes(h[:4], "big")


def partition_of(doc_id: str, n_partitions: int) -> int:
    """Stable document-space partitioning (the Kafka partition-by-doc
    role, lambdas-driver/src/document-router)."""
    return doc_hash(doc_id) % n_partitions


def range_id(lo: int, hi: int, epoch: Optional[int] = None) -> str:
    """THE range naming rule: the half-open hash range ``[lo, hi)``
    born at `epoch` is ``r{lo:08x}-{hi:08x}[-e{epoch}]`` — lease keys,
    topic names and topology entries all derive from this one function
    (the elastic twin of `partition_suffix`), so a range's identities
    can never drift. The epoch tag (absent only for the bootstrap
    topology) makes every INCARNATION of a range a fresh identity: a
    merge that recreates an ancestor's exact bounds must NOT inherit
    the ancestor's topics or checkpoint key — its state comes from its
    immediate predecessors, not from a dead ancestor's stale
    checkpoint."""
    base = f"r{lo:08x}-{hi:08x}"
    return base if epoch is None else f"{base}-e{int(epoch)}"


class RangeLeaseStore:
    """Hash-range (virtual-partition) leases + the fenced topology
    epoch record — the coordination substrate of the ELASTIC fabric.

    Two pieces, both arbitrated by the same ``O_CREAT|O_EXCL`` claim
    machinery as the classic partition leases:

    - **Range leases** — a `LeaseManager` whose keys are range lease
      names (``deli-r{lo:08x}-{hi:08x}``) and whose fencing tokens
      come from ONE fabric-wide monotonic counter (`fence_scope`), so
      a successor's fence is comparable on any predecessor's topics —
      the property a split/merge handoff rests on.
    - **Topology epochs** — ``<shared>/topology.json`` maps the live
      ranges to their topic names. Commits are fenced like checkpoints:
      a writer proposes against the epoch it READ, under the claim, and
      a concurrent commit wins the CAS — the loser re-reads and
      retries or stands down. Epochs only ever advance; every range id
      ever live stays in ``history`` so records written under epoch E
      remain readable (merged catch-up) after E+1.

    The topology shape (pure JSON, operator-readable):

    ``{"epoch": E, "ranges": [{"lo", "hi", "rid", "raw", "deltas",
    "preds": [rid, ...]}, ...], "history": [rid, ...]}``

    ``preds`` names the range(s) an entry replaced (one parent for a
    split child, two parents for a merge survivor): successors restore
    the predecessors' final fenced checkpoints sliced to their range
    and close the durable gap with the exactly-once ``inOff`` scan.
    """

    TOPOLOGY_CLAIM = "__topology__"

    def __init__(self, shared_dir: str, owner: str, ttl_s: float = 1.0,
                 claim_ttl_s: Optional[float] = None):
        self.shared_dir = shared_dir
        self.leases = LeaseManager(
            os.path.join(shared_dir, "leases"), owner, ttl_s,
            claim_ttl_s=claim_ttl_s
            if claim_ttl_s is not None else max(0.25, ttl_s / 2),
            fence_scope="__fabric__",
        )
        self.topology_path = os.path.join(shared_dir, "topology.json")

    # -------------------------------------------------------- topology

    def read_topology(self) -> Optional[dict]:
        try:
            with open(self.topology_path) as f:
                d = json.load(f)
        except (OSError, ValueError):
            return None
        if (isinstance(d, dict) and isinstance(d.get("ranges"), list)
                and isinstance(d.get("epoch"), int)):
            return d
        return None

    def _write_topology(self, topo: dict) -> None:
        tmp = self.topology_path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(topo, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.topology_path)

    def ensure_topology(self, n_ranges: int) -> dict:
        """Bootstrap epoch 1 with `n_ranges` equal hash slices (claim-
        arbitrated, idempotent — whoever loses the race adopts the
        winner's record)."""
        topo = self.read_topology()
        if topo is not None:
            return topo
        try:
            with self.leases._claim(self.TOPOLOGY_CLAIM):
                topo = self.read_topology()
                if topo is None:
                    topo = initial_topology(n_ranges)
                    self._write_topology(topo)
                return topo
        except _ClaimBusy:
            # A peer is bootstrapping right now; wait it out.
            deadline = time.time() + 10 * self.leases.ttl_s
            while time.time() < deadline:
                topo = self.read_topology()
                if topo is not None:
                    return topo
                time.sleep(0.01)
            raise RuntimeError("topology bootstrap claim never resolved")

    def commit_topology(self, topo: dict, expect_epoch: int) -> bool:
        """Fenced CAS: commit `topo` as epoch ``expect_epoch + 1`` iff
        the record still reads `expect_epoch`. Returns False on a lost
        race (the caller re-reads and reconsiders — a topology change
        is an ownership change, and two may not interleave)."""
        try:
            with self.leases._claim(self.TOPOLOGY_CLAIM):
                cur = self.read_topology()
                if cur is not None and cur["epoch"] != expect_epoch:
                    return False
                self._write_topology({**topo, "epoch": expect_epoch + 1})
                return True
        except _ClaimBusy:
            return False


def _range_entry(lo: int, hi: int, preds: Tuple[str, ...] = (),
                 epoch: Optional[int] = None) -> dict:
    """One topology entry: the range, its id, and the topic names it
    maps to (the epoch record IS the ranges→topics map)."""
    rid = range_id(lo, hi, epoch)
    return {"lo": int(lo), "hi": int(hi), "rid": rid,
            "raw": f"rawdeltas-{rid}", "deltas": f"deltas-{rid}",
            "preds": list(preds)}


def initial_topology(n_ranges: int) -> dict:
    """Epoch-1 topology: `n_ranges` equal slices of the hash ring."""
    n = int(n_ranges)
    if n < 1:
        raise ValueError(f"n_ranges must be >= 1: {n_ranges}")
    bounds = [HASH_SPACE * i // n for i in range(n)] + [HASH_SPACE]
    ranges = [_range_entry(bounds[i], bounds[i + 1]) for i in range(n)]
    return {"epoch": 1, "ranges": ranges,
            "history": [e["rid"] for e in ranges]}


def split_ranges(topo: dict, rid: str, at: Optional[int] = None) -> dict:
    """`topo` with range `rid` split into two children at hash `at`
    (default: the midpoint). Pure function — the caller commits the
    result through `RangeLeaseStore.commit_topology` (which bumps the
    epoch) AFTER writing the parent's final fenced checkpoint."""
    entry = next((e for e in topo["ranges"] if e["rid"] == rid), None)
    if entry is None:
        raise ValueError(f"range {rid!r} not in topology")
    lo, hi = entry["lo"], entry["hi"]
    at = (lo + hi) // 2 if at is None else int(at)
    if not lo < at < hi:
        raise ValueError(f"split point {at} outside ({lo}, {hi})")
    # Children are tagged with the epoch the commit will install
    # (commit CAS is against topo["epoch"], so the successor epoch is
    # known here): a fresh incarnation never collides with an
    # ancestor's topics or checkpoint key.
    born = topo["epoch"] + 1
    children = [_range_entry(lo, at, preds=(rid,), epoch=born),
                _range_entry(at, hi, preds=(rid,), epoch=born)]
    ranges = sorted(
        [e for e in topo["ranges"] if e["rid"] != rid] + children,
        key=lambda e: e["lo"],
    )
    history = list(topo.get("history", []))
    history += [c["rid"] for c in children if c["rid"] not in history]
    return {"epoch": topo["epoch"], "ranges": ranges, "history": history}


def merge_ranges(topo: dict, rid_a: str, rid_b: str) -> dict:
    """`topo` with ADJACENT ranges `rid_a`/`rid_b` merged into one
    (order-insensitive). The survivor's `preds` names both parents —
    its successor restores both final checkpoints and closes both
    durable gaps."""
    a = next((e for e in topo["ranges"] if e["rid"] == rid_a), None)
    b = next((e for e in topo["ranges"] if e["rid"] == rid_b), None)
    if a is None or b is None:
        raise ValueError(f"range {rid_a!r}/{rid_b!r} not in topology")
    if a["lo"] > b["lo"]:
        a, b = b, a
    if a["hi"] != b["lo"]:
        raise ValueError(
            f"ranges {a['rid']}/{b['rid']} are not adjacent"
        )
    merged = _range_entry(a["lo"], b["hi"],
                          preds=(a["rid"], b["rid"]),
                          epoch=topo["epoch"] + 1)
    ranges = sorted(
        [e for e in topo["ranges"]
         if e["rid"] not in (a["rid"], b["rid"])] + [merged],
        key=lambda e: e["lo"],
    )
    history = list(topo.get("history", []))
    if merged["rid"] not in history:
        history.append(merged["rid"])
    return {"epoch": topo["epoch"], "ranges": ranges, "history": history}


def range_containing(topo: dict, h: int) -> dict:
    """The topology entry whose ``[lo, hi)`` contains hash `h` (the
    ranges are contiguous and sorted, so this cannot miss)."""
    import bisect

    ranges = topo["ranges"]
    i = bisect.bisect_right([e["lo"] for e in ranges], h) - 1
    return ranges[max(0, i)]


def range_for_doc(topo: dict, doc_id: str) -> dict:
    """`(epoch, hash(doc))` routing: the live range `doc_id` maps to —
    the elastic replacement for ``doc % N``."""
    return range_containing(topo, doc_hash(doc_id))


def partition_suffix(name: str, partition: int) -> str:
    """THE partition naming rule: `name` sliced to partition `k` is
    ``{name}-p{k}`` — topics (``rawdeltas-p3`` → ``deltas-p3``), lease
    keys, checkpoint keys and role names all derive from this one
    function, so the fabric's identities can never drift apart."""
    return f"{name}-p{int(partition)}"


def record_partition(rec: Any, n_partitions: int) -> int:
    """The partition one INGRESS record routes to: by its doc id (a
    boxcar carries exactly one doc, so it rides whole). Doc-less junk
    pins to partition 0 — any single consistent home keeps offsets
    deterministic."""
    if n_partitions <= 1:
        return 0
    doc = rec.get("doc") if isinstance(rec, dict) else None
    return partition_of(doc, n_partitions) if isinstance(doc, str) else 0


def split_by_partition(records: List[Any],
                       n_partitions: int) -> Dict[int, List[Any]]:
    """Ingress records grouped by `record_partition`, input order
    preserved within each group — the one grouping rule every router
    edge (`shard_fabric.ShardRouter`, `LocalServer._route_raw`) shares,
    so a record can never route differently on different edges."""
    out: Dict[int, List[Any]] = {}
    for rec in records:
        out.setdefault(record_partition(rec, n_partitions), []).append(rec)
    return out


def lease_table(directory: str,
                now: Optional[float] = None) -> Dict[str, dict]:
    """Live leases in `directory` as ``{partition_name: {"owner",
    "fence", "expires"}}`` — the operator's (and chaos harness's) view
    of who owns what right now, WITH the fencing token: an owner
    string alone cannot distinguish a stale pre-split/pre-takeover
    holder from the live one, the fence can (it strictly advances on
    every ownership change). Read-only: no claim taken, so the
    snapshot may be an instant stale, which is all a monitoring
    surface needs. Liveness semantics are `LeaseManager.lease_info`'s
    — one place owns the expiry rule."""
    out: Dict[str, dict] = {}
    if not os.path.isdir(directory):
        return out
    probe = LeaseManager(directory, owner="__observer__")
    now = time.time() if now is None else now
    for fn in os.listdir(directory):
        if not fn.endswith(".lease"):
            continue
        name = fn[:-len(".lease")]
        info = probe.lease_info(name, now)
        if info is not None:
            out[name] = info
    return out


def lease_owners(directory: str,
                 now: Optional[float] = None) -> Dict[str, str]:
    """`lease_table` collapsed to {partition_name: owner} — the
    historical shape, still what most health surfaces render."""
    return {k: v["owner"] for k, v in lease_table(directory, now).items()}
