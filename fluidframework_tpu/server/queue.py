"""Ordering-log seam + partition leases: multi-node coordination.

Two abstractions the reference keeps in `services-core`
(server/routerlicious/packages/services-core/src/queue.ts `IProducer`/
`IConsumer`) and ZooKeeper (partition ownership for the Kafka
consumers, SURVEY.md §2.5 ⚙️):

- **Producer/consumer seam** — lambdas talk to topics only through
  `Producer`/`Consumer`; the in-proc journal (`server.log.MessageLog`)
  is one backend, and `SharedFileTopic` is a CROSS-PROCESS backend
  (multi-writer appends under an OS file lock, consumers tail from a
  checkpointed offset), so two server processes share one ordering
  log the way two routerlicious pods share a Kafka cluster.
- **Lease manager** — partition ownership with expiry-based failover
  (the zookeeper role): a worker acquires leases over document-space
  partitions, renews them while alive, and a peer takes over any
  lease that expires (crashed owner), resuming from the dead worker's
  checkpointed consumer offset.

`tools/partition_worker_main.py` runs a sequencer worker over this
seam; `tests/test_partition_leases.py` kills one of two workers and
proves the survivor takes over its partitions exactly-once.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, List, Optional, Protocol


class Producer(Protocol):
    """services-core/src/queue.ts IProducer role."""

    def send(self, message: Any) -> int: ...


class Consumer(Protocol):
    """services-core/src/queue.ts IConsumer role: an offset-owning
    reader whose position is the caller's checkpoint state."""

    offset: int

    def poll(self, max_count: Optional[int] = None) -> List[Any]: ...


class JournalProducer:
    """Producer over an in-proc `server.log.LogTopic`."""

    def __init__(self, topic):
        self.topic = topic

    def send(self, message: Any) -> int:
        return self.topic.append(message)


class JournalConsumer:
    """Consumer over an in-proc `server.log.LogTopic`."""

    def __init__(self, topic, offset: int = 0):
        self.topic = topic
        self.offset = offset

    def poll(self, max_count: Optional[int] = None) -> List[Any]:
        msgs = self.topic.read(self.offset, max_count)
        self.offset += len(msgs)
        return msgs


class SharedFileTopic:
    """A cross-process topic over one JSONL file.

    Appends take an OS file lock (multi-writer safe); consumers tail
    the file from a LINE offset, re-reading anything new on each poll
    — the minimal faithful form of a shared Kafka partition. Entries
    are plain JSON values.
    """

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if not os.path.exists(path):
            with open(path, "a"):
                pass

    def append(self, message: Any) -> None:
        import fcntl

        line = json.dumps(message) + "\n"
        with open(self.path, "a") as f:
            fcntl.flock(f.fileno(), fcntl.LOCK_EX)
            try:
                f.write(line)
                f.flush()
                os.fsync(f.fileno())
            finally:
                fcntl.flock(f.fileno(), fcntl.LOCK_UN)

    def read_from(self, offset: int) -> List[Any]:
        out: List[Any] = []
        with open(self.path) as f:
            for i, line in enumerate(f):
                if i >= offset and line.strip():
                    out.append(json.loads(line))
        return out


class SharedFileProducer:
    def __init__(self, topic: SharedFileTopic):
        self.topic = topic

    def send(self, message: Any) -> int:
        self.topic.append(message)
        return -1  # offsets are consumer-side for file topics


class SharedFileConsumer:
    def __init__(self, topic: SharedFileTopic, offset: int = 0):
        self.topic = topic
        self.offset = offset

    def poll(self, max_count: Optional[int] = None) -> List[Any]:
        msgs = self.topic.read_from(self.offset)
        if max_count is not None:
            msgs = msgs[:max_count]
        self.offset += len(msgs)
        return msgs


# ---------------------------------------------------------------------------
# Lease manager (zookeeper role)
# ---------------------------------------------------------------------------


class LeaseManager:
    """Expiry-based partition leases over a shared directory.

    A lease is a JSON file `<dir>/<partition>.lease` holding
    ``{"owner", "expires", "fence"}``. Acquisition writes a temp file
    and atomically renames it over the lease, then READS BACK to
    confirm ownership (two racers both rename; exactly one's content
    survives — the read-back arbitrates). `fence` increments on every
    ownership change, the fencing token that lets downstream state
    (checkpoints) reject a deposed owner's stale writes.
    """

    def __init__(self, directory: str, owner: str, ttl_s: float = 2.0):
        self.dir = directory
        self.owner = owner
        self.ttl_s = ttl_s
        os.makedirs(directory, exist_ok=True)

    def _path(self, partition: str) -> str:
        return os.path.join(self.dir, f"{partition}.lease")

    def _read(self, partition: str) -> Optional[dict]:
        try:
            with open(self._path(partition)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _write(self, partition: str, lease: dict) -> None:
        tmp = self._path(partition) + f".tmp.{self.owner}.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(lease, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path(partition))

    def try_acquire(self, partition: str,
                    now: Optional[float] = None) -> Optional[int]:
        """Acquire `partition` if unowned, expired, or already ours.
        Returns the fencing token on success, None otherwise."""
        now = time.time() if now is None else now
        cur = self._read(partition)
        if cur is not None:
            if cur.get("owner") == self.owner:
                return int(cur.get("fence", 0))
            if float(cur.get("expires", 0)) > now:
                return None  # live foreign lease
        fence = int(cur.get("fence", 0)) + 1 if cur else 1
        self._write(partition, {
            "owner": self.owner, "expires": now + self.ttl_s,
            "fence": fence,
        })
        # Read-back arbitration: a concurrent racer may have renamed
        # over ours between write and now.
        got = self._read(partition)
        if got is not None and got.get("owner") == self.owner:
            return int(got.get("fence", fence))
        return None

    def renew(self, partition: str,
              now: Optional[float] = None) -> bool:
        now = time.time() if now is None else now
        cur = self._read(partition)
        if cur is None or cur.get("owner") != self.owner:
            return False  # deposed
        self._write(partition, {**cur, "expires": now + self.ttl_s})
        return True

    def release(self, partition: str) -> None:
        cur = self._read(partition)
        if cur is not None and cur.get("owner") == self.owner:
            self._write(partition, {**cur, "expires": 0})

    def owner_of(self, partition: str) -> Optional[str]:
        cur = self._read(partition)
        if cur is None or float(cur.get("expires", 0)) <= time.time():
            return None
        return cur.get("owner")


def partition_of(doc_id: str, n_partitions: int) -> int:
    """Stable document-space partitioning (the Kafka partition-by-doc
    role, lambdas-driver/src/document-router)."""
    import hashlib

    h = hashlib.sha256(doc_id.encode()).digest()
    return int.from_bytes(h[:4], "big") % n_partitions
