"""Summary service: the scriptorium-offload summarizer role.

Per PAPER.md the hot path is merge-tree apply *and* the summary /
catch-up read side: persistence is periodic summaries in git-like
storage, and real collaborative traffic is mostly joins and reads —
yet until this module every client joining a document replayed the
entire op log, and the overlay/merge-tree kernel had no live consumer.

`SummarizerRole` is a supervised farm lambda (`server.supervisor._Role`
machinery: fenced lease, heartbeat, exactly-once ``inOff`` recovery)
that consumes the sequenced **deltas** stream and periodically emits
**fenced summary records**:

- the summary **blob** — a replayable per-doc state snapshot — is
  content-addressed into the shared `castore.ContentAddressedStore`
  behind a `historian.HistorianCache` (immutable blobs, LRU budget);
- a small **manifest** ``{doc, seq, msn, count, form, handle, off}``
  is appended (fenced, with ``inOff``) to the ``summaries`` topic, so
  readers discover the newest summary ≤ seq by tailing ONE topic
  (`SummaryIndex`).

Two blob forms, decided per document from its first op:

- ``"mergetree"`` — op contents parse as merge-tree wire ops
  (`protocol.mergetree_ops`). The role folds the doc's ops through the
  vectorized merge-tree kernel (`core.kernel_replica.KernelReplica`
  over `ops.mergetree_kernel`; several docs folding in the same pump
  are STACKED and dispatched through the vmapped
  `apply_op_batch_docs_jit` — one device call across the doc axis,
  the `overlay_replay.stack_replicas` idiom applied to the live
  stream). The blob serializes the **canonical row form** of the
  table at the fold point: settled rows (ins ≤ msn, not removed)
  coalesced into maximal equal-prop runs, tombstones below the window
  dropped (zamboni), above-window rows kept with their semantic
  fields, adjacent rows with identical semantic fields merged. The
  canonical form is a pure function of the op prefix — NOT of pump
  boundaries, checkpoint timing, or restart history — which is what
  makes the content-addressed handle stable across crashes: after
  every emission the live replica is REBUILT from the serialized rows
  (the restart path runs on every cadence), so an interrupted and an
  uninterrupted summarizer are byte-identical by construction.
  Blob size is O(document + collab window), independent of log
  length — the flat-cold-join property the catch-up bench gates.
- ``"ops"`` — generic contents (no merge-tree structure to compact):
  the blob carries the canonical records themselves. Correct (and the
  boundary between summary and tail is still exactly-once checked),
  but O(log); mixed/undecodable docs freeze their summaries rather
  than emit garbage.

**Safety argument** (why summary + tail == full replay): the fold
point of a summary at record k uses record k's stamped ``msn``. Every
op sequenced after k carries ``refSeq >= msn_at_its_sequencing >=
msn_k`` (deli nacks stale refSeqs and msn is monotone), so a tombstone
removed at/below ``msn_k`` is invisible to every later perspective and
a row inserted at/below ``msn_k`` is visible to every later
perspective — exactly the zamboni/compaction safety contract
`KernelReplica.compact` rests on, applied at a recorded point. The
differential gates (tests/test_summarizer.py, `config10_catchup`, the
chaos summarizer-kill run) check it bit-for-bit via document-state
digests.

Readers: `SummaryIndex` (manifest tailer), `read_catchup` (nearest
summary + op tail off the deltas topic), `SummaryReplica` (boots from
a blob — or cold — and applies tail records), `state_digest` (the
GOLDEN-style form two boots are compared in).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional, Tuple

from .castore import ContentAddressedStore
from .columnar_log import make_tail_reader, make_topic
from .historian import HistorianCache
from .supervisor import _Role, canonical_record

__all__ = [
    "FOLD_BACKENDS",
    "FOLD_BACKEND_ENV",
    "FOLD_INTERPRET_ENV",
    "SUMMARY_OPS_ENV",
    "SummarizerRole",
    "SummaryIndex",
    "SummaryReplica",
    "open_summary_store",
    "read_catchup",
    "state_digest",
    "summarize_document",
]

# Default emission cadence: one summary per doc every N sequenced
# records (override per role via summary_ops=, or process-wide via the
# env — the supervisor's child_env seam carries it to farm children).
SUMMARY_OPS_ENV = "FLUID_SUMMARY_OPS"
DEFAULT_SUMMARY_OPS = 256

# Merge-tree fold backend (`fold_backend=` / env): "kernel" is the
# vmapped row-model kernel (`apply_op_batch_docs_jit`), "overlay" the
# O(collab window) overlay-pallas engine (`core.overlay_fold` —
# BENCH_r04/r05 measure it ~38x the vmapped replay). Canonical row
# serialization is backend-invariant BY CONTRACT, so blobs and
# content-addressed handles are bit-identical either way — gated by
# `config15_device_plane` and tests/test_device_plane.py on every
# host. When pallas cannot lower here (CPU host, no interpreter
# requested) the role falls back to "kernel" LOUDLY.
FOLD_BACKEND_ENV = "FLUID_FOLD_BACKEND"
# "1": run the overlay backend through the pallas INTERPRETER — the
# CPU-CI correctness mode (slow, bit-identical), used by the chaos /
# differential gates on hosts without a TPU.
FOLD_INTERPRET_ENV = "FLUID_FOLD_INTERPRET"
FOLD_BACKENDS = ("kernel", "overlay")

# Fold-engine shape knobs (uniform across docs so the stacked vmapped
# dispatch can group them; a doc that outgrows the uniform capacity
# simply folds through the same kernel un-stacked).
_CHUNK = 128
_MIN_CAP = 512


def _pow2(n: int, lo: int = _MIN_CAP) -> int:
    c = lo
    while c < n:
        c *= 2
    return c


def _summary_ops_default() -> int:
    try:
        return max(1, int(os.environ.get(SUMMARY_OPS_ENV, "")))
    except ValueError:
        return DEFAULT_SUMMARY_OPS


def _fold_backend_default() -> str:
    b = os.environ.get(FOLD_BACKEND_ENV, "").strip() or "kernel"
    if b not in FOLD_BACKENDS:
        raise ValueError(
            f"{FOLD_BACKEND_ENV}={b!r} not in {FOLD_BACKENDS}"
        )
    return b


def _fold_interpret_default() -> bool:
    return os.environ.get(FOLD_INTERPRET_ENV, "") == "1"


_store_seq = 0


def open_summary_store(shared_dir: str,
                       budget_bytes: int = 64 * 1024 * 1024
                       ) -> HistorianCache:
    """The farm's summary store: a durable content-addressed store
    under ``<shared_dir>/store`` fronted by the historian cache
    (immutable blobs LRU-cache; every process — summarizer children,
    catch-up readers, benches — opens the same directory). Each open
    gets its own metrics label: distinct caches (different dirs, or a
    role and a reader side by side) must not fold into one gauge."""
    global _store_seq
    _store_seq += 1
    return HistorianCache(
        ContentAddressedStore(
            prefer_native=False,
            directory=os.path.join(shared_dir, "store"),
        ),
        blob_budget_bytes=budget_bytes,
        name=f"summary{_store_seq}",
    )


# ---------------------------------------------------------------------------
# merge-tree fold engine
# ---------------------------------------------------------------------------


def _decode_mt_op(contents: Any):
    """Merge-tree wire op, or None when the contents carry no
    merge-tree structure (the generic-doc detection rule)."""
    if not isinstance(contents, dict) or "type" not in contents:
        return None
    try:
        from ..protocol.mergetree_ops import op_from_json

        return op_from_json(contents)
    except (KeyError, ValueError, TypeError):
        return None


def _boot_mergetree(rows: List[list], msn: int):
    """Build a live `KernelReplica` from serialized canonical rows —
    THE restart path, also run after every emission so interrupted and
    uninterrupted summarizers proceed from the identical state."""
    import numpy as np

    from ..core.kernel_replica import KernelReplica, TextArena
    from ..ops.mergetree_kernel import (
        NOT_REMOVED,
        PROP_ABSENT,
        SegmentTable,
    )
    from ..protocol.constants import NO_CLIENT

    import jax.numpy as jnp

    rep = KernelReplica(initial="", chunk_size=_CHUNK, capacity=_MIN_CAP)
    n = len(rows)
    cap = _pow2(n + 2 * _CHUNK + 8)
    buf_start = np.zeros(cap, np.int32)
    length = np.zeros(cap, np.int32)
    ins_seq = np.zeros(cap, np.int32)
    ins_client = np.full(cap, NO_CLIENT, np.int32)
    rem_seq = np.full(cap, NOT_REMOVED, np.int32)
    rem_clients = np.full((cap, rep.n_removers), NO_CLIENT, np.int32)
    props = np.full((cap, rep.n_prop_keys), PROP_ABSENT, np.int32)
    parts: List[str] = []
    off = 0
    for i, (seg, ins, icl, rem, rcl, prow) in enumerate(rows):
        buf_start[i] = off
        length[i] = len(seg)
        ins_seq[i] = ins
        ins_client[i] = icl
        if rem is not None:
            rem_seq[i] = rem
            rem_clients[i, : len(rcl)] = rcl
        if prow:
            for k, v in prow.items():
                props[i, rep.props.key_id(k)] = rep.props.value_id(v)
        parts.append(seg)
        off += len(seg)
    rep.arena = TextArena("".join(parts))
    rep.capacity = cap
    rep.table = SegmentTable(
        n_rows=jnp.int32(n),
        buf_start=jnp.asarray(buf_start),
        length=jnp.asarray(length),
        ins_seq=jnp.asarray(ins_seq),
        ins_client=jnp.asarray(ins_client),
        rem_seq=jnp.asarray(rem_seq),
        rem_clients=jnp.asarray(rem_clients),
        props=jnp.asarray(props),
        error=jnp.int32(0),
    )
    rep.min_seq = rep._applied_min_seq = int(msn)
    rep._pending_rows_bound = n
    return rep


def _encode_fold(rep, records: List[dict]) -> None:
    """Encode canonical op records into the replica's pending rows
    (`kernel_replica.encode_op` — the same encoder every kernel
    replica consumer uses). Join/leave/noop records advance msn only."""
    from ..core.kernel_replica import encode_op
    from ..protocol.messages import MessageType, SequencedMessage

    for rec in records:
        if rec.get("type") == "op":
            op = _decode_mt_op(rec.get("contents"))
            if op is None:
                raise ValueError(f"non-mergetree contents at seq "
                                 f"{rec.get('seq')}")
            msg = SequencedMessage(
                int(rec["seq"]), int(rec["msn"]), int(rec["client"]),
                int(rec.get("clientSeq", 0)), int(rec.get("refSeq", 0)),
                MessageType.OP, op,
            )
            encode_op(rep, op, msg)
        rep.current_seq = int(rec["seq"])
        rep.min_seq = max(rep.min_seq, int(rec["msn"]))


def _place_fold_stack(tables, stacked, plane):
    """Lay a stacked kernel fold over the 2-D device plane: the doc
    axis shards on ``docs`` and the TABLE row/segment axis on
    ``model`` (`PartitionSpec('docs', 'model')` — XLA partitions the
    row-axis gathers with model-axis collectives), batch columns ride
    the doc axis replicated over model. Skipped (None) when the
    shapes don't divide the grid — placement is an optimization, the
    fold is bit-identical either way."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    K = int(tables.n_rows.shape[0])
    cap = int(tables.buf_start.shape[1])
    if K % plane.docs or cap % plane.model:
        return None
    mesh = plane.mesh

    def put(a, spec):
        return jax.device_put(a, NamedSharding(mesh, spec))

    def put_table(a):
        spec = (P("docs") if a.ndim == 1
                else P("docs", "model", *([None] * (a.ndim - 2))))
        return put(a, spec)

    def put_batch(a):
        return put(a, P("docs", *([None] * (a.ndim - 1))))

    return (
        jax.tree_util.tree_map(put_table, tables),
        jax.tree_util.tree_map(put_batch, stacked),
    )


def _fold_jobs(jobs: List[tuple], plane=None) -> None:
    """Drain the pending encoded rows of several replicas through the
    merge-tree kernel, STACKING same-shape replicas into one vmapped
    `apply_op_batch_docs_jit` dispatch per round — the docs axis is
    embarrassingly parallel, so K summarizing docs cost one device
    call, not K (the `stack_replicas` idiom on the live stream).
    `plane` (a `parallel.device_plane.DevicePlane`) lays the stacked
    dispatch over the 2-D pool: docs on the ``docs`` axis, table rows
    on ``model``."""
    import jax
    import jax.numpy as jnp

    from ..ops.mergetree_kernel import (
        apply_op_batch_docs_jit,
        apply_op_batch_jit,
    )

    reps = [rep for rep, _ in jobs]
    while any(r._encoded for r in reps):
        groups: Dict[tuple, list] = {}
        for r in reps:
            if not r._encoded:
                continue
            r._ensure_capacity()
            groups.setdefault((r.capacity, r.chunk_size), []).append(r)
        for (_cap, chunk_b), grp in groups.items():
            chunks = []
            for r in grp:
                chunks.append(r._encoded[:chunk_b])
                del r._encoded[:chunk_b]
            batches = [r._build_batch(c) for r, c in zip(grp, chunks)]
            if len(grp) == 1:
                grp[0].table = apply_op_batch_jit(grp[0].table, batches[0])
            else:
                stack = lambda *xs: jnp.stack(xs)  # noqa: E731
                tables = jax.tree_util.tree_map(
                    stack, *[r.table for r in grp]
                )
                stacked = jax.tree_util.tree_map(stack, *batches)
                if plane is not None:
                    placed = _place_fold_stack(tables, stacked, plane)
                    if placed is not None:
                        tables, stacked = placed
                out = apply_op_batch_docs_jit(tables, stacked)
                for i, r in enumerate(grp):
                    r.table = jax.tree_util.tree_map(
                        lambda a, _i=i: a[_i], out
                    )
            for r, c in zip(grp, chunks):
                r._applied_min_seq = c[-1][10]
                r._applied_since_compact = True
                if (r._pending_rows_bound
                        > r.capacity * r.compact_watermark):
                    # The zamboni watermark `KernelReplica._flush_chunks`
                    # applies: without it a long fold accumulates
                    # tombstones/splits and the O(capacity)-per-op
                    # kernel goes quadratic in log length. Deterministic
                    # (a pure function of the fold sequence), and the
                    # canonical row serialization is invariant under
                    # compaction history by construction.
                    r.compact()


def _canonical_rows(rep, msn: int) -> List[list]:
    """The canonical serialized row form of a replica's table at fold
    msn `msn` — a pure function of the document's op prefix:

    - tombstones removed at/below `msn` are dropped (zamboni: invisible
      to every refSeq >= msn perspective);
    - rows inserted at/below `msn` normalize (ins_seq, ins_client) to
      (UNIVERSAL_SEQ, NO_CLIENT) — their visibility is certain for
      every future perspective, so the real stamps carry no semantics;
    - adjacent rows whose semantic fields all match merge into maximal
      runs, erasing split/chunk/checkpoint history from the bytes.

    Each row: ``[text, ins_seq, ins_client, rem_seq|None,
    rem_clients|None, props|None]``."""
    import jax
    import numpy as np

    from ..core.overlay_fold import merge_canonical_rows
    from ..ops.mergetree_kernel import NOT_REMOVED, raise_kernel_errors
    from ..protocol.constants import NO_CLIENT, UNIVERSAL_SEQ

    t = jax.tree_util.tree_map(np.asarray, rep.table)
    raise_kernel_errors(int(t.error))
    text = rep.arena.snapshot()
    raw: List[tuple] = []
    for i in range(int(t.n_rows)):
        rem = int(t.rem_seq[i])
        removed = rem != NOT_REMOVED
        if removed and rem <= msn:
            continue  # zamboni: tombstone below the window
        seg = text[int(t.buf_start[i]): int(t.buf_start[i])
                   + int(t.length[i])]
        ins = int(t.ins_seq[i])
        icl = int(t.ins_client[i])
        if ins <= msn:
            ins, icl = UNIVERSAL_SEQ, NO_CLIENT
        rcl = (sorted(int(c) for c in t.rem_clients[i]
                      if int(c) != NO_CLIENT) if removed else None)
        props = rep.props.decode_row(t.props[i])
        raw.append((seg, ins, icl, rem if removed else None, rcl,
                    props))
    # The merge rule is SHARED with the overlay fold backend
    # (`core.overlay_fold.merge_canonical_rows`) — one definition, so
    # the two backends cannot drift apart on the bytes.
    return merge_canonical_rows(raw)


# ---------------------------------------------------------------------------
# the supervised role
# ---------------------------------------------------------------------------


class SummarizerRole(_Role):
    """deltas → summaries: the scriptorium-offload summary lambda.

    Composes with the whole PR-1 machinery unchanged: fenced lease,
    heartbeat, checkpoint cadence, and the exactly-once ``inOff``
    recovery — manifests are ordinary outputs of their trigger input
    line, so a crash between the manifest append and the checkpoint
    replays silently and re-emits only the clipped tail. Blob puts are
    content-addressed (idempotent), so recovery re-putting a blob is a
    no-op with the same handle: **restarts cannot fork a summary**.

    Runs per-partition under `partitioned_role_class` (``deltas-p{k}``
    → ``summaries-p{k}``) for the static sharded fabric, and per RANGE
    under `shard_fabric.ranged_role_class` on the ELASTIC fabric: the
    fold state is a flat per-doc map, so a split/merge successor
    absorbs its predecessors' fold dicts sliced to its hash range
    through the generic `_RangedMixin` machinery (seed from the final
    fenced checkpoints, fence-bind on the pred manifest topics, silent
    replay of the durable prefix, missing manifests re-emitted
    exactly-once) — summaries ride every topology.

    Manifests additionally carry ``byteOff`` — the LOGICAL deltas-topic
    byte position at the start of the trigger's input batch (None when
    the emission came from recovery replay or a predecessor drain,
    where no own-topic anchor exists). It is a hard lower bound for
    the catch-up tail seek (`read_catchup` feeds it to the backward
    scans as ``stop_at``), stable under op-log truncation.

    Around each emission round the role PINS the summary store
    (`server.retention.write_pin`) until the round's manifests are
    durably appended: the retention plane's castore GC never sweeps a
    blob newer than the oldest live pin, closing the put→manifest
    race without coordinating with the sweeper."""

    name = "summarizer"
    in_topic_name = "deltas"
    out_topic_name = "summaries"

    def __init__(self, *a, summary_ops: Optional[int] = None,
                 store=None, historian_budget: int = 64 * 1024 * 1024,
                 fold_backend: Optional[str] = None,
                 device_plane=None,
                 fold_interpret: Optional[bool] = None,
                 **kw):
        super().__init__(*a, **kw)
        self.summary_ops = int(summary_ops or _summary_ops_default())
        if self.summary_ops < 1:
            raise ValueError(f"summary_ops must be >= 1: {summary_ops}")
        # Fold backend + device plane (resolved LAZILY: both touch jax
        # and the role must construct cheaply in scalar/no-mergetree
        # farms). `device_plane` is a spec/`DevicePlane`; None falls
        # back to the process env (`parallel.device_plane.PLANE_ENV`)
        # so supervised children inherit the farm plane.
        requested = fold_backend or _fold_backend_default()
        if requested not in FOLD_BACKENDS:
            raise ValueError(
                f"fold_backend {requested!r} not in {FOLD_BACKENDS}"
            )
        self._fold_backend_requested = requested
        self._fold_backend: Optional[str] = None
        self.fold_interpret = (
            bool(fold_interpret) if fold_interpret is not None
            else _fold_interpret_default()
        )
        self._plane_arg = device_plane
        self._plane_resolved = False
        self._plane = None
        self.store = store if store is not None else open_summary_store(
            self.shared_dir, historian_budget
        )
        # doc -> fold dict (JSON-serializable; live replicas cached
        # separately and rebuilt lazily from the serialized rows).
        self.docs: Dict[str, dict] = {}
        self._reps: Dict[str, Any] = {}
        # (doc, line_idx, window_upto, seq, msn, count) — the pending
        # emission points of this pump, folded/emitted in flush_batch.
        self._triggers: List[tuple] = []
        m = self.metrics
        labels = self._metric_labels()
        self._m_summaries = m.counter("summaries_emitted_total", **labels)
        self._m_blob_bytes = m.counter("summary_blob_bytes_total",
                                       **labels)
        self._m_fold_ops = m.counter("summary_fold_ops_total", **labels)
        self._m_stacked = m.counter("summary_stacked_folds_total",
                                    **labels)
        self._m_frozen = m.counter("summary_docs_frozen_total", **labels)
        self._m_docs = m.gauge("summary_docs", **labels)
        self._m_build_ms = m.histogram("summary_build_ms", **labels)
        self._m_backend_fallbacks = m.counter(
            "summary_fold_backend_fallbacks_total", **labels
        )
        self._m_plane_folds = m.counter("summary_plane_folds_total",
                                        **labels)

    # --------------------------------------------------- fold backend

    def fold_backend(self) -> str:
        """The RESOLVED fold backend: "overlay" only when the
        overlay-pallas engine can actually run here (real TPU
        lowering, or the interpreter when `fold_interpret` asks for
        the CPU-CI correctness mode) — otherwise a LOUD fallback to
        "kernel" (stdout + metric), never a silent one. Resolution is
        process-cheap after the first call."""
        if self._fold_backend is None:
            backend = self._fold_backend_requested
            if backend == "overlay":
                from ..core.overlay_fold import overlay_available

                if not overlay_available(self.fold_interpret):
                    print(
                        f"summarizer: fold_backend=overlay unavailable "
                        f"(pallas cannot lower here, interpret="
                        f"{self.fold_interpret}); FALLING BACK to "
                        f"fold_backend=kernel", flush=True,
                    )
                    self._m_backend_fallbacks.inc()
                    backend = "kernel"
            self._fold_backend = backend
            self.metrics.gauge(
                "summary_fold_backend", backend=backend,
                **self._metric_labels()
            ).set(1)
        return self._fold_backend

    def device_plane(self):
        """The farm's 2-D device plane (None when unconfigured):
        explicit arg wins, else the process env — the supervisor's
        child_env seam (`--device-plane`/`FLUID_DEVICE_PLANE`)."""
        if not self._plane_resolved:
            from ..parallel.device_plane import resolve_plane

            self._plane = resolve_plane(self._plane_arg, env=True)
            self._plane_resolved = True
        return self._plane

    def _boot_rep(self, rows: List[list], msn: int):
        if self.fold_backend() == "overlay":
            from ..core.overlay_fold import boot_overlay

            return boot_overlay(rows, msn,
                                interpret=self.fold_interpret)
        return _boot_mergetree(rows, msn)

    def _dispatch_fold(self, fold_jobs: List[tuple]) -> None:
        plane = self.device_plane()
        if plane is not None:
            self._m_plane_folds.inc()
        if self.fold_backend() == "overlay":
            from ..core.overlay_fold import fold_jobs_overlay

            fold_jobs_overlay(fold_jobs, plane=plane,
                              interpret=self.fold_interpret)
        else:
            _fold_jobs(fold_jobs, plane=plane)

    def _rows_of(self, rep, msn: int) -> List[list]:
        """Canonical rows at `msn` — backend-dispatched, identical
        bytes by contract (the content-addressed no-fork invariant)."""
        if self.fold_backend() == "overlay":
            return rep.canonical_rows(msn)
        return _canonical_rows(rep, msn)

    # ------------------------------------------------------------ state

    def snapshot_state(self) -> Any:
        # A FLAT {doc: fold} map — the shape `_RangedMixin` slices by
        # hash range when an elastic successor absorbs this role's
        # final checkpoint (every ranged role's state contract).
        return dict(self.docs)

    def restore_state(self, state: Any) -> None:
        state = dict(state or {})
        if set(state) == {"docs"} and isinstance(state["docs"], dict) \
                and all(isinstance(v, dict) and "count" in v
                        for v in state["docs"].values()):
            # Pre-retention checkpoint shape ({"docs": {...}}): unwrap.
            state = dict(state["docs"])
        self.docs = state
        self._reps = {}
        self._triggers = []

    # ------------------------------------------------------------- fold

    def _fold(self, doc: str) -> dict:
        f = self.docs.get(doc)
        if f is None:
            f = self.docs[doc] = {
                "seq": 0, "msn": 0, "count": 0, "engine": None,
                "window": [], "records": [],
                "base": 0, "base_msn": 0, "rows": [],
                "last": None,
            }
            self._m_docs.set(len(self.docs))
        return f

    def _rep(self, doc: str, f: dict):
        rep = self._reps.get(doc)
        if rep is None:
            rep = self._reps[doc] = self._boot_rep(
                f["rows"], f["base_msn"]
            )
        return rep

    def process(self, line_idx: int, rec: Any, out: List[dict]) -> None:
        if not isinstance(rec, dict) or rec.get("kind") != "op" \
                or "doc" not in rec:
            return  # nacks / junk: summaries fold sequenced ops only
        f = self._fold(rec["doc"])
        f["seq"] = max(int(f["seq"]), int(rec["seq"]))
        f["msn"] = max(int(f["msn"]), int(rec["msn"]))
        f["count"] = int(f["count"]) + 1
        c = canonical_record(rec)
        if f["engine"] is None and rec.get("type") == "op":
            f["engine"] = ("mergetree"
                           if _decode_mt_op(rec.get("contents"))
                           is not None else "ops")
            if f["engine"] == "ops":
                # Generic doc: the whole history is the state.
                f["records"].extend(f["window"])
                f["window"] = []
        if f["engine"] == "ops":
            f["records"].append(c)
        else:  # mergetree / undecided / frozen: buffer the window
            f["window"].append(c)
        if f["engine"] in ("mergetree", "ops") and \
                f["count"] % self.summary_ops == 0:
            # Snapshot the fold-prefix lengths AT the trigger: records
            # later in the same pump belong to the NEXT summary, and a
            # blob cut anywhere else would depend on pump boundaries —
            # the determinism the content-addressed no-fork contract
            # rests on. A cadence point reached while the engine is
            # still UNDECIDED (>= summary_ops joins/leaves before the
            # first op) is skipped outright — an engine decided later
            # in the same pump would otherwise emit an empty blob, and
            # one decided in a later pump would leave a dangling
            # trigger; both deterministic only by accident. Skipping
            # is itself deterministic (a pure function of the
            # stream), and join/leave records carry no summarizable
            # state beyond the (seq, msn, count) head.
            self._triggers.append((
                rec["doc"], line_idx, len(f["window"]),
                len(f["records"]), f["seq"], f["msn"], f["count"],
                # The input batch's start byte (logical; None in
                # recovery replay / pred drains): every record of this
                # doc below it is at/below this summary's seq, so the
                # manifest's byteOff bounds the catch-up tail seek.
                self._in_pos,
            ))

    # ------------------------------------------------------- emission

    def _freeze(self, doc: str, f: dict, why: str) -> None:
        """A doc whose stream stopped folding (undecodable op, kernel
        error, prop overflow): stop emitting summaries for it — a
        frozen doc falls back to longer tails, never to a wrong
        summary. Loud in the metrics, not in the stream."""
        f["engine"] = "frozen"
        f["window"] = []
        f["rows"] = []
        self._reps.pop(doc, None)
        self._m_frozen.inc()
        print(f"summarizer: froze {doc} ({why})", flush=True)

    def flush_batch(self, out: List[dict]) -> None:
        if not self._triggers:
            return
        import time as _time

        from .retention import write_pin

        # GC epoch pin: blobs put from here on may not be referenced
        # by a durable manifest yet — the retention sweeper spares
        # everything newer than this instant until the pin clears
        # (after this round's outputs are appended, or on expiry if we
        # die — recovery's silent replay re-puts the blobs before the
        # clipped manifests are re-emitted, so expiry is safe).
        self._pin_t = write_pin(self.shared_dir, self.name)
        self._pin_hb = self._pin_t
        self._pinned = True
        t0 = _time.perf_counter()
        triggers, self._triggers = self._triggers, []
        consumed: Dict[str, int] = {}
        # Group consecutive triggers of DISTINCT docs into one stacked
        # fold round; a doc triggering twice in one pump starts a new
        # round (its second fold depends on its first).
        i = 0
        while i < len(triggers):
            round_jobs: List[tuple] = []
            round_docs: set = set()
            j = i
            while j < len(triggers) and triggers[j][0] not in round_docs:
                round_docs.add(triggers[j][0])
                round_jobs.append(triggers[j])
                j += 1
            self._emit_round(round_jobs, consumed, out)
            i = j
        self._m_build_ms.observe((_time.perf_counter() - t0) * 1000.0)

    def _refresh_pin(self) -> None:
        # Heartbeat the GC pin mid-round: rewriting with the ORIGINAL
        # floor keeps blobs put earlier in the round covered while the
        # file mtime proves this writer is alive — a round longer than
        # retention.PIN_TTL_S must not lose its early puts to the sweep.
        # Time-gated: liveness runs on the TTL clock, so the hot
        # emission path only pays the file rewrite every TTL/4, not
        # per blob put.
        if getattr(self, "_pinned", False):
            import time as _time

            from .retention import PIN_TTL_S, write_pin

            now = _time.time()
            if now - getattr(self, "_pin_hb", 0.0) < PIN_TTL_S / 4.0:
                return
            self._pin_hb = now
            write_pin(self.shared_dir, self.name, self._pin_t)

    def _unpin(self) -> None:
        if getattr(self, "_pinned", False):
            from .retention import clear_pin

            clear_pin(self.shared_dir, self.name)
            self._pinned = False

    def _append_outputs(self, out: List[dict]) -> int:
        n = super()._append_outputs(out)
        # The round's manifests are durable: release the GC pin.
        self._unpin()
        return n

    def checkpoint(self) -> None:
        super().checkpoint()
        # Recovery and pred drains append outside `_append_outputs`;
        # both checkpoint right after, so the pin never outlives the
        # round however the manifests landed.
        self._unpin()

    def _emit_round(self, round_jobs: List[tuple],
                    consumed: Dict[str, int], out: List[dict]) -> None:
        self._refresh_pin()
        fold_jobs: List[tuple] = []
        for doc, _line, upto, _rupto, _seq, msn, _count, _bo \
                in round_jobs:
            f = self.docs[doc]
            if f["engine"] != "mergetree":
                continue
            done = consumed.get(doc, 0)
            take = f["window"][: upto - done]
            rep = self._rep(doc, f)
            try:
                _encode_fold(rep, take)
            except (ValueError, TypeError) as exc:
                self._freeze(doc, f, repr(exc))
                continue
            self._m_fold_ops.inc(len(take))
            fold_jobs.append((rep, take))
        if len(fold_jobs) > 1:
            self._m_stacked.inc(len(fold_jobs))
        if fold_jobs:
            self._dispatch_fold(fold_jobs)
        for doc, line_idx, upto, rec_upto, seq, msn, count, byte_off \
                in round_jobs:
            f = self.docs[doc]
            if f["engine"] == "frozen":
                continue
            done = consumed.get(doc, 0)
            if f["engine"] == "mergetree":
                rep = self._reps.get(doc)
                if rep is None:
                    continue  # froze mid-round
                try:
                    rows = self._rows_of(rep, msn)
                except RuntimeError as exc:  # kernel error flag
                    self._freeze(doc, f, repr(exc))
                    continue
                del f["window"][: upto - done]
                consumed[doc] = upto
                f["rows"] = rows
                f["base"] = seq
                f["base_msn"] = msn
                # Rebuild from the serialized form — the restart path,
                # exercised every cadence, so a crashed-and-restored
                # summarizer can never diverge from this one.
                self._reps[doc] = self._boot_rep(rows, msn)
                blob = {"form": "mergetree", "doc": doc, "seq": seq,
                        "msn": msn, "count": count, "rows": rows}
            elif f["engine"] == "ops":
                blob = {"form": "ops", "doc": doc, "seq": seq,
                        "msn": msn, "count": count,
                        "records": list(f["records"][:rec_upto])}
            else:
                continue  # undecided: nothing but joins/leaves yet
            payload = json.dumps(
                blob, sort_keys=True, separators=(",", ":")
            ).encode()
            self._refresh_pin()
            handle = self._durable(lambda: self.store.put(payload))
            f["last"] = {"seq": seq, "handle": handle}
            self._m_summaries.inc()
            self._m_blob_bytes.inc(len(payload))
            out.append({
                "kind": "summary", "doc": doc, "seq": seq, "msn": msn,
                "count": count, "form": blob["form"], "handle": handle,
                "bytes": len(payload), "off": line_idx,
                # Byte-offset hint for the O(tail) catch-up seek
                # (None: recovery replay / pred drain — readers fall
                # back to the unbounded backward scan). byteTopic
                # names the byte space: on the elastic fabric a
                # ranged summarizer's offsets are meaningless in any
                # OTHER range's topic, so readers use the floor only
                # when the topic they scan matches.
                "byteOff": byte_off,
                "byteTopic": self.in_topic_name,
                "inOff": line_idx,
            })


# ---------------------------------------------------------------------------
# readers: manifest index, boot replica, catch-up
# ---------------------------------------------------------------------------


class SummaryIndex:
    """Tail of the ``summaries`` topic(s): newest manifest per doc ≤ a
    requested seq. One topic read answers every reader — the discovery
    surface of the summary service. `partitions` adds the static
    fabric's ``summaries-p{k}`` siblings to the tail set."""

    def __init__(self, shared_dir: str, log_format: Optional[str] = None,
                 partitions: int = 1,
                 topics: Optional[List[str]] = None):
        """`topics` names the manifest topics explicitly (the ELASTIC
        fabric's per-range ``summaries-{rid}`` set across the topology
        history — `ShardRouter.stage_topic_names("summaries")`);
        `partitions` keeps the static fabric's ``summaries-p{k}``
        shorthand."""
        import threading

        from .queue import partition_suffix

        if topics is not None:
            names = list(topics)
        else:
            names = ["summaries"]
            if partitions > 1:
                names += [partition_suffix("summaries", k)
                          for k in range(partitions)]
        self._readers = [
            make_tail_reader(make_topic(
                os.path.join(shared_dir, "topics", f"{n}.jsonl"),
                log_format,
            ))
            for n in names
        ]
        # doc -> manifests sorted by seq (appends are seq-monotone per
        # doc within a topic; merged across topics defensively). One
        # index is shared across FarmReadServer's session THREADS: the
        # tail readers' read-modify-write and the manifest lists go
        # under a lock, or racing polls double-deliver or strand
        # reader positions.
        self.manifests: Dict[str, List[dict]] = {}
        self._lock = threading.Lock()

    def poll(self) -> int:
        n = 0
        with self._lock:
            for r in self._readers:
                for _, rec in r.poll():
                    if not isinstance(rec, dict) or \
                            rec.get("kind") != "summary":
                        continue
                    lst = self.manifests.setdefault(rec["doc"], [])
                    lst.append(rec)
                    if len(lst) > 1 and lst[-2]["seq"] > rec["seq"]:
                        lst.sort(key=lambda m: m["seq"])
                    n += 1
        return n

    def nearest(self, doc: str, seq: Optional[int] = None
                ) -> Optional[dict]:
        """Newest manifest for `doc` with ``manifest.seq <= seq``
        (no bound: the newest overall)."""
        with self._lock:
            lst = list(self.manifests.get(doc) or ())
        if not lst:
            return None
        if seq is None:
            return lst[-1]
        best = None
        for m in lst:
            if m["seq"] <= seq:
                best = m
            else:
                break
        return best


class SummaryReplica:
    """A reader-side replica booted from a summary blob (or cold).

    The join path under test: boot from ``blob`` then
    ``apply_records(tail)`` must be bit-identical — per
    `state_digest` — to a cold boot applying the full log. Cold boots
    decide their engine exactly like the summarizer (first op's
    contents), so the differential compares like with like."""

    def __init__(self, blob: Optional[dict] = None):
        self.form = blob["form"] if blob else None
        self.seq = int(blob["seq"]) if blob else 0
        self.msn = int(blob["msn"]) if blob else 0
        self.count = int(blob.get("count", 0)) if blob else 0
        self._rep = None
        self.records: List[dict] = []
        # Canonical records seen before the engine is decided (a cold
        # boot's joins/leaves ahead of the first op).
        self._prefix: List[dict] = []
        if blob is None:
            return
        if self.form == "mergetree":
            self._rep = _boot_mergetree(blob["rows"], self.msn)
        elif self.form == "ops":
            self.records = [dict(r) for r in blob["records"]]
        else:
            raise ValueError(f"unknown summary form {self.form!r}")

    def apply_records(self, records: List[dict]) -> int:
        """Apply sequenced wire records (kind == "op") past the boot
        point; duplicates at/below the current seq drop (the reader's
        half of the exactly-once boundary). Merge-tree folding batches
        the whole call into chunked kernel dispatches."""
        pending_mt: List[dict] = []
        n = 0
        for rec in records:
            if not isinstance(rec, dict) or rec.get("kind") != "op":
                continue
            if int(rec["seq"]) <= self.seq:
                continue
            c = canonical_record(rec)
            if self.form is None and rec.get("type") == "op":
                self.form = ("mergetree"
                             if _decode_mt_op(rec.get("contents"))
                             is not None else "ops")
                if self.form == "ops":
                    self.records.extend(self._prefix)
                else:
                    pending_mt.extend(self._prefix)
                self._prefix = []
            if self.form == "mergetree":
                pending_mt.append(c)
            elif self.form == "ops":
                self.records.append(c)
            else:  # undecided: joins/leaves before the first op
                self._prefix.append(c)
            self.seq = int(rec["seq"])
            self.msn = max(self.msn, int(rec["msn"]))
            self.count += 1
            n += 1
        if pending_mt:
            if self._rep is None:
                self._rep = _boot_mergetree([], 0)
            _encode_fold(self._rep, pending_mt)
            _fold_jobs([(self._rep, pending_mt)])
        return n

    # ------------------------------------------------------------ state

    def get_text(self) -> str:
        return self._rep.get_text() if self._rep is not None else ""

    def char_spans(self) -> List[tuple]:
        if self._rep is None:
            return []
        from ..testing.farm import char_spans

        return char_spans(self._rep.annotated_spans())

    def state_digest(self) -> str:
        return state_digest(self)


def state_digest(replica: SummaryReplica) -> str:
    """The GOLDEN-style digest two boots are compared in: document
    state (char-level, so segmentation history is invisible) for
    merge-tree docs, the canonical record stream for generic docs —
    plus the (seq, msn, count) head so a tail boundary off-by-one can
    never hide."""
    if replica.form == "mergetree":
        body: Any = [replica.get_text(), replica.char_spans()]
    else:
        body = replica.records
    payload = json.dumps(
        [replica.seq, replica.msn, replica.count, replica.form, body],
        sort_keys=True, ensure_ascii=True, default=str,
    )
    return hashlib.sha256(payload.encode()).hexdigest()


def _tail_records_reverse(path: str, doc: str, base: int,
                          upto: Optional[int],
                          stop_at: Optional[int] = None) -> List[dict]:
    """`doc`'s op records with ``base < seq [<= upto]`` read BACKWARD
    from the topic's end — O(tail + interleave), not O(log): per-doc
    seqs are append-monotone, so the first own-doc record at/below
    `base` bounds the scan. JSONL topics only (a frame log needs the
    forward walk); the torn-tail rule holds — a final line without
    its newline is never consumed.

    ``stop_at`` (a manifest's ``byteOff`` — a line boundary) floors
    the walk: every own-doc record below it is at/below `base`, so
    the seek is O(tail) even with zero own-doc interleave."""
    stop = max(0, int(stop_at)) if isinstance(stop_at, int) else 0
    out: List[dict] = []
    try:
        f = open(path, "rb")
    except OSError:
        return out
    with f:
        f.seek(0, os.SEEK_END)
        pos = f.tell()
        stop = min(stop, pos)
        block = 1 << 16
        carry = b""
        first = True
        while pos > stop:
            step = min(block, pos - stop)
            pos -= step
            f.seek(pos)
            data = f.read(step) + carry
            parts = data.split(b"\n")
            carry = parts[0]  # partial first line: joins the next block
            lines = parts[1:]
            if first:
                first = False
                if lines and not data.endswith(b"\n"):
                    lines.pop()  # torn tail: invisible until complete
            for raw in reversed(lines):
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    rec = json.loads(raw)
                except ValueError:
                    continue  # sealed junk from a crashed writer
                if not isinstance(rec, dict) or rec.get("doc") != doc \
                        or rec.get("kind") != "op":
                    continue
                s = int(rec["seq"])
                if s <= base:
                    out.reverse()
                    return out
                if upto is None or s <= upto:
                    out.append(rec)
            block = min(block * 2, 1 << 22)
        # Floor reached (file start, or the byteOff line boundary):
        # carry is the (complete) first line of the scanned region —
        # a non-aligned stop leaves a partial line, which simply
        # fails to parse and is skipped (records below the floor are
        # at/below `base` by the byteOff contract anyway).
        raw = carry.strip()
        if raw:
            try:
                rec = json.loads(raw)
                if isinstance(rec, dict) and rec.get("doc") == doc \
                        and rec.get("kind") == "op" \
                        and int(rec["seq"]) > base \
                        and (upto is None or int(rec["seq"]) <= upto):
                    out.append(rec)
            except ValueError:
                pass
    out.reverse()
    return out


def read_catchup(shared_dir: str, doc: str,
                 log_format: Optional[str] = None,
                 seq: Optional[int] = None,
                 index: Optional[SummaryIndex] = None,
                 store=None,
                 deltas_topic: str = "deltas") -> dict:
    """Answer a cold join from the farm's topics: nearest summary ≤
    `seq` (manifest + blob) plus the op tail past it off the deltas
    topic — the read that replaces full-log replay. Returns
    ``{"manifest", "blob", "ops"}`` (manifest/blob None when no
    summary exists yet — the tail is then the whole log).

    With a summary present the tail is read BACKWARD from the topic's
    end (O(tail), so the join cost is flat in log length — the
    config10 gate) on BOTH log formats: JSONL via the line scan below,
    columnar via the frame-chaining scan
    (`columnar_log.tail_records_reverse`), which falls back to the
    forward skip from the manifest's `off` only when it cannot anchor
    (a pre-sidecar or JSON-era-prefix file)."""
    from .columnar_log import ColumnarFileTopic, tail_records_reverse

    idx = index or SummaryIndex(shared_dir, log_format)
    idx.poll()
    man = idx.nearest(doc, seq)
    blob = None
    swept = False
    if man is not None:
        st = store or open_summary_store(shared_dir)
        try:
            blob = json.loads(st.get(man["handle"]).decode())
        except KeyError:
            # Castore GC swept this manifest's blob: it fell below
            # the doc's retention root set (only the newest
            # ``keep_summaries`` manifests stay referenced, while a
            # quiet doc can hold the manifest-topic cut back far
            # enough for older ones to stay discoverable). Fall to
            # the full-replay path — honest only while the op log
            # still holds the doc's whole history, checked below.
            man, swept = None, True
    topic = make_topic(
        os.path.join(shared_dir, "topics", f"{deltas_topic}.jsonl"),
        log_format,
    )
    if man is None and (swept or seq is not None):
        # No usable summary at/below the requested seq. A replay from
        # logical 0 silently resumes at the truncation base, so if the
        # doc IS summarized (its covered prefix may be physically
        # reclaimed) and the log has a cut, partial state would come
        # back as if complete — refuse loudly instead. Docs with no
        # summary at all never pass the retention coverage clamp, so
        # their history is structurally intact whatever the base.
        base_gone = (topic.base_offsets()[0] > 0
                     if hasattr(topic, "base_offsets") else False)
        if base_gone and (swept or idx.nearest(doc) is not None):
            raise LookupError(
                f"catchup({doc!r}, seq={seq}): state below the "
                f"retention horizon — the nearest summary blob was "
                f"garbage-collected and/or the covered op prefix was "
                f"truncated; only the newest summaries are retained"
            )
    base = int(man["seq"]) if man is not None else 0
    ops = None
    if man is not None:
        # The manifest's byteOff (when present) floors the backward
        # walk: O(tail) however sparse the doc's records are in the
        # interleave, truncation-stable (logical bytes) — but ONLY in
        # the byte space it was stamped against (`byteTopic`). A
        # pred-era manifest read through the merged elastic index
        # against a successor range's topic would floor the walk at a
        # foreign offset and silently drop tail ops; mismatch falls
        # back to the unbounded (still correct) scan.
        stop = man.get("byteOff")
        stop = (stop if isinstance(stop, int)
                and man.get("byteTopic") == deltas_topic else None)
        if isinstance(topic, ColumnarFileTopic):
            ops = tail_records_reverse(topic, doc, base, seq,
                                       stop_at=stop)
        else:
            ops = _tail_records_reverse(topic.path, doc, base, seq,
                                        stop_at=stop)
    if ops is None:
        # The manifest's `off` (its trigger's input line) bounds the
        # forward scan: records at/below it are covered.
        reader = make_tail_reader(
            topic, int(man["off"]) + 1 if man is not None else 0
        )
        ops = [
            rec for _, rec in reader.poll()
            if isinstance(rec, dict) and rec.get("kind") == "op"
            and rec.get("doc") == doc and int(rec["seq"]) > base
            and (seq is None or int(rec["seq"]) <= seq)
        ]
    return {"manifest": man, "blob": blob, "ops": ops}


# ---------------------------------------------------------------------------
# in-proc summarizer agent (the LocalServer / tinylicious twin)
# ---------------------------------------------------------------------------


def summarize_document(server, registry, doc_id: str) -> Tuple[str, int]:
    """The reference's summarizer-client shape for the in-proc
    `LocalServer`: resolve the document headless (no join — the
    catch-up tail applies without connecting), upload the runtime
    summary, and point the doc's ref at it, so every later
    `Loader.resolve` boots from this summary plus only the op tail.
    Returns ``(handle, base_seq)``."""
    from ..drivers.local_driver import LocalDriver
    from ..loader.container import Loader

    loader = Loader(LocalDriver(server), registry)
    c = loader.resolve(doc_id, connect=False)
    try:
        wire = c.runtime.summarize().to_json()
        base_seq = int(c.runtime.current_seq)
    finally:
        c.close()
    handle = server.upload_summary(wire)
    server.storage.set_ref(doc_id, handle)
    return handle, base_seq
