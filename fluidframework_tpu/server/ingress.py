"""The supervised admission front door: alfred as a farm role.

In the reference topology EVERY op crosses alfred before it can reach
the sequencer (SURVEY §S0, ``lambdas/src/alfred``): token validation
(the riddler gate, alfred/index.ts:595), size caps, rate throttles and
nacks all happen at the front door, so an unauthorized or oversized or
flooding client costs the ordering pipeline nothing. Until this PR the
farm's ingress edge was a bare `ShardRouter` library object — any
process could append anything to ``rawdeltas`` at full rate and the
kernel deli would dutifully sequence it.

`IngressRole` is that front door as a SUPERVISED role (full `_Role`
machinery: fenced lease, heartbeat, checkpoint cadence, exactly-once
``inOff`` recovery):

    ingress (client submits) ──> IngressRole ──┬─> rawdeltas[-p{k}|-{rid}]
                                               └─> nacks

- **Admission owns the write path.** Clients submit to the ``ingress``
  topic; ONLY admitted records reach the raw partition topics, each
  stamped with its ingress offset (``inOff`` — riding the codec's
  existing in_off column, so admission costs the columnar fast path
  nothing). Every rejection is a NACK RECORD on the ``nacks`` topic
  (never sequenced), carrying the reason taxonomy:

    - ``auth`` (code 401): riddler token validation failed
      (`server.riddler.TenantManager` over ``<dir>/tenants.json``;
      enforced whenever the tenants file exists). Nacks are SIGNED
      with the tenant's key when the tenant resolves (`sign_nack` /
      `verify_nack`), so a client can authenticate its rejection.
    - ``size`` (code 413): per-record contents bytes over
      `max_record_bytes`, or a wire boxcar with more than
      `max_boxcar_ops` ops / oversized total payload.
    - ``rate`` (code 429, ``retryAfter``): the per-tenant token
      bucket (`rate_limit` ops/s, burst `rate_burst`) ran dry.
    - ``backpressure`` (code 429, ``retryAfter``): the doc's
      partition has more than `backlog_max` admitted-but-unsequenced
      records (ingress routed count minus the deli's checkpointed
      offset, refreshed every `backlog_poll_s`). Overload degrades
      VISIBLY — throttle-nacks with retry-after and a ``degraded``
      heartbeat flag the supervisor's /healthz surfaces — instead of
      growing the raw log without bound.

- **Exactly-once over N+1 output legs.** Recovery binds the fence on
  the nacks topic AND every raw leg the fabric has ever written (the
  topology history when elastic), scans them all for the durable
  ``inOff`` prefix, silently re-decides the input gap, and re-emits
  only decisions whose input left no durable output anywhere — so an
  ingress crash never duplicates a nack and never drops an admitted
  submit. Decisions for inputs that died with NO durable output are
  re-decided at recovery time: auth and size are pure functions of
  the record (same verdict), rate/backpressure are functions of load
  (admission control is inherently time-based; the record was never
  acknowledged either way). A duplicated ADMIT (the elastic router's
  epoch re-route, a retried multi-leg append) is silenced downstream
  by the deli's resubmission dedup — the same idempotence the
  at-least-once client contract already relies on.

- **Every decision is a labeled metric**: ``ingress_admitted_total``,
  ``ingress_nacks_total{reason=...}``,
  ``ingress_backlog_gauge{partition=...}``, ``ingress_overloaded``.
  The ``ingress_*`` counters also ride the ``/slo`` body
  (`utils.metrics.slo_summary`) so refused load shows up next to the
  latency quantiles of the load that was admitted.

- **Admission is a traced stage.** In wire-trace mode
  (``FLUID_TRACE_WIRE=1``) every admitted record is stamped with
  ``tr_adm`` (one clock read — the same ``now`` the admission checks
  use); the deli folds it into the wire ``tr`` dict as ``adm`` and
  observes ``op_stage_ms{stage=admit_to_stamp}`` from the SAME clock
  read that stamps the record — recovery-silent like every other
  stage, so a restart's replay never double-observes.

The socket layer tails the ``nacks`` topic
(`socket_service.FarmReadServer(nacks=True)` pushes them to
subscribed sessions), closing the submit→nack feedback loop the
reference's WS door gives clients.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from .columnar_log import make_topic
from .queue import partition_of, range_for_doc
from .riddler import AuthError, TenantManager
from .supervisor import _Role, _topic_path

__all__ = [
    "INGRESS_TOPIC",
    "IngressRole",
    "NACKS_TOPIC",
    "NACK_AUTH",
    "NACK_RATE",
    "NACK_SIZE",
    "load_tenants",
    "sign_nack",
    "verify_nack",
    "write_tenants",
]

INGRESS_TOPIC = "ingress"
NACKS_TOPIC = "nacks"

# Nack codes (HTTP-shaped, like the reference's nack contracts).
NACK_AUTH = 401
NACK_SIZE = 413
NACK_RATE = 429  # rate AND backpressure; `reason` tells them apart

# Admitted-record key sets per wire kind: admission STRIPS everything
# else (credentials, stray junk), so the raw topics carry exactly the
# schemas the codec columnizes (+ the inOff admission stamp).
_KIND_KEYS = {
    "op": ("client", "clientSeq", "refSeq", "contents"),
    "join": ("client",),
    "leave": ("client",),
    "boxcar": ("client", "ops"),
}
# The exact BARE key sets (no credentials, no strays): records shaped
# like this take the zero-rebuild canonical fast path — one inOff
# assignment, no new dict.
_KIND_KEYSETS = {
    kind: frozenset(("kind", "doc") + keys)
    for kind, keys in _KIND_KEYS.items()
}
_BOXCAR_OP_KEYS = frozenset(("clientSeq", "refSeq", "contents"))

TENANTS_FILE = "tenants.json"


def write_tenants(shared_dir: str, keys: Dict[str, str]) -> str:
    """Persist the fabric's tenant signing keys (the riddler registry
    the front door enforces). Returns the file path. Writing this file
    TURNS AUTH ON for every ingress role reading the directory."""
    os.makedirs(shared_dir, exist_ok=True)
    path = os.path.join(shared_dir, TENANTS_FILE)
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(dict(keys), f)
    os.replace(tmp, path)
    return path


def load_tenants(shared_dir: str) -> Optional[Dict[str, str]]:
    """The tenant key registry, or None when the fabric runs open
    (no tenants file — the tinylicious-style dev mode)."""
    try:
        with open(os.path.join(shared_dir, TENANTS_FILE)) as f:
            keys = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(keys, dict):
        return None
    return {str(k): str(v) for k, v in keys.items()}


def _nack_core(nack: dict) -> bytes:
    """The byte string a nack signature covers (every client-meaningful
    field, canonical JSON)."""
    return json.dumps(
        {k: nack.get(k) for k in ("doc", "client", "clientSeq", "code",
                                  "reason", "inOff")},
        sort_keys=True, separators=(",", ":"),
    ).encode()


def sign_nack(key: str, nack: dict) -> str:
    """HMAC-SHA256 signature over the nack's core fields with the
    tenant's signing key — the client-verifiable rejection (a forged
    nack cannot carry a valid signature)."""
    return hmac.new(key.encode(), _nack_core(nack),
                    hashlib.sha256).hexdigest()


def verify_nack(key: str, nack: dict) -> bool:
    sig = nack.get("sig")
    if not isinstance(sig, str):
        return False
    return hmac.compare_digest(sign_nack(key, nack), sig)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ[name])
    except (KeyError, ValueError):
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ[name])
    except (KeyError, ValueError):
        return default


class IngressRole(_Role):
    """The supervised admission gate in front of the `ShardRouter`.

    One instance fronts ONE fabric: classic single-partition farm
    (``n_partitions=1`` — admitted records land on the plain
    ``rawdeltas`` topic), static modulo-N fabric, or the elastic
    hash-range topology (``elastic=True`` — routing follows the live
    epoch record, exactly like the library router). Admission knobs
    come from constructor args, falling back to ``FLUID_INGRESS_*``
    env (the supervised-child configuration channel):

    - `max_record_bytes` (env ``FLUID_INGRESS_MAX_BYTES``, default
      256 KiB): contents-byte cap per record AND per boxcar total.
    - `max_boxcar_ops` (``FLUID_INGRESS_MAX_BOXCAR_OPS``, 64).
    - `rate_limit` (``FLUID_INGRESS_RATE``, 0 = off): per-tenant
      token-bucket ops/s; `rate_burst` (``FLUID_INGRESS_BURST``,
      2x rate) is the bucket depth.
    - `backlog_max` (``FLUID_INGRESS_BACKLOG``, 0 = off): per-
      partition admitted-minus-sequenced record budget; beyond it,
      submits for docs hashing there get throttle-nacks until the
      deli catches up. `backlog_poll_s` (0.25) paces the deli-
      checkpoint reads the estimate needs.
    - `retry_after_s` (``FLUID_INGRESS_RETRY_AFTER_S``, 0.25): the
      floor of the ``retryAfter`` hint on throttle nacks.
    """

    name = "ingress"
    in_topic_name = INGRESS_TOPIC
    # The nacks topic doubles as the PRIMARY fenced output leg (the
    # base step's append/fence machinery runs against it); the raw
    # partition legs are routed in `_append_outputs`.
    out_topic_name = NACKS_TOPIC

    def __init__(self, shared_dir: str, owner: str, *,
                 n_partitions: int = 1, elastic: bool = False,
                 max_record_bytes: Optional[int] = None,
                 max_boxcar_ops: Optional[int] = None,
                 rate_limit: Optional[float] = None,
                 rate_burst: Optional[float] = None,
                 backlog_max: Optional[int] = None,
                 backlog_poll_s: float = 0.25,
                 retry_after_s: Optional[float] = None,
                 **kw):
        super().__init__(shared_dir, owner, **kw)
        self.n_partitions = int(n_partitions)
        self.elastic = bool(elastic)
        if self.n_partitions < 1:
            raise ValueError(
                f"n_partitions must be >= 1: {n_partitions}"
            )
        self.max_record_bytes = int(
            max_record_bytes if max_record_bytes is not None
            else _env_float("FLUID_INGRESS_MAX_BYTES", 256 * 1024)
        )
        self.max_boxcar_ops = int(
            max_boxcar_ops if max_boxcar_ops is not None
            else _env_float("FLUID_INGRESS_MAX_BOXCAR_OPS", 64)
        )
        self.rate_limit = float(
            rate_limit if rate_limit is not None
            else _env_float("FLUID_INGRESS_RATE", 0.0)
        )
        self.rate_burst = float(
            rate_burst if rate_burst is not None
            else _env_float("FLUID_INGRESS_BURST",
                            max(1.0, 2.0 * self.rate_limit))
        )
        self.backlog_max = int(
            backlog_max if backlog_max is not None
            else _env_float("FLUID_INGRESS_BACKLOG", 0.0)
        )
        self.backlog_poll_s = float(backlog_poll_s)
        self.retry_after_s = float(
            retry_after_s if retry_after_s is not None
            else _env_float("FLUID_INGRESS_RETRY_AFTER_S", 0.25)
        )
        # Routing surface. Classic farm: ONE raw topic, no suffix —
        # the supervised deli consumes plain "rawdeltas". Fabric:
        # the library ShardRouter, now appending under OUR fence.
        if self.n_partitions > 1 or self.elastic:
            from .shard_fabric import ShardRouter

            self.router: Optional[ShardRouter] = ShardRouter(
                shared_dir, self.n_partitions, self.log_format,
                elastic=self.elastic,
            )
            self.raw_topic = None
        else:
            self.router = None
            self.raw_topic = make_topic(
                _topic_path(shared_dir, "rawdeltas"), self.log_format
            )
        # Riddler gate: enforced iff the tenants file exists.
        self.tenant_keys = load_tenants(shared_dir)
        self.tenants: Optional[TenantManager] = None
        if self.tenant_keys is not None:
            self.tenants = TenantManager()
            for tid, key in self.tenant_keys.items():
                self.tenants.create_tenant(tid, key)
        # Validated-token cache: (tenant, token) -> (exp, documentId).
        # The reference validates per CONNECTION, not per op — a
        # client's stream re-presents one token thousands of times, so
        # the HMAC+base64 work runs once per distinct token and every
        # later record pays a dict probe plus the expiry/doc-binding
        # compares. Bounded; expiry still enforced per record.
        self._token_cache: Dict[Tuple[str, str], Tuple[float, str]] = {}
        # SESSIONS (the alfred connection-auth shape, checkpointed):
        # an {"kind": "auth", doc, client, tenant, token} ingress
        # record validates once and opens a session; subsequent BARE
        # records from that (doc, client) inherit it until expiry —
        # op records then carry no credentials at all, which keeps
        # them on the codec's columnar schema AND off the per-record
        # validation cost. Per-record tokens remain accepted. Value:
        # (expiry, tenant) — the tenant identity feeds rate limiting.
        self._sessions: Dict[Tuple[str, int], Tuple[float, str]] = {}
        # Admission state (checkpointed): per-tenant token buckets and
        # per-raw-leg routed-record counts (the backlog numerator).
        self._buckets: Dict[str, List[float]] = {}
        self._routed: Dict[str, int] = {}
        # Backlog estimate cache (NOT state: recomputed from the deli
        # checkpoints on a poll cadence).
        self._backlogs: Dict[str, int] = {}
        self._backlog_t = 0.0
        self._overloaded: Tuple[str, ...] = ()
        # doc -> raw-leg cache (one consistent-hash per doc, not per
        # record); keyed to the topology epoch when elastic so a
        # split/merge invalidates it wholesale.
        self._leg_cache: Dict[str, str] = {}
        self._leg_cache_epoch: Optional[int] = None
        self._leg_refresh_t = 0.0
        self._leg_topics: Dict[str, Any] = {}
        m = self.metrics
        labels = self._metric_labels()
        self._m_admitted = m.counter("ingress_admitted_total", **labels)
        self._m_dropped = m.counter("ingress_dropped_total", **labels)
        self._m_nacks = {
            reason: m.counter("ingress_nacks_total", reason=reason,
                              **labels)
            for reason in ("auth", "size", "rate", "backpressure")
        }
        self._m_overloaded = m.gauge("ingress_overloaded", **labels)

    # ------------------------------------------------------------ state

    def snapshot_state(self) -> Any:
        return {
            "routed": dict(self._routed),
            "buckets": {t: list(b) for t, b in self._buckets.items()},
            "sessions": [[d, c, exp, ten] for (d, c), (exp, ten)
                         in self._sessions.items()],
        }

    def restore_state(self, state: Any) -> None:
        state = state or {}
        self._routed = {
            str(k): int(v)
            for k, v in (state.get("routed") or {}).items()
        }
        self._buckets = {
            str(t): [float(b[0]), float(b[1])]
            for t, b in (state.get("buckets") or {}).items()
            if isinstance(b, (list, tuple)) and len(b) == 2
        }
        self._sessions = {
            (str(d), int(c)): (float(exp), str(ten))
            for d, c, exp, ten in (state.get("sessions") or ())
        }

    # ---------------------------------------------------------- routing

    def _leg_name(self, doc: str) -> str:
        """The raw-topic name `doc`'s partition maps to under the
        CURRENT topology (also the key routed counts/backlogs use).
        Cached per doc — one consistent-hash per DOCUMENT, not per
        record; an elastic epoch change flushes the cache."""
        if self.router is None:
            return "rawdeltas"
        if self.elastic:
            # Throttled topology refresh (one stat per ~20ms, not one
            # per record): staleness here only mis-keys the backlog
            # estimate for a beat — the actual elastic APPEND goes
            # through the library router, whose post-append epoch
            # recheck re-routes anything a flip stranded.
            now = time.time()
            if now - self._leg_refresh_t > 0.02:
                self._leg_refresh_t = now
                self.router._refresh()
            epoch = self.router.topology["epoch"]
            if epoch != self._leg_cache_epoch:
                self._leg_cache.clear()
                self._leg_cache_epoch = epoch
        leg = self._leg_cache.get(doc)
        if leg is None:
            if len(self._leg_cache) > (1 << 20):
                self._leg_cache.clear()
            if self.elastic:
                leg = range_for_doc(self.router.topology, doc)["raw"]
            else:
                leg = f"rawdeltas-p{partition_of(doc, self.n_partitions)}"
            self._leg_cache[doc] = leg
        return leg

    def _leg_topic(self, leg: str):
        t = self._leg_topics.get(leg)
        if t is None:
            t = self._leg_topics[leg] = make_topic(
                _topic_path(self.shared_dir, leg), self.log_format
            )
        return t

    def _deli_ckpt_key(self, leg: str) -> str:
        """The deli checkpoint key consuming raw leg `leg` (its offset
        is the backlog denominator)."""
        if leg == "rawdeltas":
            return "deli"
        return "deli-" + leg[len("rawdeltas-"):]

    def _raw_scan_topics(self) -> List[Any]:
        """EVERY raw topic this fabric has ever routed to (topology
        history when elastic) — the recovery fence-bind + durable-scan
        set. Retired legs stay in the scan: an admit that landed there
        moments before a split is still this role's durable output."""
        if self.router is None:
            return [self.raw_topic]
        if self.elastic:
            return [
                self.router._topic(n)
                for n in self.router.stage_topic_names("rawdeltas")
            ]
        return list(self.router.topics)

    # -------------------------------------------------------- admission

    def _nack(self, out: List[Any], rec: dict, line_idx: int,
              code: int, reason: str, kind: str,
              retry_after: Optional[float] = None,
              tenant: Optional[str] = None) -> None:
        """`tenant`: the RESOLVED tenant identity (a session-authed
        bare record carries none on the wire) — the signing key lookup
        falls back to the record's own tenant field."""
        ops = rec.get("ops")
        if rec.get("kind") == "boxcar" and isinstance(ops, list) and ops:
            first = ops[0] if isinstance(ops[0], dict) else {}
            cseq = first.get("clientSeq", 0)
        else:
            cseq = rec.get("clientSeq", 0)
        nack: Dict[str, Any] = {
            "kind": "nack",
            "doc": rec.get("doc"),
            "client": rec.get("client", -1),
            "clientSeq": cseq if isinstance(cseq, int) else 0,
            "code": code,
            "reason": f"{kind}: {reason}",
            "inOff": line_idx,
        }
        if retry_after is not None:
            nack["retryAfter"] = round(float(retry_after), 4)
        if not isinstance(tenant, str):
            t = rec.get("tenant")
            tenant = t if isinstance(t, str) else None
        key = (self.tenant_keys or {}).get(tenant) \
            if isinstance(tenant, str) else None
        if key is not None:
            # Signed rejection: the client verifies the nack really
            # came from a holder of its tenant key (`verify_nack`).
            nack["sig"] = sign_nack(key, nack)
        self._m_nacks[kind].inc()
        out.append(("nack", None, nack))

    def _canonical(self, rec: dict, line_idx: int) -> Optional[dict]:
        """The admitted wire form: schema keys only + the admission
        stamp. None when a required field is missing/mistyped (the
        record is DROPPED — there is no one to nack). A BARE record
        (exactly the schema keys — the session-auth hot path) is
        stamped in place with no rebuild."""
        kind = rec["kind"]
        if rec.keys() == _KIND_KEYSETS[kind] \
                and isinstance(rec["client"], int):
            if kind == "op":
                if isinstance(rec["clientSeq"], int) \
                        and isinstance(rec["refSeq"], int):
                    rec["inOff"] = line_idx
                    return rec
                return None
            if kind == "boxcar":
                ops = rec["ops"]
                if isinstance(ops, list) and all(
                    isinstance(op, dict)
                    and op.keys() == _BOXCAR_OP_KEYS
                    and isinstance(op["clientSeq"], int)
                    and isinstance(op["refSeq"], int)
                    for op in ops
                ):
                    rec["inOff"] = line_idx
                    return rec
                # fall through: normalize partial boxcar ops below
            else:
                rec["inOff"] = line_idx
                return rec
        out: Dict[str, Any] = {"kind": kind, "doc": rec["doc"]}
        for k in _KIND_KEYS[kind]:
            if k not in rec:
                return None
            out[k] = rec[k]
        if not isinstance(out.get("client"), int):
            return None
        if kind == "op" and not (
            isinstance(out["clientSeq"], int)
            and isinstance(out["refSeq"], int)
        ):
            return None
        if kind == "boxcar":
            ops = out["ops"]
            if not isinstance(ops, list) or not all(
                isinstance(op, dict)
                and isinstance(op.get("clientSeq"), int)
                and isinstance(op.get("refSeq", 0), int)
                for op in ops
            ):
                # A non-int clientSeq/refSeq past this gate would be a
                # poison pill crash-looping the deli downstream.
                return None
            out["ops"] = [
                {"clientSeq": op["clientSeq"],
                 "refSeq": op.get("refSeq", 0),
                 "contents": op.get("contents")}
                for op in ops
            ]
        if isinstance(rec.get("tr_sub"), (int, float)):
            # The wire-trace submit stamp rides through admission so
            # the deli's submit_to_stamp span still starts at the
            # client (trace runs forgo the columnar fast path anyway).
            out["tr_sub"] = rec["tr_sub"]
        out["inOff"] = line_idx
        return out

    def _payload_bytes(self, rec: dict) -> int:
        if rec["kind"] == "boxcar":
            return sum(
                len(json.dumps(op.get("contents"), separators=(",", ":")))
                for op in rec.get("ops") or ()
                if isinstance(op, dict)
            )
        if rec["kind"] != "op":
            return 0
        return len(json.dumps(rec.get("contents"), separators=(",", ":")))

    def _take_tokens(self, tenant: str, cost: float,
                     now: float) -> Tuple[bool, float]:
        """Token-bucket draw; returns (admitted, retry_after_s)."""
        b = self._buckets.get(tenant)
        if b is None:
            b = self._buckets[tenant] = [self.rate_burst, now]
        tokens = min(self.rate_burst,
                     b[0] + (now - b[1]) * self.rate_limit)
        b[1] = now
        if tokens >= cost:
            b[0] = tokens - cost
            return True, 0.0
        b[0] = tokens
        return False, max(self.retry_after_s,
                          (cost - tokens) / max(1e-9, self.rate_limit))

    def _refresh_backlogs(self, now: float) -> None:
        if now - self._backlog_t < self.backlog_poll_s:
            return
        self._backlog_t = now
        overloaded = []
        for leg, routed in self._routed.items():
            env = self.ckpt.load(self._deli_ckpt_key(leg))
            consumed = int(((env or {}).get("state") or {})
                           .get("offset", 0))
            backlog = max(0, routed - consumed)
            self._backlogs[leg] = backlog
            self.metrics.gauge(
                "ingress_backlog_gauge", partition=leg,
                **self._metric_labels(),
            ).set(backlog)
            if self.backlog_max and backlog >= self.backlog_max:
                overloaded.append(leg)
        self._overloaded = tuple(sorted(overloaded))
        self._m_overloaded.set(1.0 if self._overloaded else 0.0)

    # ------------------------------------------------------------- pump

    def _validate_token(self, rec: dict, now: float) -> Optional[str]:
        """Per-record token check through the validated cache; returns
        the failure reason, or None on success."""
        tenant = rec.get("tenant")
        tenant_id = tenant if isinstance(tenant, str) else "_anon"
        token = rec.get("token") or ""
        ck = (tenant_id, token)
        hit = self._token_cache.get(ck)
        if hit is not None and now < hit[0] and hit[1] == rec["doc"]:
            return None  # cached-valid, same doc binding, unexpired
        try:
            claims = self.tenants.validate_token(
                token, tenant_id, rec["doc"]
            )
        except AuthError as exc:
            return str(exc)
        if len(self._token_cache) > 4096:
            self._token_cache.clear()
        self._token_cache[ck] = (
            float(claims.get("exp", 0)),
            str(claims.get("documentId")),
        )
        return None

    def process(self, line_idx: int, rec: Any, out: List[Any]) -> None:
        if not isinstance(rec, dict) \
                or not isinstance(rec.get("doc"), str):
            self._m_dropped.inc()
            return
        kind = rec.get("kind")
        now = time.time()
        if kind == "auth":
            # Session open (the alfred connection-auth shape): one
            # token validation covers the (doc, client)'s subsequent
            # BARE records until the token's expiry. Pure state — no
            # output record, so exactly-once needs nothing extra
            # (recovery's gap replay re-opens it deterministically).
            client = rec.get("client")
            if self.tenants is None or not isinstance(client, int):
                self._m_dropped.inc()  # open fabric: sessions no-op
                return
            why = self._validate_token(rec, now)
            if why is not None:
                self._nack(out, rec, line_idx, NACK_AUTH, why, "auth")
                return
            ten = rec.get("tenant")
            ten = ten if isinstance(ten, str) else "_anon"
            hit = self._token_cache.get((ten, rec.get("token") or ""))
            self._sessions[(rec["doc"], client)] = (
                float(hit[0]) if hit else now, ten
            )
            return
        if kind not in _KIND_KEYS:
            self._m_dropped.inc()
            return
        tenant = rec.get("tenant")
        tenant_id = tenant if isinstance(tenant, str) else "_anon"
        if self.tenants is not None:
            if "token" in rec:
                why = self._validate_token(rec, now)
                if why is not None:
                    self._nack(out, rec, line_idx, NACK_AUTH, why,
                               "auth")
                    return
            else:
                sess = self._sessions.get((rec["doc"],
                                           rec.get("client")))
                if sess is None or now >= sess[0]:
                    self._nack(
                        out, rec, line_idx, NACK_AUTH,
                        "no live session for this doc/client "
                        "(send an auth record or a token)", "auth",
                    )
                    return
                tenant_id = sess[1]  # rate limits bill the session
        rec2 = self._canonical(rec, line_idx)
        if rec2 is None:
            self._m_dropped.inc()
            return
        if rec2["kind"] == "boxcar" \
                and len(rec2["ops"]) > self.max_boxcar_ops:
            self._nack(out, rec, line_idx, NACK_SIZE,
                       f"boxcar of {len(rec2['ops'])} ops > "
                       f"{self.max_boxcar_ops}", "size",
                       tenant=tenant_id)
            return
        nbytes = self._payload_bytes(rec2)
        if nbytes > self.max_record_bytes:
            self._nack(out, rec, line_idx, NACK_SIZE,
                       f"{nbytes} contents bytes > "
                       f"{self.max_record_bytes}", "size",
                       tenant=tenant_id)
            return
        if self.rate_limit > 0:
            cost = (len(rec2["ops"]) if rec2["kind"] == "boxcar"
                    else 1.0)
            ok, retry = self._take_tokens(tenant_id, cost, now)
            if not ok:
                self._nack(out, rec, line_idx, NACK_RATE,
                           f"tenant {tenant_id!r} over "
                           f"{self.rate_limit:g} ops/s", "rate",
                           retry_after=retry, tenant=tenant_id)
                return
        leg = self._leg_name(rec2["doc"])
        if self.backlog_max:
            self._refresh_backlogs(now)
            if leg in self._overloaded:
                self._nack(out, rec, line_idx, NACK_RATE,
                           f"partition {leg} backlog "
                           f"{self._backlogs.get(leg, 0)} >= "
                           f"{self.backlog_max}", "backpressure",
                           retry_after=self.retry_after_s,
                           tenant=tenant_id)
                return
        if self.trace_wire:
            # The admission stamp (`tr_adm`): rides the admitted wire
            # record to the deli, which folds it into the "tr" dict as
            # "adm" and observes op_stage_ms{stage=admit_to_stamp} —
            # the front door's queue+hop cost becomes a first-class
            # /slo stage. ONE clock read: `now` above already serves
            # the rate/session checks; no extra time() on the admit
            # path. Recovery re-decides stamp at replay time, which is
            # still earlier than any downstream stamp of the re-emitted
            # record, so monotonicity (adm <= stamp) holds across a
            # crash.
            rec2["tr_adm"] = now
        self._routed[leg] = self._routed.get(leg, 0) + 1
        self._m_admitted.inc()
        out.append(("admit", leg, rec2))

    # ---------------------------------------------------------- appends

    def _append_outputs(self, out: List[Any]) -> int:
        """Route one batch's decisions: admits to their raw partition
        legs (grouped by the leg admission already computed — one
        fenced append per leg, no second consistent-hash pass), nacks
        to the nacks topic. Every leg append runs under its own
        durable-retry budget; a retried multi-leg batch may duplicate
        an admit, which the deli's resubmission dedup silences (see
        the module docstring's exactly-once story). ELASTIC admits go
        through the library router instead: its post-append epoch
        recheck covers the stalled-topology hole per-leg grouping
        cannot."""
        nacks = [rec for tag, _leg, rec in out if tag == "nack"]
        n = 0
        if self.elastic:
            admits = [rec for tag, _leg, rec in out if tag == "admit"]
            if admits:
                def _route() -> int:
                    self.router.append(admits, fence=self.fence,
                                       owner=self.owner)
                    # The router reports record counts, not bytes:
                    # approximate the checkpoint-cadence byte signal.
                    return len(admits) * 64

                n += self._durable(_route)
        else:
            by_leg: Dict[str, List[dict]] = {}
            for tag, leg, rec in out:
                if tag == "admit":
                    by_leg.setdefault(leg, []).append(rec)
            for leg, recs in by_leg.items():
                topic = (self.raw_topic if self.router is None
                         else self._leg_topic(leg))
                n += self._durable(lambda t=topic, r=recs:
                                   t.append_many(r, fence=self.fence,
                                                 owner=self.owner))
        if nacks:
            n += self._durable(lambda: self.out_topic.append_many(
                nacks, fence=self.fence, owner=self.owner
            ))
        return n

    # The heartbeat exports overload next to disk degradation: an
    # operator watching /healthz sees a backpressuring front door as
    # "degraded", which is exactly what it is.
    def heartbeat(self, force: bool = False) -> None:
        prev = self.degraded
        self.degraded = bool(prev or self._overloaded)
        try:
            super().heartbeat(force)
        finally:
            self.degraded = prev

    # --------------------------------------------------------- recovery

    def _recover_inner(self) -> None:
        env = self.ckpt.load(self.name)
        self.offset = 0
        if env is not None:
            st = env["state"]
            self.offset = int(st.get("offset", 0))
            self.restore_state(st.get("state"))
        else:
            self.restore_state(None)
        # Bind our fence on EVERY output leg before scanning any: the
        # nacks topic plus every raw topic the fabric has ever routed
        # to — a deposed front door's in-flight append to any of them
        # is rejected from here on.
        legs = [self.out_topic] + self._raw_scan_topics()
        for t in legs:
            self._durable(lambda t=t: t.append_many(
                [], fence=self.fence, owner=self.owner
            ))
        # Durable decisions per input offset, across all legs.
        # Admission is 1 input -> at most 1 output, so presence is the
        # whole story (a duplicated admit — elastic re-route, retried
        # append — just counts the input done twice).
        done: Dict[int, int] = {}
        for t in legs:
            entries, _ = t.read_entries(0)
            for _i, r in entries:
                if isinstance(r, dict) and isinstance(
                        r.get("inOff"), int) and r["inOff"] >= self.offset:
                    done[r["inOff"]] = done.get(r["inOff"], 0) + 1
        if not done:
            return
        max_done = max(done)
        gap, next_off = self.in_topic.read_entries(self.offset)
        sink: List[Any] = []
        for line_idx, rec in gap:
            if line_idx > max_done:
                next_off = line_idx
                break
            self.process(line_idx, rec, sink)
        else:
            next_off = max(self.offset, max_done + 1, next_off)
        # Re-emit ONLY decisions whose input left no durable output on
        # any leg (the crash window's lost suffix); everything else
        # was a silent replay that rebuilt the admission state.
        missing = [ent for ent in sink if ent[2]["inOff"] not in done]
        if missing:
            self._append_outputs(missing)
        self.offset = next_off
        self._reader = None
        self.checkpoint()
