"""Per-document total-order sequencer (the deli role).

Scalar, host-side implementation of the sequencing semantics in the
reference's deli lambda (server/routerlicious/packages/lambdas/src/deli/
lambda.ts): stamp monotonically increasing sequence numbers, track each
connected client's reference sequence number
(ClientSequenceNumberManager, clientSeqManager.ts:22), maintain the
minimum sequence number (MSN) as the min over connected clients' refSeqs,
nack ops whose refSeq is below the MSN (lambda.ts:967), and evict idle
clients so the MSN can advance.

The batched TPU kernel version (10k documents sequenced per call) is in
fluidframework_tpu/ops/sequencer_kernel.py; this class is its oracle.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple, Union

from ..protocol.messages import (
    DocumentMessage,
    MessageType,
    NackMessage,
    SequencedMessage,
)

NACK_STALE_REFSEQ = 400
NACK_UNKNOWN_CLIENT = 403
NACK_OUT_OF_ORDER = 422
NACK_FUTURE_REFSEQ = 416


# Nack reason wording, shared with the batched kernel deli
# (server/deli_kernel.py) so both impls emit identical text where the
# kernel's host mirror has the inputs (codes are the wire contract;
# reasons are for humans and logs).

def stale_refseq_reason(ref_seq: int, min_seq: int) -> str:
    return f"refSeq {ref_seq} below MSN {min_seq}"


def future_refseq_reason(ref_seq: int, head_seq: int) -> str:
    return f"refSeq {ref_seq} ahead of head {head_seq}"


def out_of_order_reason(client_seq: int, expected: int) -> str:
    return f"clientSeq {client_seq}, expected {expected}"


@dataclass
class _ClientState:
    ref_seq: int
    client_seq: int
    last_update: float
    can_evict: bool = True


class DocumentSequencer:
    """Sequences one document's op stream and tracks its MSN."""

    def __init__(self, doc_id: str = "doc"):
        self.doc_id = doc_id
        self.seq = 0
        self.min_seq = 0
        self.clients: Dict[int, _ClientState] = {}
        # MSN = min over clients' refSeqs; recomputing per stamp costs
        # O(clients) on the hottest path, so the min is cached and
        # recomputed only when a refSeq or the membership changes
        # (deli's ClientSequenceNumberManager keeps a HEAP for the
        # same reason, clientSeqManager.ts:22).
        self._msn_dirty = True

    # ------------------------------------------------------- membership

    def join(self, client_id: int, now: Optional[float] = None) -> SequencedMessage:
        """Admit a client (reference: deli handles ClientJoin by adding
        to the MSN heap)."""
        self.clients[client_id] = _ClientState(
            ref_seq=self.seq, client_seq=0, last_update=now or time.time()
        )
        self._msn_dirty = True
        return self._stamp(
            client_id=client_id,
            client_seq=0,
            ref_seq=self.seq,
            type_=MessageType.CLIENT_JOIN,
            contents=client_id,
        )

    def leave(self, client_id: int) -> Optional[SequencedMessage]:
        if client_id not in self.clients:
            return None
        self.clients.pop(client_id)
        self._msn_dirty = True
        return self._stamp(
            client_id=client_id,
            client_seq=0,
            ref_seq=self.seq,
            type_=MessageType.CLIENT_LEAVE,
            contents=client_id,
        )

    # ------------------------------------------------------- sequencing

    def sequence(
        self, client_id: int, msg: DocumentMessage, now: Optional[float] = None
    ) -> Union[SequencedMessage, NackMessage]:
        """Stamp one client message with the next sequence number, or
        nack it (stale refSeq / unknown client / out-of-order
        clientSeq), mirroring deli's ticket() (lambda.ts:818)."""
        state = self.clients.get(client_id)
        if state is None:
            return NackMessage(
                client_id, msg.client_seq, NACK_UNKNOWN_CLIENT, "unknown client"
            )
        if msg.ref_seq < self.min_seq:
            return NackMessage(
                client_id,
                msg.client_seq,
                NACK_STALE_REFSEQ,
                stale_refseq_reason(msg.ref_seq, self.min_seq),
            )
        if msg.ref_seq > self.seq:
            # A refSeq ahead of the head would drive the MSN above the
            # sequence number and permanently nack every honest client
            # (the MSN invariant: minSeq <= seq, reference deli ticket()
            # rejects invalid refSeqs the same way).
            return NackMessage(
                client_id,
                msg.client_seq,
                NACK_FUTURE_REFSEQ,
                future_refseq_reason(msg.ref_seq, self.seq),
            )
        if msg.client_seq != state.client_seq + 1:
            return NackMessage(
                client_id,
                msg.client_seq,
                NACK_OUT_OF_ORDER,
                out_of_order_reason(msg.client_seq, state.client_seq + 1),
            )
        state.client_seq = msg.client_seq
        if msg.ref_seq != state.ref_seq:
            state.ref_seq = msg.ref_seq
            self._msn_dirty = True
        state.last_update = now or time.time()
        return self._stamp(
            client_id=client_id,
            client_seq=msg.client_seq,
            ref_seq=msg.ref_seq,
            type_=msg.type,
            contents=msg.contents,
            metadata=msg.metadata,
            address=msg.address,
        )

    def _stamp(
        self,
        client_id: int,
        client_seq: int,
        ref_seq: int,
        type_: MessageType,
        contents=None,
        metadata=None,
        address=None,
    ) -> SequencedMessage:
        self.seq += 1
        self._update_msn()
        return SequencedMessage(
            sequence_number=self.seq,
            minimum_sequence_number=self.min_seq,
            client_id=client_id,
            client_seq=client_seq,
            ref_seq=ref_seq,
            type=type_,
            contents=contents,
            metadata=metadata,
            address=address,
            timestamp=time.time(),
        )

    def _update_msn(self) -> None:
        # MSN = min over connected clients' refSeqs; with no clients the
        # MSN trails the head (deli: msn == seq when no clients so
        # summaries can collect everything).
        if self.clients:
            if not self._msn_dirty:
                return  # cached: no refSeq/membership change since
            msn = min(s.ref_seq for s in self.clients.values())
            self._msn_dirty = False
        else:
            msn = self.seq
        # MSN is monotone even across eviction races.
        self.min_seq = max(self.min_seq, msn)

    def evict_idle(self, older_than: float) -> List[SequencedMessage]:
        """Evict clients idle since before `older_than` (deli's idle
        eviction keeps the MSN advancing)."""
        out = []
        for cid, st in list(self.clients.items()):
            if st.can_evict and st.last_update < older_than:
                msg = self.leave(cid)
                if msg is not None:
                    out.append(msg)
        return out

    # ------------------------------------------------------- checkpoint

    def checkpoint(self) -> dict:
        """Serializable sequencer state (reference: deli
        checkpointContext.ts writes the equivalent to Mongo)."""
        return {
            "doc_id": self.doc_id,
            "seq": self.seq,
            "min_seq": self.min_seq,
            "clients": {
                str(cid): {
                    "ref_seq": st.ref_seq,
                    "client_seq": st.client_seq,
                    "last_update": st.last_update,
                }
                for cid, st in self.clients.items()
            },
        }

    @classmethod
    def restore(cls, state: dict) -> "DocumentSequencer":
        seq = cls(state["doc_id"])
        seq.seq = state["seq"]
        seq.min_seq = state["min_seq"]
        for cid, st in state["clients"].items():
            seq.clients[int(cid)] = _ClientState(
                ref_seq=st["ref_seq"],
                client_seq=st["client_seq"],
                last_update=st["last_update"],
            )
        return seq
