"""Retention plane: fenced op-log truncation + castore GC — the
summary-then-prune contract that keeps the farm's disk bounded.

The farm sequences, fans out and summarizes, but until this module its
disk only ever grew — the one thing a production ordering service can
never do. The reference's scribe prunes the Mongo deltas collection
behind each accepted summary and gitrest's objects are garbage-
collected from the live refs (SURVEY §S1); `RetentionRole` is that
contract as a SIXTH supervised role (full `server.supervisor._Role`
machinery: fenced lease, heartbeat, checkpoint cadence, exactly-once
recovery):

- **Coverage** — the role consumes the ``summaries`` manifest topic:
  a doc's newest durable manifest covers every sequenced record of
  that doc at/below its seq (`SummarizerRole`'s safety argument — any
  later reader boots from the summary and needs only the tail).
- **Fenced TRUNCATE** — once a topic's prefix is covered (and every
  tracked consumer/producer checkpoint is past it, and a ``keep_tail``
  of newest records is spared for live tails), the role appends a
  COMMITTED RETENTION RECORD to its own fenced ``retention`` topic
  and only then physically reclaims the prefix
  (`columnar_log.ColumnarFileTopic.truncate_prefix`: header + suffix
  swapped in atomically; logical offsets never move). Torn-truncate
  safe by ordering: a crash before the commit record reclaims
  nothing; a crash after it is ROLLED FORWARD by recovery (re-execute
  the newest committed cut per topic — idempotent, the base only
  grows); a deposed zombie dies at its own topic's fence before any
  byte goes away.
- **Mark-and-sweep GC** — unreferenced `server.castore` blobs are
  swept from the durable store, rooted at the newest ``keep_summaries``
  manifests per doc plus every named ref. Concurrent-safe against
  in-flight summary writes via an EPOCH PIN: the summarizer pins the
  store (`write_pin`) before its first blob put of an emission round
  and clears the pin once the round's manifests are durably appended;
  the sweep never deletes a blob newer than the oldest live pin (or
  younger than ``gc_grace_s``). Pins expire (`PIN_TTL_S`) so a dead
  summarizer cannot block GC forever — safe because recovery re-puts
  its blobs (content-addressed `put` recreates a missing file) before
  re-emitting the manifests that reference them.

The truncation clamps, spelled out (every one conservative):

- **summary coverage** — an op record reclaims only once its doc's
  newest durable manifest seq is at/past it; docs that never
  summarize (frozen, undecided) pin the log rather than lose data.
- **consumer floor** — min checkpointed offset over the configured
  consumer roles (missing checkpoint = offset 0 = blocks), so no
  supervised consumer can ever find its input truncated.
- **producer floor** — records carrying ``inOff`` at/past their
  PRODUCER's checkpointed offset are retained: the producer's
  exactly-once recovery scans its output topic for that durable
  prefix, and reclaiming it would make recovery re-emit (duplicate)
  the gap. A producer counts as present once its heartbeat or
  checkpoint exists.
- **keep_tail** — the newest records are always spared, so realtime
  tails (socket pushers, flight readers) a checkpoint never tracks
  are structurally ahead of every cut.

Columnar log format only: JSONL files have no truncation header, and
the role says so loudly instead of silently never reclaiming.
`tools/chaos_run.py --retention` drives the kill-during-truncate /
kill-during-GC fault points; `testing.scenarios.run_week_of_traffic`
is the week-of-traffic churn gate (disk high-water mark bounded while
live, reconnecting and cold-from-summary clients stay bit-identical).
"""

from __future__ import annotations

import json
import os
import signal
import time
from typing import Any, Dict, List, Optional, Tuple

from .castore import ContentAddressedStore
from .columnar_log import ColumnarFileTopic, make_tail_reader, make_topic
from .ingress import _env_float, _env_int
from .supervisor import _Role, _topic_path

__all__ = [
    "PIN_TTL_S",
    "RETENTION_FAULT_ENV",
    "RetentionRole",
    "clear_pin",
    "disk_usage",
    "live_pin_floor",
    "write_pin",
]

# Env knobs (the supervisor's child_env seam carries them to the
# retention child; explicit ctor args win).
INTERVAL_ENV = "FLUID_RETENTION_INTERVAL"
MIN_BYTES_ENV = "FLUID_RETENTION_MIN_BYTES"
TOPICS_ENV = "FLUID_RETENTION_TOPICS"
CONSUMERS_ENV = "FLUID_RETENTION_CONSUMERS"
GRACE_ENV = "FLUID_RETENTION_GRACE"
KEEP_TAIL_ENV = "FLUID_RETENTION_KEEP_TAIL"
# Seeded fault points (the chaos harness's kill-during-truncate /
# kill-during-GC axis): a JSON spec file ``{"point": "truncate"|"gc"}``
# — when the role reaches the named point it consumes the spec and
# SIGKILLs itself, so recovery's roll-forward is what the test
# exercises, at exactly the nastiest instant.
RETENTION_FAULT_ENV = "FLUID_RETENTION_FAULT"

DEFAULT_TOPICS = ("deltas", "rawdeltas")
# Deltas consumers the conservative default tracks (a missing
# checkpoint reads as offset 0 and blocks truncation, so listing a
# role that does not exist in a given farm STALLS reclaim rather than
# corrupting it — the supervisor passes the exact live set).
DEFAULT_CONSUMERS = ("scriptorium", "broadcaster", "scribe",
                     "summarizer")
# Producer checkpoint keys per topic base: records stamped ``inOff``
# at/past the producer's checkpointed offset must be retained for its
# exactly-once recovery scan. Several candidates = whichever of the
# split/fused shapes this farm runs (presence-detected).
PRODUCERS = {
    "deltas": ("deli",),
    "rawdeltas": ("ingress",),
    "durable": ("scriptorium", "scriptorium_broadcaster"),
    "broadcast": ("broadcaster", "scriptorium_broadcaster"),
    "summaries": ("summarizer",),
    # The front door's nack leg: records with ``inOff`` at/past the
    # ingress role's checkpointed input offset stay — its exactly-once
    # recovery scans nacks for the durable-decision prefix, and
    # reclaiming it would re-nack (duplicate) the gap.
    "nacks": ("ingress",),
}

# A pin whose FILE has not been rewritten for this long is ignored:
# the writer died, and recovery re-puts its blobs before
# re-referencing them. Liveness is the file mtime — a live writer
# heartbeats mid-round by rewriting the pin with its ORIGINAL floor
# (`write_pin(..., t=)`), so a round longer than the TTL keeps its
# early puts covered.
PIN_TTL_S = 60.0


# ---------------------------------------------------------------------------
# summarizer epoch pins (the GC's in-flight-write guard)
# ---------------------------------------------------------------------------


def _pins_dir(shared_dir: str) -> str:
    return os.path.join(shared_dir, "store", "pins")


def write_pin(shared_dir: str, name: str,
              t: Optional[float] = None) -> float:
    """Pin the summary store: blobs put from now on must survive the
    sweep until the pin clears (the manifest referencing them is not
    durable yet). One pin file per writer identity. Returns the floor
    timestamp; a writer mid-round heartbeats by calling again with
    that SAME `t` — the rewrite advances the file mtime (liveness)
    while keeping the floor, so blobs put earlier in a long round
    stay covered past PIN_TTL_S."""
    t = time.time() if t is None else t
    d = _pins_dir(shared_dir)
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, f".{name}.{os.getpid()}.tmp")
    with open(tmp, "w") as f:
        json.dump({"t": t, "name": name}, f)
    os.replace(tmp, os.path.join(d, f"{name}.json"))
    return t


def clear_pin(shared_dir: str, name: str) -> None:
    try:
        os.unlink(os.path.join(_pins_dir(shared_dir), f"{name}.json"))
    except OSError:
        pass


def live_pin_floor(shared_dir: str,
                   now: Optional[float] = None) -> Optional[float]:
    """The oldest LIVE pin timestamp (None: no live pins). The sweep
    must not delete any blob whose mtime is at/after this instant —
    it may be referenced by a manifest still in flight."""
    now = time.time() if now is None else now
    floor: Optional[float] = None
    try:
        names = os.listdir(_pins_dir(shared_dir))
    except OSError:
        return None
    for fn in names:
        if not fn.endswith(".json"):
            continue
        path = os.path.join(_pins_dir(shared_dir), fn)
        try:
            mtime = os.stat(path).st_mtime
            with open(path) as f:
                t = float(json.load(f).get("t", 0.0))
        except (OSError, ValueError, TypeError):
            continue
        if now - mtime > PIN_TTL_S:
            continue  # stale heartbeat: the writer died; recovery re-puts
        floor = t if floor is None else min(floor, t)
    return floor


def disk_usage(shared_dir: str) -> Dict[str, int]:
    """On-disk bytes of the farm's growth surfaces: the op-log topics
    (+ sidecars) and the content-addressed store — the number the
    week-of-traffic churn gate watches."""
    def tree(path: str) -> int:
        total = 0
        for root, _dirs, files in os.walk(path):
            for fn in files:
                try:
                    total += os.path.getsize(os.path.join(root, fn))
                except OSError:
                    pass
        return total

    topics = tree(os.path.join(shared_dir, "topics"))
    castore = tree(os.path.join(shared_dir, "store", "objects"))
    return {"log_bytes": topics, "castore_bytes": castore,
            "total_bytes": topics + castore}


# ---------------------------------------------------------------------------
# the role
# ---------------------------------------------------------------------------


class RetentionRole(_Role):
    """summaries → retention: the summary-then-prune supervised role.

    Consumes the manifest stream to learn per-doc coverage, commits
    every reclaim to its own fenced ``retention`` topic BEFORE bytes
    go away, and sweeps unreferenced castore blobs on a slower
    cadence. Composes with the PR-1 machinery unchanged; its commit
    records carry no ``inOff`` (they are decisions about *state*, not
    deterministic functions of one input record), so the generic
    recovery scan ignores them and recovery instead ROLLS FORWARD the
    newest committed cut per topic — idempotent, since a topic's base
    only advances."""

    name = "retention"
    in_topic_name = "summaries"
    out_topic_name = "retention"

    def __init__(self, *a, topics: Optional[Tuple[str, ...]] = None,
                 consumers: Optional[Tuple[str, ...]] = None,
                 interval_s: Optional[float] = None,
                 gc_interval_s: Optional[float] = None,
                 min_reclaim_bytes: Optional[int] = None,
                 keep_tail: Optional[int] = None,
                 keep_summaries: int = 2,
                 gc_grace_s: Optional[float] = None,
                 **kw):
        super().__init__(*a, **kw)
        if self.log_format != "columnar":
            raise ValueError(
                "RetentionRole needs log_format='columnar': JSONL "
                "files have no truncation header, so a json farm "
                "would silently never reclaim a byte"
            )
        env_topics = os.environ.get(TOPICS_ENV)
        self.topics: Tuple[str, ...] = tuple(
            topics if topics is not None
            else (t.strip() for t in env_topics.split(","))
            if env_topics else DEFAULT_TOPICS
        )
        # The role's OWN topics may be listed too — they take the
        # META pruning rules instead of the generic coverage scan:
        # ``summaries`` keeps the newest `keep_summaries` manifests
        # per doc (plus the summarizer's recovery window),
        # ``retention`` keeps the newest commit per managed topic
        # (all roll-forward ever reads). Off by default: evidence
        # consumers (the chaos harness) read these from offset 0.
        env_cons = os.environ.get(CONSUMERS_ENV)
        self.consumers: Tuple[str, ...] = tuple(
            consumers if consumers is not None
            else (c.strip() for c in env_cons.split(",") if c.strip())
            if env_cons is not None else DEFAULT_CONSUMERS
        )
        self.interval_s = (interval_s if interval_s is not None
                           else _env_float(INTERVAL_ENV, 2.0))
        self.gc_interval_s = (gc_interval_s if gc_interval_s is not None
                              else 2.0 * self.interval_s)
        self.min_reclaim_bytes = (
            min_reclaim_bytes if min_reclaim_bytes is not None
            else _env_int(MIN_BYTES_ENV, 64 * 1024)
        )
        self.keep_tail = (keep_tail if keep_tail is not None
                          else _env_int(KEEP_TAIL_ENV, 256))
        self.keep_summaries = max(1, int(keep_summaries))
        self.gc_grace_s = (gc_grace_s if gc_grace_s is not None
                           else _env_float(GRACE_ENV, 10.0))
        # Coverage state (checkpointed): doc -> newest durable summary
        # seq, and the newest keep_summaries (seq, handle) pairs per
        # doc (the GC roots).
        self.cover: Dict[str, int] = {}
        self.handles: Dict[str, List[List[Any]]] = {}
        # Per managed topic: incremental reader, pending-uncovered
        # record window, and the monotone reclaimable-upto offset.
        self._scan: Dict[str, dict] = {}
        self._retain_t = 0.0
        self._gc_t = 0.0
        # The most recent GC pass's store view (None until a pass has
        # run) — the introspection seam tests and tools read.
        self._store: Optional[ContentAddressedStore] = None
        m = self.metrics
        labels = self._metric_labels()
        self._m_truncs = m.counter("retention_truncations_total",
                                   **labels)
        self._m_trunc_records = m.counter(
            "retention_truncated_records_total", **labels
        )
        self._m_reclaimed = m.counter(
            "retention_reclaimed_bytes_total", **labels
        )
        self._m_gc_runs = m.counter("retention_gc_runs_total", **labels)
        self._m_gc_deleted = m.counter("retention_gc_deleted_total",
                                       **labels)
        self._m_gc_bytes = m.counter("retention_gc_bytes_total",
                                     **labels)
        self._m_blobs = m.gauge("castore_blobs", **labels)
        self._m_blob_bytes = m.gauge("castore_bytes", **labels)

    # ------------------------------------------------------------ state

    def snapshot_state(self) -> Any:
        return {"cover": self.cover, "handles": self.handles}

    def restore_state(self, state: Any) -> None:
        state = state or {}
        self.cover = {str(d): int(s)
                      for d, s in (state.get("cover") or {}).items()}
        self.handles = {str(d): [list(p) for p in hs]
                        for d, hs in (state.get("handles") or {}).items()}
        self._scan = {}

    # ------------------------------------------------------ input fold

    def process(self, line_idx: int, rec: Any, out: List[dict]) -> None:
        if not isinstance(rec, dict) or rec.get("kind") != "summary":
            return
        doc = rec.get("doc")
        if not isinstance(doc, str):
            return
        seq = int(rec.get("seq", 0))
        self.cover[doc] = max(self.cover.get(doc, 0), seq)
        hs = self.handles.setdefault(doc, [])
        # [seq, handle, summaries-topic offset, manifest inOff] — the
        # last two feed the meta pruning rules (keep-depth cut and the
        # summarizer's recovery-window floor).
        hs.append([seq, rec.get("handle"), line_idx,
                   int(rec.get("inOff", -1))
                   if isinstance(rec.get("inOff"), int) else -1])
        hs.sort(key=lambda p: p[0])
        # Eviction happens in `_prune_handles` (per retain pass), not
        # here: bounding the list per record would need the producer
        # floor, and evicting an entry still inside the summarizer's
        # recovery window lets `_summaries_cut` reclaim a manifest
        # the restart scan must find.

    # ---------------------------------------------------------- plumbing

    def _suffixed(self, base: str) -> str:
        """`base` carried to this role's partition slice (classic:
        unchanged; ``-p{k}``/ranged suffixes follow the role name)."""
        if self.partition is None:
            return base
        suffix = self.name[len("retention"):]
        return f"{base}{suffix}"

    def _topic(self, base: str):
        entry = self._scan.get(base)
        if entry is None or entry.get("topic") is None:
            t = make_topic(
                _topic_path(self.shared_dir, self._suffixed(base)),
                self.log_format,
            )
            entry = self._scan.setdefault(base, {
                "topic": None, "reader": None, "pending": [],
                "upto": None, "head": 0,
            })
            entry["topic"] = t
        return entry["topic"]

    def _ckpt_offset(self, key: str) -> int:
        env = self.ckpt.load(key)
        if env is None:
            return 0
        try:
            return int((env.get("state") or {}).get("offset", 0))
        except (TypeError, ValueError):
            return 0

    def _role_present(self, key: str) -> bool:
        """Whether role `key` exists in this farm: it has checkpointed,
        or at least heartbeaten (a role writes its heartbeat on its
        very first step, before any record it stamps can exist)."""
        if self.ckpt.load(key) is not None:
            return True
        return os.path.exists(
            os.path.join(self.shared_dir, "hb", f"{key}.json")
        )

    def _producer_floor(self, base: str) -> Optional[int]:
        """Offset below which ``inOff``-stamped records are safe to
        reclaim (the producer's recovery scan never looks below its
        checkpoint). None = no present producer = no constraint."""
        floors = [
            self._ckpt_offset(self._suffixed(key))
            for key in PRODUCERS.get(base, ())
            if self._role_present(self._suffixed(key))
        ]
        return min(floors) if floors else None

    def _consumer_floor(self, base: str) -> Optional[int]:
        """Min checkpointed input offset over this topic's tracked
        consumers (missing checkpoint = 0 = blocks). None = topic has
        no tracked consumers (a derived feed: summary coverage + the
        keep_tail spare are the whole contract)."""
        if base == "rawdeltas":
            keys = [self._suffixed(k) for k in PRODUCERS["deltas"]]
            keys = [k for k in keys if self._role_present(k)] or \
                [self._suffixed("deli")]
        elif base == "deltas":
            keys = [self._suffixed(c) for c in self.consumers]
        elif base == "ingress":
            # The admission front door is the `ingress` topic's ONE
            # supervised consumer: records at/past its checkpointed
            # input offset are still un-admitted. No presence
            # fallback — a farm managing this topic without the role
            # reads a missing checkpoint as 0 and blocks, never loses.
            keys = [self._suffixed("ingress")]
        else:
            return None
        if not keys:
            return None
        return min(self._ckpt_offset(k) for k in keys)

    # --------------------------------------------------------- the pass

    def step(self, idle_sleep: float = 0.01) -> int:
        # Pin floor BEFORE the manifest poll: a (manifest append +
        # unpin) landing between a post-poll floor read and the sweep
        # would delete a blob a durable-but-unread manifest references
        # — permanently, since the summarizer has checkpointed past
        # the round and nothing re-puts it. Captured pre-poll, either
        # the pin is still live (its floor covers every blob of the
        # round) or the manifest was durable before the poll and the
        # poll returns it (moved > 0 defers the sweep; next pass it
        # is a root).
        pin0 = live_pin_floor(self.shared_dir)
        moved = super().step(idle_sleep)
        if self.fence is not None:
            now = time.time()
            if now - self._retain_t >= self.interval_s:
                self._retain_t = now
                self._retain_pass()
            # GC only from a CAUGHT-UP manifest view (an idle pump =
            # the summaries backlog is drained): the grace window
            # protects blobs whose manifests are merely in flight,
            # not ones a lagging consumer simply has not read yet.
            if moved == 0 and now - self._gc_t >= self.gc_interval_s:
                self._gc_t = now
                self._gc_pass(pin_floor=pin0)
        return moved

    def _scan_topic(self, base: str) -> Optional[dict]:
        """Advance `base`'s incremental scan: fold new records into the
        pending window and pop the reclaimable prefix (coverage only
        grows and floors only advance, so popped stays popped)."""
        topic = self._topic(base)
        if not isinstance(topic, ColumnarFileTopic):
            return None
        entry = self._scan[base]
        if entry["upto"] is None:
            entry["upto"] = topic.base_offsets()[0]
        reader = entry["reader"]
        if reader is None:
            reader = entry["reader"] = make_tail_reader(
                topic, entry["upto"]
            )
        pending: List[tuple] = entry["pending"]
        # Bounded fill: an uncoverable run (docs that never summarize)
        # must not grow the window without limit — scanning simply
        # stalls at the blocker, memory stays flat.
        while len(pending) < 65536:
            batch = reader.poll(4096)
            if not batch:
                break
            for off, rec in batch:
                if isinstance(rec, dict):
                    doc = rec.get("doc")
                    seq = rec.get("seq")
                    pending.append((
                        off,
                        doc if isinstance(doc, str)
                        and isinstance(seq, int) else None,
                        int(seq) if isinstance(seq, int) else 0,
                        int(rec.get("inOff", -1))
                        if isinstance(rec.get("inOff"), int) else -1,
                    ))
                else:
                    pending.append((off, None, 0, -1))
        entry["head"] = reader.next_line
        pfloor = self._producer_floor(base)
        cfloor = self._consumer_floor(base)
        i = 0
        upto = entry["upto"]
        for off, doc, seq, in_off in pending:
            if pfloor is not None and in_off >= pfloor:
                break
            if cfloor is not None and off >= cfloor:
                break
            if doc is not None and self.cover.get(doc, -1) < seq:
                break
            upto = off + 1
            i += 1
        if i:
            del pending[:i]
        entry["upto"] = upto
        return entry

    def _summaries_cut(self) -> int:
        """Manifest-topic cut: keep every doc's newest
        `keep_summaries` manifests (the catch-up discovery set + GC
        roots), everything the summarizer's exactly-once recovery
        window still scans (manifests with ``inOff`` at/past its
        checkpointed input offset), and nothing past our own consumed
        offset. Superseded manifests below all three are dead — no
        reader ever resolves them again."""
        if not self.handles:
            return 0
        cut: Optional[int] = None
        pfloor = self._producer_floor("summaries")
        for hs in self.handles.values():
            keep_from = (hs[-self.keep_summaries][2]
                         if len(hs) >= self.keep_summaries
                         else hs[0][2])
            if pfloor is not None:
                for ent in hs:
                    if len(ent) >= 4 and ent[3] >= pfloor:
                        keep_from = min(keep_from, ent[2])
                        break
            cut = keep_from if cut is None else min(cut, keep_from)
        return min(cut or 0, self.offset)

    def _retention_cut(self) -> int:
        """Own-topic cut: recovery's roll-forward only ever reads the
        NEWEST truncate commit per topic, so everything below the
        oldest of those (older commits, gc evidence) is dead."""
        entries, _ = self.out_topic.read_entries(0)
        newest: Dict[str, int] = {}
        for i, r in entries:
            if isinstance(r, dict) and r.get("kind") == "truncate" \
                    and isinstance(r.get("topic"), str):
                newest[r["topic"]] = i
        return min(newest.values()) if newest else 0

    def _prune_handles(self) -> None:
        """Bound the checkpointed per-doc manifest lists: keep the
        newest `keep_summaries` + 1 (root set + a same-seq
        re-emission spare) AND every manifest still inside the
        summarizer's exactly-once recovery window (``inOff`` at/past
        its checkpointed input offset) — evicting one of those would
        let `_summaries_cut` reclaim a manifest the producer's
        restart scan re-emits, forking the summary stream."""
        pfloor = self._producer_floor("summaries")
        for hs in self.handles.values():
            cut = max(0, len(hs) - (self.keep_summaries + 1))
            if pfloor is not None:
                for i, ent in enumerate(hs[:cut]):
                    if len(ent) >= 4 and ent[3] >= pfloor:
                        cut = i
                        break
            del hs[:cut]

    def _retain_pass(self) -> None:
        self._prune_handles()
        for base in self.topics:
            topic = self._topic(base)
            if not isinstance(topic, ColumnarFileTopic):
                continue
            if base == self.in_topic_name:
                cut = self._summaries_cut()
            elif base == self.out_topic_name:
                cut = self._retention_cut()
            else:
                entry = self._scan_topic(base)
                if entry is None:
                    continue
                cut = min(entry["upto"],
                          max(0, entry["head"] - self.keep_tail))
            cur_r, cur_b = topic.base_offsets()
            if cut <= cur_r:
                continue
            plan_r, plan_b = topic.truncate_prefix(cut, dry_run=True)
            if plan_r <= cur_r or \
                    plan_b - cur_b < self.min_reclaim_bytes:
                continue
            # COMMIT before RECLAIM: the fenced retention record is
            # durable before any byte disappears, so a crash in
            # between is rolled forward by recovery and a deposed
            # zombie dies right here at the fence.
            self._durable(lambda: self.out_topic.append_many(
                [{"kind": "truncate", "topic": base,
                  "records": plan_r, "bytes": plan_b}],
                fence=self.fence, owner=self.owner,
            ))
            self._check_fault("truncate")
            got_r, _got_b = self._durable(
                lambda t=topic, r=plan_r: t.truncate_prefix(r)
            )
            self._m_truncs.inc()
            self._m_trunc_records.inc(got_r - cur_r)
            self._m_reclaimed.inc(plan_b - cur_b)
            self.metrics.gauge(
                "retention_base_records", topic=base,
                **self._metric_labels()
            ).set(got_r)
            self.heartbeat(force=True)

    # --------------------------------------------------------------- GC

    def _gc_pass(self, pin_floor: Optional[float] = None) -> None:
        # A FRESH store per pass: the ref table is loaded from
        # refs.log at construction, and named refs are mark ROOTS —
        # a cached snapshot would let the sweep delete a blob some
        # other process ref'd since the first pass. Construction is
        # one small-file read; the sweep itself dwarfs it.
        store = self._store = ContentAddressedStore(
            prefer_native=False,
            directory=os.path.join(self.shared_dir, "store"),
        )
        roots = set()
        for hs in self.handles.values():
            for ent in hs[-self.keep_summaries:]:
                if len(ent) >= 2 and isinstance(ent[1], str):
                    roots.add(ent[1])
        for name in store.list_refs():
            ref = store.get_ref(name)
            if ref:
                roots.add(ref)
        # Reclaim dead writers' staging files first (put tmps and GC
        # quarantines orphaned by a kill) — they count against the
        # disk bound and nothing else sweeps them.
        store.sweep_tmp()
        now = time.time()
        mtime_bar = now - self.gc_grace_s
        # The caller's PRE-POLL floor (see `step`) — re-reading pins
        # here would reopen the unpin-after-poll window. A second
        # read can only be LESS protective (pins only clear), so the
        # pre-poll capture is the conservative one.
        pin = (pin_floor if pin_floor is not None
               else live_pin_floor(self.shared_dir, now))
        if pin is not None:
            mtime_bar = min(mtime_bar, pin)
        deleted = freed = kept = kept_bytes = 0
        faulted = False
        for key, _path, size, mtime in store.list_blobs():
            if key in roots or mtime >= mtime_bar:
                kept += 1
                kept_bytes += size
                continue
            if store.delete_blob(key, older_than=mtime_bar):
                deleted += 1
                freed += size
                if not faulted:
                    faulted = True
                    self._check_fault("gc")
        if not faulted:
            self._check_fault("gc")
        self._m_gc_runs.inc()
        self._m_gc_deleted.inc(deleted)
        self._m_gc_bytes.inc(freed)
        self._m_blobs.set(kept)
        self._m_blob_bytes.set(kept_bytes)
        if deleted:
            # The gc record is evidence, not a commit: deleting an
            # unreferenced blob needs no roll-forward (a re-put
            # recreates it), so it trails the sweep.
            self._durable(lambda: self.out_topic.append_many(
                [{"kind": "gc", "deleted": deleted, "bytes": freed,
                  "kept": kept}],
                fence=self.fence, owner=self.owner,
            ))

    # --------------------------------------------------------- recovery

    def _recover_inner(self) -> None:
        super()._recover_inner()
        # Roll committed truncations FORWARD: a crash between the
        # commit append and the physical cut re-executes it here —
        # idempotent, the base only advances, and our fence is already
        # bound on the retention topic above (a zombie never reaches
        # this line; its successor's roll-forward is a no-op or the
        # exact same cut).
        entries, _ = self.out_topic.read_entries(0)
        newest: Dict[str, int] = {}
        for _i, r in entries:
            if isinstance(r, dict) and r.get("kind") == "truncate" \
                    and isinstance(r.get("topic"), str):
                newest[r["topic"]] = max(
                    newest.get(r["topic"], 0), int(r.get("records", 0))
                )
        for base, upto in newest.items():
            if base not in self.topics:
                continue
            topic = self._topic(base)
            if isinstance(topic, ColumnarFileTopic):
                self._durable(
                    lambda t=topic, u=upto: t.truncate_prefix(u)
                )

    # ------------------------------------------------------ fault seam

    def _check_fault(self, point: str) -> None:
        spec_path = os.environ.get(RETENTION_FAULT_ENV)
        if not spec_path:
            return
        try:
            with open(spec_path) as f:
                spec = json.load(f)
        except (OSError, ValueError):
            return
        if not isinstance(spec, dict) or spec.get("point") != point:
            return
        try:
            os.unlink(spec_path)  # consume: the fault fires ONCE
        except OSError:
            return
        print(f"retention: seeded kill at {point!r}", flush=True)
        self.heartbeat(force=True)
        os.kill(os.getpid(), signal.SIGKILL)
