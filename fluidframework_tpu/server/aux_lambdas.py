"""Auxiliary pipeline lambdas: copier, foreman, moira.

The small routerlicious lambdas beyond the core four
(server/routerlicious/packages/lambdas/src/{copier,foreman,moira}):

- `CopierLambda` — archives the RAW (pre-sequencing) op stream to
  storage verbatim (copier/lambda.ts): the forensic record of exactly
  what clients submitted, before deli stamped or nacked anything.
- `ForemanLambda` — distributes help tasks to agent clients
  (foreman/lambda.ts): watches the sequenced stream for task
  requests and assigns each to a registered agent (round-robin),
  emitting assignment control messages.
- `MoiraLambda` — revision pusher (moira/lambda.ts): collects
  summary acks and "pushes" each accepted revision (doc, seq, handle)
  to a registry sink.

All three consume the shared topics the way the core lambdas do and
checkpoint their offsets, so they slot into LocalServer's pump and
restart contract."""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional

from ..protocol.messages import MessageType
from .castore import ContentAddressedStore
from .log import LogConsumer, MessageLog, _encode_entry
from .queue import partition_suffix


class CopierLambda:
    """Raw-op archiver: every rawdeltas record — including the
    partitioned ``rawdeltas-p{k}`` ingress of a sharded server — lands
    in the content store under a per-doc archive ref chain. Per-doc
    archive order is safe across partitions because a doc lives in
    exactly one partition."""

    def __init__(self, log: MessageLog, storage: ContentAddressedStore,
                 checkpoint: Optional[dict] = None,
                 batch_size: int = 256):
        self.log = log
        self.storage = storage
        self.batch_size = batch_size
        if checkpoint and "offsets" in checkpoint:
            self._offsets: Dict[str, int] = dict(checkpoint["offsets"])
        elif checkpoint:  # pre-shard checkpoint: the one flat topic
            self._offsets = {"rawdeltas": checkpoint["offset"]}
        else:
            self._offsets = {}
        self.consumers: Dict[str, LogConsumer] = {
            "rawdeltas": LogConsumer(log.topic("rawdeltas"),
                                     self._offsets.get("rawdeltas", 0))
        }
        self._pending: List[Any] = []
        self._chunks: Dict[str, int] = (
            dict(checkpoint["chunks"]) if checkpoint else {}
        )

    # Single-partition face (and pre-shard API): "the" raw consumer.
    @property
    def consumer(self) -> LogConsumer:
        return self.consumers["rawdeltas"]

    def _discover(self) -> None:
        """A sharded `LocalServer` creates its ``rawdeltas-p{k}``
        ingress topics lazily, so re-scan the broker each pump — the
        archive contract is every raw record, whatever the topology."""
        prefix = partition_suffix("rawdeltas", 0)[:-1]  # "rawdeltas-p"
        for name, topic in list(self.log.topics.items()):
            if name.startswith(prefix) and name not in self.consumers:
                self.consumers[name] = LogConsumer(
                    topic, self._offsets.get(name, 0)
                )

    def pump(self) -> int:
        self._discover()
        n = 0
        for consumer in self.consumers.values():
            for entry in consumer.poll():
                self._pending.append(entry)
                n += 1
                if len(self._pending) >= self.batch_size:
                    self._flush()
        if self._pending:
            self._flush()
        return n

    def _flush(self) -> None:
        by_doc: Dict[str, List[Any]] = {}
        for e in self._pending:
            by_doc.setdefault(e.get("doc", "?"), []).append(e)
        self._pending = []
        for doc, entries in by_doc.items():
            idx = self._chunks.get(doc, 0)
            key = self.storage.put(
                json.dumps([_encode_entry(e) for e in entries]).encode()
            )
            self.storage.set_ref(f"rawarchive/{doc}/{idx}", key)
            self._chunks[doc] = idx + 1

    def archived_chunks(self, doc: str) -> int:
        return self._chunks.get(doc, 0)

    def read_archive(self, doc: str) -> List[Any]:
        from .log import _decode_entry

        out: List[Any] = []
        for i in range(self._chunks.get(doc, 0)):
            key = self.storage.get_ref(f"rawarchive/{doc}/{i}")
            out.extend(
                _decode_entry(e)
                for e in json.loads(self.storage.get(key).decode())
            )
        return out

    def checkpoint(self) -> dict:
        offsets = {name: c.checkpoint()
                   for name, c in self.consumers.items()}
        return {"offset": offsets["rawdeltas"], "offsets": offsets,
                "chunks": dict(self._chunks)}


class ForemanLambda:
    """Task distributor: sequenced {"task": name} help requests are
    assigned round-robin to registered agents (the reference assigns
    tasks like 'intel'/'translation' to agent runtimes)."""

    def __init__(self, log: MessageLog, checkpoint: Optional[dict] = None):
        offset = checkpoint["offset"] if checkpoint else 0
        self.consumer = LogConsumer(log.topic("deltas"), offset)
        self.agents: List[Any] = []  # objects with assign(doc, task)
        self.assignments: List[dict] = []
        self._rr = 0

    def register_agent(self, agent: Any) -> None:
        self.agents.append(agent)

    def pump(self) -> int:
        n = 0
        for entry in self.consumer.poll():
            n += 1
            if entry.get("kind") != "op":
                continue
            msg = entry["msg"]
            contents = getattr(msg, "contents", None)
            if (msg.type == MessageType.OP and isinstance(contents, dict)
                    and "helpTask" in contents and self.agents):
                agent = self.agents[self._rr % len(self.agents)]
                self._rr += 1
                record = {
                    "doc": entry["doc"], "task": contents["helpTask"],
                    "seq": msg.sequence_number, "agent": id(agent),
                }
                self.assignments.append(record)
                agent.assign(entry["doc"], contents["helpTask"])
        return n

    def checkpoint(self) -> dict:
        return {"offset": self.consumer.checkpoint()}


class MoiraLambda:
    """Revision pusher: accepted summaries (summaryAck control
    messages) become revision records delivered to a sink."""

    def __init__(self, log: MessageLog,
                 sink: Optional[Callable[[dict], None]] = None,
                 checkpoint: Optional[dict] = None):
        offset = checkpoint["offset"] if checkpoint else 0
        self.consumer = LogConsumer(log.topic("deltas"), offset)
        self.revisions: List[dict] = []
        self.sink = sink

    def pump(self) -> int:
        n = 0
        for entry in self.consumer.poll():
            n += 1
            if entry.get("kind") != "op":
                continue
            msg = entry["msg"]
            if msg.type == MessageType.SUMMARY_ACK:
                rev = {
                    "doc": entry["doc"],
                    "seq": msg.sequence_number,
                    "handle": (msg.contents or {}).get("handle"),
                }
                self.revisions.append(rev)
                if self.sink is not None:
                    self.sink(rev)
        return n

    def checkpoint(self) -> dict:
        return {"offset": self.consumer.checkpoint()}
