"""Content-addressed summary/blob store.

Plays the role git storage plays in the reference (gitrest over
libgit2 — a C++ library — server/gitrest; fronted by historian's
cache): summaries and attachment blobs are immutable blobs addressed
by SHA-256 content hash, with named refs pointing at each document's
latest summary.

Two backends with identical semantics and digests:
- the C++ store (fluidframework_tpu/native/castore.cpp, ctypes-bound,
  compiled on demand) — the native path, used when a compiler is
  available;
- a pure-Python dict store — the always-available fallback.

`ContentAddressedStore(prefer_native=True)` picks automatically;
`.backend` reports which one is live.
"""

from __future__ import annotations

import hashlib
import os
import time
from typing import Dict, Iterator, List, Optional, Tuple


class _PyStore:
    """In-memory or disk-backed store, on-disk format IDENTICAL to the
    native store (objects/<h[0:2]>/<hash> blob files + fsynced
    refs.log journal) so the backends interchange freely."""

    def __init__(self, directory: Optional[str] = None):
        self._blobs: Dict[str, bytes] = {}
        self._refs: Dict[str, str] = {}
        self._dir = directory
        self._refs_f = None
        if directory:
            os.makedirs(os.path.join(directory, "objects"), exist_ok=True)
            refs_path = os.path.join(directory, "refs.log")
            if os.path.exists(refs_path):
                with open(refs_path) as f:
                    for line in f:
                        parts = line.split()
                        if len(parts) == 2:
                            self._refs[parts[0]] = parts[1]

    def _blob_path(self, key: str) -> str:
        return os.path.join(self._dir, "objects", key[:2], key)

    def put(self, content) -> str:
        if isinstance(content, str):
            content = content.encode()
        key = hashlib.sha256(content).hexdigest()
        self._blobs[key] = content
        if self._dir:
            path = self._blob_path(key)
            if not os.path.exists(path):
                os.makedirs(os.path.dirname(path), exist_ok=True)
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "wb") as f:
                    f.write(content)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
        return key

    def get(self, key: str) -> bytes:
        if key in self._blobs:
            return self._blobs[key]
        if self._dir:
            path = self._blob_path(key)
            if os.path.exists(path):
                with open(path, "rb") as f:
                    data = f.read()
                self._blobs[key] = data
                return data
        raise KeyError(key)

    def contains(self, key: str) -> bool:
        if key in self._blobs:
            return True
        return bool(self._dir) and os.path.exists(self._blob_path(key))

    def set_ref(self, name: str, key: str) -> None:
        if not self.contains(key):
            raise KeyError(f"unknown blob {key}")
        self._refs[name] = key
        if self._dir:
            if self._refs_f is None:
                self._refs_f = open(
                    os.path.join(self._dir, "refs.log"), "a"
                )
            self._refs_f.write(f"{name} {key}\n")
            self._refs_f.flush()
            os.fsync(self._refs_f.fileno())  # ref update = durability point

    def get_ref(self, name: str) -> Optional[str]:
        return self._refs.get(name)

    def list_refs(self) -> List[str]:
        return sorted(self._refs)


class ContentAddressedStore:
    """Facade over the native or pure-Python backend."""

    def __init__(self, prefer_native: bool = True,
                 directory: Optional[str] = None):
        """`directory` switches on DURABLE mode (the gitrest role's
        persistence): blobs as content-addressed object files, refs in
        an fsynced append-only journal, state surviving process
        restart. Both backends share the on-disk format."""
        self._impl = None
        self.backend = "python"
        self.directory = directory
        if prefer_native:
            try:
                from ..native import NativeContentStore, load_castore

                lib = load_castore()
                if lib is not None:
                    self._impl = NativeContentStore(lib, directory)
                    self.backend = "native"
            except Exception:
                self._impl = None
        if self._impl is None:
            self._impl = _PyStore(directory)

    def put(self, content) -> str:
        """Store `content`, returning its hash key. In durable mode the
        blob file's mtime is refreshed even when the content-addressed
        write was skipped (file already on disk): the retention GC's
        epoch-pin floor compares blob mtimes, so a deduplicated re-put
        must look as fresh as a first put or a recovery re-put of a
        not-yet-referenced blob could be swept before its manifest
        lands. If a concurrent sweep unlinks the file between the
        backend's existence check and the stamp, the put is retried."""
        key = self._impl.put(content)
        if self.directory:
            path = os.path.join(self.directory, "objects", key[:2], key)
            for attempt in range(5):
                try:
                    os.utime(path)
                    break
                except OSError:
                    if attempt == 4:
                        raise
                    self._impl.put(content)
        return key

    def get(self, key: str) -> bytes:
        return self._impl.get(key)

    def contains(self, key: str) -> bool:
        return self._impl.contains(key)

    def set_ref(self, name: str, key: str) -> None:
        self._impl.set_ref(name, key)

    def get_ref(self, name: str) -> Optional[str]:
        return self._impl.get_ref(name)

    def list_refs(self) -> List[str]:
        return self._impl.list_refs()

    # ------------------------------------------------------- GC surface
    # Both backends share the on-disk object layout
    # (objects/<h[0:2]>/<hash>), so the sweep side of the retention
    # plane's mark-and-sweep GC (`server.retention`) works off the
    # directory itself — backend-agnostic by construction. In-memory
    # stores (no directory) expose nothing to sweep: their lifetime IS
    # the process.

    def list_blobs(self) -> Iterator[Tuple[str, str, int, float]]:
        """Every durable blob as ``(key, path, size_bytes, mtime)``.
        Durable mode only (empty for in-memory stores)."""
        if not self.directory:
            return
        root = os.path.join(self.directory, "objects")
        try:
            shards = sorted(os.listdir(root))
        except OSError:
            return
        for shard in shards:
            sdir = os.path.join(root, shard)
            try:
                names = sorted(os.listdir(sdir))
            except OSError:
                continue
            for name in names:
                if name.startswith(".") or ".tmp." in name:
                    continue  # a writer's in-flight temp: never swept
                path = os.path.join(sdir, name)
                try:
                    st = os.stat(path)
                except OSError:
                    continue  # swept by a concurrent GC pass
                yield name, path, int(st.st_size), float(st.st_mtime)

    def sweep_tmp(self, max_age_s: float = 60.0) -> int:
        """Unlink orphaned writer temp files (``*.tmp.*`` under
        objects/) left by a crash between a tmp write and its atomic
        rename — `put`'s staging file and `delete_blob`'s quarantine
        both park there, nothing else ever removes them, and
        `disk_usage` counts them against the retention plane's disk
        bound. Age-gated: an in-flight writer's tmp lives for
        milliseconds, so anything older than `max_age_s` is a dead
        writer's. Returns the number removed."""
        if not self.directory:
            return 0
        removed = 0
        now = time.time()
        root = os.path.join(self.directory, "objects")
        try:
            shards = os.listdir(root)
        except OSError:
            return 0
        for shard in shards:
            sdir = os.path.join(root, shard)
            try:
                names = os.listdir(sdir)
            except OSError:
                continue
            for name in names:
                if ".tmp." not in name:
                    continue
                p = os.path.join(sdir, name)
                try:
                    if now - os.stat(p).st_mtime > max_age_s:
                        os.unlink(p)
                        removed += 1
                except OSError:
                    continue
        return removed

    def delete_blob(self, key: str,
                    older_than: Optional[float] = None) -> bool:
        """Unlink one durable blob (the GC sweep's only write). Safe
        against re-reference by construction of the caller's contract:
        a later `put` of identical content recreates the file (put
        checks the disk, not a cache). Returns whether a file was
        removed. Any process-local memory cache of the key is dropped
        too, so this store never serves a blob the disk no longer
        holds.

        `older_than` closes the sweep's stat→unlink race against a
        concurrent re-put: the blob is first RENAMED to a quarantine
        name (atomic — a racing `put` now sees no file and rewrites
        it), then its mtime re-checked; a blob refreshed since the
        sweep's stat is renamed back instead of deleted (identical
        content, so restoring over a racing rewrite is harmless)."""
        if not self.directory:
            return False
        path = os.path.join(self.directory, "objects", key[:2], key)
        getattr(self._impl, "_blobs", {}).pop(key, None)
        if older_than is None:
            try:
                os.unlink(path)
                return True
            except OSError:
                return False
        trash = f"{path}.tmp.gc{os.getpid()}"  # ".tmp." infix:
        try:                                   # list_blobs skips it
            os.replace(path, trash)
            if os.stat(trash).st_mtime >= older_than:
                os.replace(trash, path)  # re-put mid-sweep: keep it
                try:
                    # replace() carried the OLD mtime back; stamp the
                    # survivor fresh so a racing put's pin still
                    # covers it on the next pass.
                    os.utime(path)
                except OSError:
                    pass
                return False
            os.unlink(trash)
            return True
        except OSError:
            return False
