"""Content-addressed summary/blob store.

Plays the role git storage plays in the reference (gitrest over
libgit2, server/gitrest; fronted by historian's cache): summaries are
immutable blobs addressed by content hash, with named refs for each
document's latest summary. The C++ implementation
(fluidframework_tpu/native) backs the high-throughput path; this is
the reference/fallback.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional


class ContentAddressedStore:
    def __init__(self):
        self._blobs: Dict[str, bytes] = {}
        self._refs: Dict[str, str] = {}  # doc id -> blob key

    def put(self, content: bytes) -> str:
        if isinstance(content, str):
            content = content.encode()
        key = hashlib.sha256(content).hexdigest()
        self._blobs[key] = content
        return key

    def get(self, key: str) -> bytes:
        return self._blobs[key]

    def contains(self, key: str) -> bool:
        return key in self._blobs

    # ------------------------------------------------------------- refs

    def set_ref(self, name: str, key: str) -> None:
        if key not in self._blobs:
            raise KeyError(f"unknown blob {key}")
        self._refs[name] = key

    def get_ref(self, name: str) -> Optional[str]:
        return self._refs.get(name)

    def list_refs(self) -> List[str]:
        return sorted(self._refs)
