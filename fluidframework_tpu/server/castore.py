"""Content-addressed summary/blob store.

Plays the role git storage plays in the reference (gitrest over
libgit2 — a C++ library — server/gitrest; fronted by historian's
cache): summaries and attachment blobs are immutable blobs addressed
by SHA-256 content hash, with named refs pointing at each document's
latest summary.

Two backends with identical semantics and digests:
- the C++ store (fluidframework_tpu/native/castore.cpp, ctypes-bound,
  compiled on demand) — the native path, used when a compiler is
  available;
- a pure-Python dict store — the always-available fallback.

`ContentAddressedStore(prefer_native=True)` picks automatically;
`.backend` reports which one is live.
"""

from __future__ import annotations

import hashlib
import os
from typing import Dict, List, Optional


class _PyStore:
    """In-memory or disk-backed store, on-disk format IDENTICAL to the
    native store (objects/<h[0:2]>/<hash> blob files + fsynced
    refs.log journal) so the backends interchange freely."""

    def __init__(self, directory: Optional[str] = None):
        self._blobs: Dict[str, bytes] = {}
        self._refs: Dict[str, str] = {}
        self._dir = directory
        self._refs_f = None
        if directory:
            os.makedirs(os.path.join(directory, "objects"), exist_ok=True)
            refs_path = os.path.join(directory, "refs.log")
            if os.path.exists(refs_path):
                with open(refs_path) as f:
                    for line in f:
                        parts = line.split()
                        if len(parts) == 2:
                            self._refs[parts[0]] = parts[1]

    def _blob_path(self, key: str) -> str:
        return os.path.join(self._dir, "objects", key[:2], key)

    def put(self, content) -> str:
        if isinstance(content, str):
            content = content.encode()
        key = hashlib.sha256(content).hexdigest()
        self._blobs[key] = content
        if self._dir:
            path = self._blob_path(key)
            if not os.path.exists(path):
                os.makedirs(os.path.dirname(path), exist_ok=True)
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "wb") as f:
                    f.write(content)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
        return key

    def get(self, key: str) -> bytes:
        if key in self._blobs:
            return self._blobs[key]
        if self._dir:
            path = self._blob_path(key)
            if os.path.exists(path):
                with open(path, "rb") as f:
                    data = f.read()
                self._blobs[key] = data
                return data
        raise KeyError(key)

    def contains(self, key: str) -> bool:
        if key in self._blobs:
            return True
        return bool(self._dir) and os.path.exists(self._blob_path(key))

    def set_ref(self, name: str, key: str) -> None:
        if not self.contains(key):
            raise KeyError(f"unknown blob {key}")
        self._refs[name] = key
        if self._dir:
            if self._refs_f is None:
                self._refs_f = open(
                    os.path.join(self._dir, "refs.log"), "a"
                )
            self._refs_f.write(f"{name} {key}\n")
            self._refs_f.flush()
            os.fsync(self._refs_f.fileno())  # ref update = durability point

    def get_ref(self, name: str) -> Optional[str]:
        return self._refs.get(name)

    def list_refs(self) -> List[str]:
        return sorted(self._refs)


class ContentAddressedStore:
    """Facade over the native or pure-Python backend."""

    def __init__(self, prefer_native: bool = True,
                 directory: Optional[str] = None):
        """`directory` switches on DURABLE mode (the gitrest role's
        persistence): blobs as content-addressed object files, refs in
        an fsynced append-only journal, state surviving process
        restart. Both backends share the on-disk format."""
        self._impl = None
        self.backend = "python"
        self.directory = directory
        if prefer_native:
            try:
                from ..native import NativeContentStore, load_castore

                lib = load_castore()
                if lib is not None:
                    self._impl = NativeContentStore(lib, directory)
                    self.backend = "native"
            except Exception:
                self._impl = None
        if self._impl is None:
            self._impl = _PyStore(directory)

    def put(self, content) -> str:
        return self._impl.put(content)

    def get(self, key: str) -> bytes:
        return self._impl.get(key)

    def contains(self, key: str) -> bool:
        return self._impl.contains(key)

    def set_ref(self, name: str, key: str) -> None:
        self._impl.set_ref(name, key)

    def get_ref(self, name: str) -> Optional[str]:
        return self._impl.get_ref(name)

    def list_refs(self) -> List[str]:
        return self._impl.list_refs()
