"""Content-addressed summary/blob store.

Plays the role git storage plays in the reference (gitrest over
libgit2 — a C++ library — server/gitrest; fronted by historian's
cache): summaries and attachment blobs are immutable blobs addressed
by SHA-256 content hash, with named refs pointing at each document's
latest summary.

Two backends with identical semantics and digests:
- the C++ store (fluidframework_tpu/native/castore.cpp, ctypes-bound,
  compiled on demand) — the native path, used when a compiler is
  available;
- a pure-Python dict store — the always-available fallback.

`ContentAddressedStore(prefer_native=True)` picks automatically;
`.backend` reports which one is live.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional


class _PyStore:
    def __init__(self):
        self._blobs: Dict[str, bytes] = {}
        self._refs: Dict[str, str] = {}

    def put(self, content) -> str:
        if isinstance(content, str):
            content = content.encode()
        key = hashlib.sha256(content).hexdigest()
        self._blobs[key] = content
        return key

    def get(self, key: str) -> bytes:
        return self._blobs[key]

    def contains(self, key: str) -> bool:
        return key in self._blobs

    def set_ref(self, name: str, key: str) -> None:
        if key not in self._blobs:
            raise KeyError(f"unknown blob {key}")
        self._refs[name] = key

    def get_ref(self, name: str) -> Optional[str]:
        return self._refs.get(name)

    def list_refs(self) -> List[str]:
        return sorted(self._refs)


class ContentAddressedStore:
    """Facade over the native or pure-Python backend."""

    def __init__(self, prefer_native: bool = True):
        self._impl = None
        self.backend = "python"
        if prefer_native:
            try:
                from ..native import NativeContentStore, load_castore

                lib = load_castore()
                if lib is not None:
                    self._impl = NativeContentStore(lib)
                    self.backend = "native"
            except Exception:
                self._impl = None
        if self._impl is None:
            self._impl = _PyStore()

    def put(self, content) -> str:
        return self._impl.put(content)

    def get(self, key: str) -> bytes:
        return self._impl.get(key)

    def contains(self, key: str) -> bool:
        return self._impl.contains(key)

    def set_ref(self, name: str, key: str) -> None:
        self._impl.set_ref(name, key)

    def get_ref(self, name: str) -> Optional[str]:
        return self._impl.get_ref(name)

    def list_refs(self) -> List[str]:
        return self._impl.list_refs()
