"""Length-prefixed binary framing for the TCP transport.

Round 2's transport used newline-delimited JSON — fine for a demo,
but content containing newlines needs escaping, partial reads corrupt
the stream, and framing costs a scan of every byte. Frames are now
``>I`` big-endian length + payload (JSON bytes today; the scheme is
payload-agnostic, matching how the reference rides socket.io's binary
packet framing). A max-frame guard kills malformed/hostile streams
instead of attempting a multi-GB allocation.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Optional

_HEADER = struct.Struct(">IB")
MAX_FRAME = 64 << 20  # 64 MiB: far above any legitimate frame

# Frame kinds (outside the payload, so receivers can route/defer a
# frame WITHOUT parsing it — an idle connection buffers kind-OPS
# frames as raw bytes at zero CPU).
KIND_MSG = 0  # RPC request/response or single event: parse on receipt
KIND_OPS = 1  # batched sequenced-op broadcast: parse lazily


def encode_frame(obj: Any, kind: int = KIND_MSG) -> bytes:
    payload = json.dumps(obj, separators=(",", ":")).encode()
    if len(payload) > MAX_FRAME:
        raise ValueError(f"frame too large: {len(payload)} bytes")
    return _HEADER.pack(len(payload), kind) + payload


def write_frame(wfile, obj: Any, kind: int = KIND_MSG) -> None:
    wfile.write(encode_frame(obj, kind))
    wfile.flush()


def _read_exact(rfile, n: int) -> Optional[bytes]:
    chunks = []
    got = 0
    while got < n:
        part = rfile.read(n - got)
        if not part:
            return None  # EOF
        chunks.append(part)
        got += len(part)
    return b"".join(chunks)


def read_frame_raw(rfile):
    """Next frame as ``(kind, payload_bytes)``; None on clean EOF at
    a frame boundary."""
    hdr = _read_exact(rfile, _HEADER.size)
    if hdr is None:
        return None
    n, kind = _HEADER.unpack(hdr)
    if n > MAX_FRAME:
        raise ValueError(f"frame length {n} exceeds cap {MAX_FRAME}")
    body = _read_exact(rfile, n)
    if body is None:
        raise ConnectionError("truncated frame")
    return kind, body


def read_frame(rfile) -> Optional[Any]:
    """Next frame parsed, or None on clean EOF (kind discarded —
    server-side requests are always KIND_MSG)."""
    raw = read_frame_raw(rfile)
    if raw is None:
        return None
    return json.loads(raw[1])
