"""Columnar binary op-log topics: `SharedFileTopic`'s batch-framed twin.

One `ColumnarFileTopic` append writes ONE fence-gated, CRC-guarded
record-batch frame (`protocol.record_batch`) instead of one JSON line
per record — the storage-side half of the reference's outbound
boxcarring, riding the same payload-agnostic framing philosophy as
`server.framing`. The robustness contract matches `SharedFileTopic`
exactly, lifted from lines to batches:

- **Torn tail** — a frame whose bytes are not fully on disk is never
  consumed; it is invisible until complete (the same rule covers an
  append in flight). The next append SEALS a crash-torn tail by
  truncating it away (the partial frame was never acknowledged — the
  JSON topic's "junk line" outcome, minus the junk); complete units
  are NEVER truncated, so nothing a reader consumed can disappear. A
  committed-length sidecar (`<path>.clen`, updated under the append
  lock after fsync) bounds the seal scan; it is a hint, not an
  authority — the sealer re-extends it over complete units, so a
  json⇄columnar format round-trip (which leaves the sidecar dormant)
  cannot truncate acknowledged records.
- **Corruption** — a frame whose CRC no longer matches is skipped but
  its records stay COUNTED (the header's record count survives payload
  corruption), so line/record offsets remain stable across all
  readers — the sealed-junk-line rule, batch-sized. A frame whose
  HEADER itself is hit (version/length bytes garbled — the frame's
  extent unknowable) is recovered by a bounded magic-resync scan
  (`record_batch.iter_units`): the poisoned region is skipped but
  counts ONE record slot, and reading resumes at the next CONFIRMED
  unit boundary (a decodable complete frame, or a parseable JSON
  line) instead of stalling forever. The poisoned frame's true record
  count is unknowable, so offsets past it are heuristic — exactly-once
  consumers treat the slot like a sealed junk line.
- **Fencing** — identical to `SharedFileTopic` (same sidecar, same
  `FencedError` gate under the same lock); accepted (fence, owner) is
  additionally stamped into each frame header for audit.
- **Mixed history** — readers parse JSON lines AND binary frames in
  one file, so a topic written as JSONL can continue columnar after a
  restart (`FLUID_LOG_FORMAT=columnar`) mid-stream: offsets count
  JSON lines as one record each, exactly like `SharedFileTopic`.
  The UPGRADE direction only: `SharedFileTopic` readers cannot parse
  frames, so a farm downgrade (columnar → json) needs drained topics
  (LocalServer journals replay both ways — `log._replay_journal`
  sniffs per unit — so persist_dir restarts may switch freely).

`ColumnarTailReader` mirrors `queue.TailReader` (incremental byte
position, identical record offsets) and adds `poll_batches()`: raw
`RecordBatch` objects whose columns feed `server.deli_kernel` with
zero per-record JSON decode.
"""

from __future__ import annotations

import json
import os
import struct
import time
import zlib
from typing import Any, List, Optional, Tuple

from ..protocol.record_batch import (
    HEADER,
    K_GENERIC,
    K_SEQ_OP,
    MAGIC,
    MAX_BATCH_BYTES,
    RecordBatch,
    count_records,
    decode_batch,
    encode_batch,
    iter_units,
)
from .queue import SharedFileTopic, TailReader, check_disk_fault, fsync_file

__all__ = [
    "ColumnarFileTopic",
    "ColumnarTailReader",
    "LOG_FORMATS",
    "TRUNC_HEADER_LEN",
    "default_log_format",
    "make_tail_reader",
    "make_topic",
    "tail_records_reverse",
]

LOG_FORMATS = ("json", "columnar")

# -- prefix truncation (the retention plane's fenced op-log TRUNCATE) --
#
# A truncated topic file begins with this fixed header naming the
# LOGICAL stream position its first physical data byte maps to:
#
#     magic "\x00FTR" | u64 base_records | u64 base_bytes | u32 crc
#
# Record offsets and byte positions are LOGICAL — stable across
# truncation — so checkpointed offsets, `inOff` bookkeeping and
# manifest byte offsets never move when the prefix behind a durable
# summary is reclaimed (`ColumnarFileTopic.truncate_prefix`,
# `server.retention`). The leading NUL byte can never open a JSON line
# and never matches the frame MAGIC, so a header-unaware scan fails
# loudly instead of misparsing. JSONL topics do not truncate: the
# retention role requires the columnar log format.
TRUNC_MAGIC = b"\x00FTR"
_TRUNC = struct.Struct("<4sQQI")  # magic, base_records, base_bytes, crc
TRUNC_HEADER_LEN = _TRUNC.size


def _pack_trunc(base_records: int, base_bytes: int) -> bytes:
    crc = zlib.crc32(struct.pack("<QQ", base_records, base_bytes))
    return _TRUNC.pack(TRUNC_MAGIC, base_records, base_bytes, crc)

def default_log_format(explicit: Optional[str] = None) -> str:
    """Resolve a log format: explicit arg > ``FLUID_LOG_FORMAT`` env >
    "json". Loud on typos — a silently-misrouted format would
    invalidate benches and chaos runs."""
    fmt = explicit or os.environ.get("FLUID_LOG_FORMAT", "json")
    if fmt not in LOG_FORMATS:
        raise ValueError(f"log_format {fmt!r} not in {LOG_FORMATS}")
    return fmt


def make_topic(path: str, log_format: Optional[str] = None):
    """Topic factory for the supervised farm / benches: "json" →
    `SharedFileTopic`, "columnar" → `ColumnarFileTopic`."""
    fmt = default_log_format(log_format)
    return ColumnarFileTopic(path) if fmt == "columnar" else \
        SharedFileTopic(path)


def make_tail_reader(topic, line_offset: int = 0):
    """The matching incremental reader for either topic flavor."""
    if isinstance(topic, ColumnarFileTopic):
        return ColumnarTailReader(topic, line_offset)
    return TailReader(topic, line_offset)


class ColumnarFileTopic(SharedFileTopic):
    """A cross-process topic over one record-batch log file.

    Drop-in `SharedFileTopic` sibling: same constructor, same
    `append_many(...) -> bytes-written` contract, same
    `read_entries`/`read_from` record-offset semantics (JSON lines in
    the same file count one record each — the migration path), same
    fence sidecar and `FencedError` gate."""

    log_format = "columnar"

    # -------------------------------------------------- committed length

    def _clen_path(self) -> str:
        return self.path + ".clen"

    def _read_committed(self) -> Optional[int]:
        try:
            with open(self._clen_path()) as f:
                return int(json.load(f)["len"])
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def _write_committed(self, n: int) -> None:
        # Deliberately NOT fsynced: the data fsync precedes this write,
        # so after an OS crash the sidecar can only UNDERSTATE (stale
        # value → the seal scan covers more bytes, correct) or be
        # junk/missing (full scan, correct) — it can never name bytes
        # that are not durable. Dropping the fsync halves the columnar
        # append's durability cost (one fsync per batch, not two).
        tmp = self._clen_path() + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"len": int(n)}, f)
            f.flush()
        os.replace(tmp, self._clen_path())

    @staticmethod
    def _scan_clean_len(data: bytes) -> int:
        """Byte length of the longest prefix made of complete units
        (frames or newline-terminated lines) — the committed length of
        a topic that predates its sidecar (a migrated JSONL file)."""
        pos = 0
        for _kind, _idx, _cnt, _payload, end in iter_units(data):
            pos = end
        return pos

    # -------------------------------------------------- truncation base

    @staticmethod
    def _parse_base(head: bytes) -> Tuple[int, int, int]:
        """(base_records, base_bytes, header_len) off a file's first
        `TRUNC_HEADER_LEN` bytes — (0, 0, 0) for a never-truncated
        file (or a garbled header, which reads as ordinary data and
        fails loudly downstream rather than silently re-basing)."""
        if len(head) >= TRUNC_HEADER_LEN and \
                head[:4] == TRUNC_MAGIC:
            _m, r, b, crc = _TRUNC.unpack(head[:TRUNC_HEADER_LEN])
            if crc == zlib.crc32(head[4:20]):
                return int(r), int(b), TRUNC_HEADER_LEN
        return 0, 0, 0

    def base_offsets(self) -> Tuple[int, int]:
        """(base_records, base_bytes): the logical stream position of
        this topic's first physically-present unit. (0, 0) until a
        `truncate_prefix` reclaims something. Records/bytes below the
        base are GONE — readers that need them must boot from a
        summary (the retention contract)."""
        try:
            with open(self.path, "rb") as f:
                r, b, _h = self._parse_base(f.read(TRUNC_HEADER_LEN))
        except OSError:
            return 0, 0
        return r, b

    # ----------------------------------------------------------- append

    def __init__(self, path: str):
        super().__init__(path)
        # Process-local seal hint: the LOGICAL clean length after OUR
        # last append (complete units only, so it stays valid whatever
        # other writers append after it — and logical, so a concurrent
        # prefix truncation cannot strand it mid-frame). Bounds the
        # seal scan for unsynced-append topics whose on-disk sidecar
        # is pinned.
        self._seal_hint = 0
        # True while this topic holds appends that were never fsynced
        # (fsync=False legs): the on-disk sidecar must not advance
        # over them — after an OS crash it could otherwise name bytes
        # the page cache lost, and the seal scan trusts it.
        self._unsynced = False

    def _inode_stable(self, f) -> bool:
        """Whether the locked fd still names `self.path`: a concurrent
        `truncate_prefix` REPLACES the file (atomic rename), so an
        appender that opened the old inode and then won its flock
        would otherwise write acknowledged bytes into an orphan."""
        try:
            return os.stat(self.path).st_ino == os.fstat(f.fileno()).st_ino
        except OSError:
            return False

    def append_many(self, messages: List[Any],
                    fence: Optional[int] = None,
                    owner: Optional[str] = None,
                    lock_timeout_s: Optional[float] = None,
                    fsync: bool = True,
                    src: Optional[str] = None) -> int:
        """Append `messages` — plain records and/or pre-columnized
        `ColumnarRecords` segments, spliced in order — as ONE binary
        record-batch frame under the OS lock; returns the frame bytes
        written (0 for an empty batch, which still gates the fence — a
        deposed owner must learn it is deposed even with nothing to
        write).

        ``src`` stamps the frame-level ``inSrc`` tag
        (`record_batch.FLAG_SRC`): every record decoded out of this
        append carries ``"inSrc": src`` — the elastic pred-drain tag
        without per-record dict emission.

        ``fsync=False`` skips the data fsync AND pins the committed-
        length sidecar (a sidecar naming un-fsynced bytes could
        overstate after an OS crash): torn-tail-safe but not crash-
        durable — the derived-feed contract (`SharedFileTopic`
        .append_many has the full story). A later ``fsync=True``
        append re-covers everything (fsync flushes the whole file) and
        resumes the sidecar."""
        from .queue import flock_exclusive

        while True:
            with open(self.path, "r+b") as f:
                with flock_exclusive(f, lock_timeout_s, self.path):
                    if not self._inode_stable(f):
                        continue  # truncation replaced the file: reopen
                    wrote = self._append_locked(
                        f, messages, fence, owner, fsync, src
                    )
                    break
        if wrote:
            # Event-driven consumers wake now (outside the lock, after
            # durability — queue.TopicDoorbell semantics, both formats).
            self._ring_doorbells()
        return wrote

    def _append_locked(self, f, messages, fence, owner, fsync,
                       src) -> int:
        self._gate_fence(fence, owner)
        f.seek(0)
        base_r, base_b, hlen = self._parse_base(
            f.read(TRUNC_HEADER_LEN)
        )
        f.seek(0, os.SEEK_END)
        size = f.tell()
        committed = self._read_committed()  # PHYSICAL length
        # The sidecar is a HINT that bounds the seal scan, not
        # an authority over the data: EXTEND it over any
        # complete units past it (JSON-era lines appended while
        # the farm ran the other format, frames whose sidecar
        # update was lost to a crash) so a format round-trip
        # can never truncate acknowledged records; only the
        # genuinely torn suffix (partial frame, unterminated
        # line) is sealed away — it was never acknowledged.
        # The process-local hint covers our own unsynced
        # appends, whose bytes the sidecar must not name. The
        # hint is LOGICAL: a truncation between our appends
        # re-bases the file, and mapping through the current
        # base keeps the hint on the same unit boundary.
        hint_phys = hlen + max(0, self._seal_hint - base_b)
        start = max(hlen if committed is None
                    else min(max(committed, hlen), size),
                    min(hint_phys, size))
        f.seek(start)
        clean = start + self._scan_clean_len(f.read())
        if size > clean:
            f.truncate(clean)
        if not count_records(messages):
            self._seal_hint = base_b + (clean - hlen)
            if committed != clean and not self._unsynced:
                # The scan may have covered bytes ANOTHER
                # writer appended fsync=False (a dead fused
                # consumer's broadcast frames — our local
                # `_unsynced` flag can't see them): fsync the
                # data BEFORE the sidecar names it, preserving
                # the file-global "sidecar never overstates
                # durable data" invariant. Rare path — fence
                # binds and recovery, never the steady state.
                fsync_file(f, "topic")
                self._write_committed(clean)
            return 0
        cur_fence, cur_owner = self.latest_fence()
        frame = encode_batch(messages, fence=cur_fence,
                             owner=cur_owner, src=src)
        check_disk_fault("topic")
        f.seek(clean)
        f.write(frame)
        f.flush()
        self._seal_hint = base_b + (clean + len(frame) - hlen)
        if fsync:
            fsync_file(f, "topic")
            self._unsynced = False
            # Data is durable BEFORE the length names it.
            self._write_committed(clean + len(frame))
        else:
            self._unsynced = True
        return len(frame)

    # ------------------------------------------------------- truncation

    def truncate_prefix(self, upto_records: int, min_bytes: int = 0,
                        dry_run: bool = False,
                        lock_timeout_s: Optional[float] = None
                        ) -> Tuple[int, int]:
        """Physically reclaim every complete unit whose records ALL sit
        below logical record offset `upto_records` (the cut lands on
        the greatest unit boundary <= it). Returns the
        ``(base_records, base_bytes)`` the call decided on — the
        current base when nothing qualifies (or the reclaimable run is
        under `min_bytes`), the planned new base with ``dry_run=True``
        (nothing touched), the installed new base otherwise.

        Crash-safe by construction: the replacement file (truncation
        header + the untouched suffix bytes, fsynced) is atomically
        renamed over the topic, so a reader sees the old complete file
        or the new complete file, never a mix; the committed-length
        sidecar is DELETED before the rename and rewritten after, so a
        crash anywhere in the window costs at worst a full seal scan.
        Offsets are unchanged — record indices and byte positions are
        logical, and the header preserves the mapping.

        NOT fence-gated: the topic's fence belongs to its WRITER role,
        and binding another would depose it. The caller's zombie
        safety comes from the fenced COMMIT record that precedes every
        reclaim (`server.retention` — a deposed retention role dies at
        its own topic's fence before bytes go away; re-executing an
        already-applied cut is a no-op since the base only grows)."""
        from .queue import flock_exclusive

        while True:
            with open(self.path, "r+b") as f:
                with flock_exclusive(f, lock_timeout_s, self.path):
                    if not self._inode_stable(f):
                        continue
                    return self._truncate_locked(
                        f, int(upto_records), min_bytes, dry_run
                    )

    def _truncate_locked(self, f, upto_records: int, min_bytes: int,
                         dry_run: bool) -> Tuple[int, int]:
        # Orphan sweep: a crash between the tmp write below and its
        # rename leaves `<topic>.trunc.tmp.<pid>` behind — nothing
        # else ever removes it, and it counts against the disk bound
        # this plane exists to hold. The flock serializes truncators,
        # so any such sibling here is a dead writer's.
        tdir = os.path.dirname(self.path) or "."
        tprefix = os.path.basename(self.path) + ".trunc.tmp."
        try:
            for fn in os.listdir(tdir):
                if fn.startswith(tprefix):
                    try:
                        os.unlink(os.path.join(tdir, fn))
                    except OSError:
                        pass
        except OSError:
            pass
        f.seek(0)
        base_r, base_b, hlen = self._parse_base(
            f.read(TRUNC_HEADER_LEN)
        )
        if upto_records <= base_r:
            return base_r, base_b
        f.seek(hlen)
        data = f.read()
        cut_rel = 0
        cut_records = base_r
        for _kind, idx, cnt, _payload, end in iter_units(data, base_r):
            if idx + cnt > upto_records:
                break
            cut_rel, cut_records = end, idx + cnt
        if cut_records <= base_r or cut_rel < max(1, min_bytes):
            return base_r, base_b
        new_r, new_b = cut_records, base_b + cut_rel
        if dry_run:
            return new_r, new_b
        suffix = data[cut_rel:]
        check_disk_fault("topic")
        tmp = self.path + f".trunc.tmp.{os.getpid()}"
        with open(tmp, "wb") as tf:
            tf.write(_pack_trunc(new_r, new_b))
            tf.write(suffix)
            tf.flush()
            fsync_file(tf, "topic")
        # Sidecar OUT before the swap: its physical length is about to
        # change, and a stale value pointing mid-frame in the new file
        # would poison the seal scan. A crash between these steps
        # leaves no sidecar — full scan, correct.
        try:
            os.remove(self._clen_path())
        except OSError:
            pass
        os.replace(tmp, self.path)
        try:
            dfd = os.open(os.path.dirname(self.path) or ".",
                          os.O_RDONLY)
            try:
                os.fsync(dfd)  # the rename itself must survive a crash
            finally:
                os.close(dfd)
        except OSError:
            pass
        # The whole replacement file was fsynced above, so the fresh
        # sidecar may name every complete unit in it.
        self._write_committed(
            TRUNC_HEADER_LEN + self._scan_clean_len(suffix)
        )
        self._seal_hint = max(self._seal_hint, new_b)
        from ..utils.metrics import get_registry

        get_registry().counter(
            "topic_truncations_total",
            topic=os.path.basename(self.path),
        ).inc()
        return new_r, new_b

    # ------------------------------------------------------------- read

    def _read_based(self) -> Tuple[bytes, int, int, int]:
        """``(data_after_header, base_records, base_bytes,
        header_len)`` — the physical file with any truncation header
        stripped, plus the logical base it establishes. Readers rely
        on the torn-unit rules (an incomplete frame or unterminated
        line is never consumed), so an in-flight append is naturally
        invisible and a stale sidecar can never hide acknowledged
        records. Complete units are never truncated by the seal path,
        so what a reader consumed stays consumed (prefix truncation
        only reclaims units behind a committed retention record)."""
        try:
            with open(self.path, "rb") as f:
                head = f.read(TRUNC_HEADER_LEN)
                base_r, base_b, hlen = self._parse_base(head)
                rest = f.read()
        except OSError:
            return b"", 0, 0, 0
        return (rest if hlen else head + rest), base_r, base_b, hlen

    def _read_data(self) -> bytes:
        """The file's unit data (truncation header stripped)."""
        return self._read_based()[0]

    def read_entries(self, offset: int,
                     max_count: Optional[int] = None
                     ) -> Tuple[List[Tuple[int, Any]], int]:
        """Same contract as `SharedFileTopic.read_entries`, over mixed
        frames + JSON lines: record offsets are stable (CRC-skipped
        batches and junk lines stay counted; a truncated prefix keeps
        its logical offsets — its records are simply absent), torn
        units are never consumed, `max_count` caps the parsed entries
        taken."""
        data, base_r, _base_b, _hlen = self._read_based()
        if not data:
            return [], max(offset, base_r)

        def capped():
            return max_count is not None and len(out) >= max_count

        out: List[Tuple[int, Any]] = []
        idx = base_r
        for kind, idx0, cnt, payload, _end in iter_units(data, base_r):
            if capped():
                break
            idx = idx0 + cnt
            if kind == "batch":
                if payload is None or idx <= offset:
                    continue  # CRC-skipped or entirely below the offset
                recs = payload.records()
                for i in range(max(0, offset - idx0), cnt):
                    if capped():
                        break
                    out.append((idx0 + i, recs[i]))
            elif idx0 >= offset:
                line = payload.strip()
                if line:
                    try:
                        out.append((idx0, json.loads(line)))
                    except ValueError:
                        pass  # sealed junk from a crashed writer
        if capped():
            return out, (out[-1][0] + 1 if out else offset)
        return out, max(offset, idx)


class ColumnarTailReader:
    """Incremental reader over a `ColumnarFileTopic` (the `TailReader`
    role): remembers the byte position after the last fully-consumed
    unit, so each poll reads only NEW committed bytes — `read_entries`
    is O(file) per call, which would make a long-lived consumer
    O(file²) over its lifetime. Record offsets (`next_line`) are
    identical to `read_entries` offsets, and — like `TailReader` — a
    `line_offset` AHEAD of the file keeps `next_line == line_offset`
    (records below it are swallowed silently as they appear, never
    delivered).

    `poll()` yields decoded records for legacy consumers;
    `poll_batches()` yields raw `RecordBatch` objects (plus decoded
    stray JSON records from a migrated history) for the kernel deli's
    zero-JSON ingest. `max_count` is a batch-granular bound: a batch is
    always consumed whole, and no new batch starts once the cap is
    reached."""

    def __init__(self, topic: ColumnarFileTopic, line_offset: int = 0):
        self.topic = topic
        self.next_line = line_offset
        # LOGICAL byte position after the last consumed unit, and the
        # record index of the unit there. Logical positions are stable
        # under prefix truncation (physical = logical - base_bytes +
        # header_len), so a long-lived reader survives a concurrent
        # TRUNCATE without re-anchoring. A cold reader (offset at/below
        # the base) needs only the header — the O(file) read happens
        # solely when a record offset must be translated to bytes.
        base_r, base_b = topic.base_offsets()
        self._pos = base_b
        self._abs = base_r
        if line_offset > base_r:
            # One O(file) scan translates the record offset into a byte
            # position; everything after is incremental. Stops before
            # the unit CONTAINING the offset (mid-batch delivery is
            # handled record-wise in _poll_units). Fresh base values
            # from the same read: a truncate between the header probe
            # and this scan only ever advances the base.
            data, base_r, base_b, _hlen = topic._read_based()
            self._pos = base_b
            self._abs = base_r
            for _kind, idx, cnt, _payload, end in iter_units(
                    data, base_r):
                if idx + cnt > line_offset:
                    break
                self._pos = base_b + end
                self._abs = idx + cnt

    def _read_new(self) -> bytes:
        """Only the bytes past `_pos` (incremental tail); the torn-unit
        rules bound what of them is consumable. Re-reads the truncation
        base per poll: a concurrent TRUNCATE moves the physical layout
        while logical positions stand still."""
        try:
            with open(self.topic.path, "rb") as f:
                base_r, base_b, hlen = self.topic._parse_base(
                    f.read(TRUNC_HEADER_LEN)
                )
                if self._pos < base_b:
                    # Our position was reclaimed (a reader behind the
                    # cut — the retention role only cuts behind every
                    # tracked consumer, so this is a COLD reader):
                    # records between are gone; resume at the base.
                    self._pos = base_b
                    self._abs = max(self._abs, base_r)
                f.seek(hlen + (self._pos - base_b))
                return f.read()
        except OSError:
            return b""

    def _poll_units(self, max_count: Optional[int]):
        data = self._read_new()
        if not data:
            return []
        units: List[tuple] = []  # ("batch", start_line, RecordBatch)
        #                        | ("rec", line, value)
        taken = 0
        consumed_bytes = 0
        for kind, rel_idx, cnt, payload, end in iter_units(
                data, self._abs):
            if max_count is not None and taken >= max_count:
                break
            consumed_bytes = end
            self._abs = rel_idx + cnt
            if kind == "batch":
                # Records below next_line (a checkpoint taken against a
                # longer topic) are swallowed without delivery.
                skip = max(0, min(cnt, self.next_line - rel_idx))
                if payload is not None and skip < cnt:
                    if skip == 0:
                        units.append(("batch", rel_idx, payload))
                    else:  # offset lands mid-batch: deliver the tail
                        recs = payload.records()
                        units.extend(
                            ("rec", rel_idx + i, recs[i])
                            for i in range(skip, cnt)
                        )
                    taken += cnt - skip
            elif rel_idx >= self.next_line:
                line = payload.strip()
                if line:
                    try:
                        units.append(("rec", rel_idx, json.loads(line)))
                        taken += 1
                    except ValueError:
                        pass  # sealed junk
            self.next_line = max(self.next_line, self._abs)
        self._pos += consumed_bytes
        return units

    def poll_batches(self, max_count: Optional[int] = None) -> List[tuple]:
        """New committed units as ``("batch", start_line, RecordBatch)``
        / ``("rec", line, value)`` tuples, in stream order."""
        return self._poll_units(max_count)

    def poll(self, max_count: Optional[int] = None
             ) -> List[Tuple[int, Any]]:
        """Decoded-records view (the `TailReader.poll` contract, with
        batch-granular `max_count`)."""
        out: List[Tuple[int, Any]] = []
        for unit in self._poll_units(max_count):
            if unit[0] == "batch":
                _, start, batch = unit
                recs = batch.records()
                out.extend((start + i, recs[i]) for i in range(batch.n))
            else:
                out.append((unit[1], unit[2]))
        return out


# ---------------------------------------------------------------------------
# backward tail scan (summary catch-up's O(tail) read, frame edition)
# ---------------------------------------------------------------------------

# How far back one frame boundary can possibly sit from a known one: a
# frame larger than this cannot exist, so a backward chain that finds
# no anchoring frame inside the window is provably in a non-frame
# region (JSON-era lines) and the caller falls forward.
HEADER_MAX_EXTENT = HEADER.size + MAX_BATCH_BYTES
_REV_BLOCK = 1 << 16


def _frame_ops_reverse(batch: RecordBatch, doc: str, base: int,
                       upto: Optional[int]):
    """One frame's contribution to a reverse tail scan: `doc`'s
    kind=="op" records (forward order within the frame), and whether
    an own-doc record at/below `base` proves the scan may stop.
    Column-first: a frame whose doc dictionary lacks `doc` is skipped
    on the dictionary alone (no record decode), K_SEQ_OP rows gather
    by mask, and only K_GENERIC rows pay a per-record decode."""
    import numpy as np

    ops: List[dict] = []
    stop = False
    gen_rows = np.flatnonzero(batch.kind == K_GENERIC)
    if doc in batch.docs:
        di = batch.docs.index(doc)
        rows = np.flatnonzero(
            (batch.kind == K_SEQ_OP) & (batch.doc_idx == di)
        )
        for i in rows.tolist():
            s = int(batch.seq[i])
            if s <= base:
                stop = True
                continue
            if upto is None or s <= upto:
                ops.append(batch.record(i))
    elif gen_rows.shape[0] == 0:
        return ops, stop
    for i in gen_rows.tolist():
        rec = batch.record(i)
        if not isinstance(rec, dict) or rec.get("doc") != doc \
                or rec.get("kind") != "op":
            continue
        s = int(rec["seq"])
        if s <= base:
            stop = True
        elif upto is None or s <= upto:
            ops.append(rec)
    if len(ops) > 1:
        ops.sort(key=lambda r: int(r["seq"]))  # generics interleave
    return ops, stop


def tail_records_reverse(topic: ColumnarFileTopic, doc: str, base: int,
                         upto: Optional[int],
                         stop_at: Optional[int] = None
                         ) -> Optional[List[dict]]:
    """`doc`'s op records with ``base < seq [<= upto]`` read BACKWARD
    from the topic's end — the frame-log twin of the summarizer's
    JSONL `_tail_records_reverse`, so summary catch-up on columnar
    topics costs O(tail + interleave) instead of the O(log-bytes)
    forward skip.

    Frames are length-prefixed forward structures, so the walk anchors
    on the committed-length sidecar and CHAINS backward: a MAGIC
    candidate is trusted only when its frame decodes (header+payload
    CRC) AND ends exactly at an already-trusted boundary — later
    boundaries validate first, so false MAGICs inside blob heaps can
    never mis-frame the walk. Returns None when it cannot anchor (no
    sidecar, or a non-frame region — a JSON-era prefix mid-chain);
    the caller falls back to the forward walk, slower but always
    correct.

    ``stop_at`` (LOGICAL byte position — a summary manifest's
    ``byteOff``) bounds the chain: every own-doc record below it is
    known to be at/below `base`, so the walk never descends past it —
    O(tail) even when the doc's records are arbitrarily sparse in the
    interleave. A truncated topic anchors the same way; its header
    maps logical to physical and the chain floors at the header."""
    # ONE consistent snapshot: sidecar, then fd, then an inode check.
    # A concurrent truncate_prefix atomically renames a new file over
    # the path (sidecar deleted before, rewritten after) — mixing the
    # new base with the old contents would map `stop_at` through the
    # wrong base and silently drop tail records. Reading the sidecar
    # BEFORE the stability check makes every interleaving safe: a
    # sidecar deleted mid-truncate reads None (fall forward), a
    # rewritten one implies the rename already landed and the inode
    # check catches it; once stable, the held fd pins one complete
    # file version for the size, the header, and every byte the scan
    # reads.
    while True:
        try:
            fh = open(topic.path, "rb")
        except OSError:
            return None
        committed = topic._read_committed()
        if committed is None:
            fh.close()
            return None  # pre-sidecar file (migrated JSONL): fall fwd
        if not topic._inode_stable(fh):
            fh.close()
            continue  # truncate swapped the file mid-probe: re-probe
        break
    size = os.fstat(fh.fileno()).st_size
    fh.seek(0)
    _base_r, base_b, hlen = topic._parse_base(fh.read(TRUNC_HEADER_LEN))
    committed = max(min(committed, size), hlen)
    floor = hlen
    if stop_at is not None:
        floor = max(floor, min(hlen + max(0, stop_at - base_b), size))
    from ..utils.metrics import get_registry

    m_bytes = get_registry().counter(
        "catchup_tail_scan_bytes_total", mode="reverse-columnar"
    )
    groups: List[List[dict]] = []  # per-unit op lists, newest first
    with fh as f:
        # 1. The post-sidecar suffix (at most the appends whose
        # sidecar update a crash dropped, or one append in flight):
        # parse FORWARD — torn-unit rules apply, complete units count.
        f.seek(committed)
        tail = f.read()
        m_bytes.inc(len(tail))
        done = False
        fwd: List[List[dict]] = []
        for kind, _idx, _cnt, payload, _end in iter_units(tail):
            if kind == "batch" and payload is not None:
                ops, stop = _frame_ops_reverse(payload, doc, base, upto)
                fwd.append(ops)
                done = done or stop
            elif kind == "line":
                line = payload.strip()
                if line:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) and rec.get("doc") == doc \
                            and rec.get("kind") == "op":
                        s = int(rec["seq"])
                        if s <= base:
                            done = True
                        elif upto is None or s <= upto:
                            fwd.append([rec])
        groups.extend(reversed(fwd))
        # 2. Chain BACKWARD from the sidecar boundary, frame by frame,
        # flooring at the truncation header (records below the base
        # are reclaimed — a caller holding a summary never needs them)
        # and at `stop_at` (records below it are provably <= base).
        lo = committed
        buf = b""
        buf_start = committed
        while lo > floor and not done:
            # Grow the window until a frame ending exactly at `lo`
            # appears (or the region is provably not a frame). While
            # `lo` is fixed, a rejected candidate's verdict can never
            # change when only EARLIER bytes arrive, so after each
            # front growth only the newly prepended block (+3 bytes of
            # straddle) is searched — the fallback on a non-frame
            # region stays linear, not quadratic. A new anchor moves
            # `lo`, which CAN validate previously rejected candidates;
            # the outer loop therefore re-searches the (truncated)
            # remainder from scratch per anchor.
            anchored = None
            fresh_hi = len(buf)  # unsearched-prefix bound, this `lo`
            while anchored is None:
                pos = min(fresh_hi, len(buf))
                while pos > 0:
                    cand = buf.rfind(MAGIC, 0, pos)
                    if cand < 0:
                        break
                    try:
                        batch, end, cnt = decode_batch(buf, cand)
                    except ValueError:
                        pos = cand + 3
                        continue
                    if cnt >= 0 and buf_start + end == lo:
                        # A CRC-failed frame (batch None) still
                        # anchors the chain — its records are the
                        # skip-but-count slots every reader skips.
                        anchored = (buf_start + cand, batch)
                        break
                    pos = cand + 3
                if anchored is not None:
                    break
                if buf_start <= hlen or \
                        lo - buf_start > HEADER_MAX_EXTENT:
                    return None  # non-frame region: fall forward
                step = min(_REV_BLOCK, buf_start - hlen)
                f.seek(buf_start - step)
                buf = f.read(step) + buf
                m_bytes.inc(step)
                buf_start -= step
                fresh_hi = step + 3  # the new block + MAGIC straddle
            b_at, batch = anchored
            if batch is not None:
                ops, stop = _frame_ops_reverse(batch, doc, base, upto)
                groups.append(ops)
                done = done or stop
            lo = b_at
            buf = buf[:lo - buf_start]
    out: List[dict] = []
    for ops in reversed(groups):
        out.extend(ops)
    return out
