"""Columnar binary op-log topics: `SharedFileTopic`'s batch-framed twin.

One `ColumnarFileTopic` append writes ONE fence-gated, CRC-guarded
record-batch frame (`protocol.record_batch`) instead of one JSON line
per record — the storage-side half of the reference's outbound
boxcarring, riding the same payload-agnostic framing philosophy as
`server.framing`. The robustness contract matches `SharedFileTopic`
exactly, lifted from lines to batches:

- **Torn tail** — a frame whose bytes are not fully on disk is never
  consumed; it is invisible until complete (the same rule covers an
  append in flight). The next append SEALS a crash-torn tail by
  truncating it away (the partial frame was never acknowledged — the
  JSON topic's "junk line" outcome, minus the junk); complete units
  are NEVER truncated, so nothing a reader consumed can disappear. A
  committed-length sidecar (`<path>.clen`, updated under the append
  lock after fsync) bounds the seal scan; it is a hint, not an
  authority — the sealer re-extends it over complete units, so a
  json⇄columnar format round-trip (which leaves the sidecar dormant)
  cannot truncate acknowledged records.
- **Corruption** — a frame whose CRC no longer matches is skipped but
  its records stay COUNTED (the header's record count survives payload
  corruption), so line/record offsets remain stable across all
  readers — the sealed-junk-line rule, batch-sized. A frame whose
  HEADER itself is hit (version/length bytes garbled — the frame's
  extent unknowable) is recovered by a bounded magic-resync scan
  (`record_batch.iter_units`): the poisoned region is skipped but
  counts ONE record slot, and reading resumes at the next CONFIRMED
  unit boundary (a decodable complete frame, or a parseable JSON
  line) instead of stalling forever. The poisoned frame's true record
  count is unknowable, so offsets past it are heuristic — exactly-once
  consumers treat the slot like a sealed junk line.
- **Fencing** — identical to `SharedFileTopic` (same sidecar, same
  `FencedError` gate under the same lock); accepted (fence, owner) is
  additionally stamped into each frame header for audit.
- **Mixed history** — readers parse JSON lines AND binary frames in
  one file, so a topic written as JSONL can continue columnar after a
  restart (`FLUID_LOG_FORMAT=columnar`) mid-stream: offsets count
  JSON lines as one record each, exactly like `SharedFileTopic`.
  The UPGRADE direction only: `SharedFileTopic` readers cannot parse
  frames, so a farm downgrade (columnar → json) needs drained topics
  (LocalServer journals replay both ways — `log._replay_journal`
  sniffs per unit — so persist_dir restarts may switch freely).

`ColumnarTailReader` mirrors `queue.TailReader` (incremental byte
position, identical record offsets) and adds `poll_batches()`: raw
`RecordBatch` objects whose columns feed `server.deli_kernel` with
zero per-record JSON decode.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, List, Optional, Tuple

from ..protocol.record_batch import (
    RecordBatch,
    encode_batch,
    iter_units,
)
from .queue import SharedFileTopic, TailReader, check_disk_fault

__all__ = [
    "ColumnarFileTopic",
    "ColumnarTailReader",
    "LOG_FORMATS",
    "default_log_format",
    "make_tail_reader",
    "make_topic",
]

LOG_FORMATS = ("json", "columnar")

def default_log_format(explicit: Optional[str] = None) -> str:
    """Resolve a log format: explicit arg > ``FLUID_LOG_FORMAT`` env >
    "json". Loud on typos — a silently-misrouted format would
    invalidate benches and chaos runs."""
    fmt = explicit or os.environ.get("FLUID_LOG_FORMAT", "json")
    if fmt not in LOG_FORMATS:
        raise ValueError(f"log_format {fmt!r} not in {LOG_FORMATS}")
    return fmt


def make_topic(path: str, log_format: Optional[str] = None):
    """Topic factory for the supervised farm / benches: "json" →
    `SharedFileTopic`, "columnar" → `ColumnarFileTopic`."""
    fmt = default_log_format(log_format)
    return ColumnarFileTopic(path) if fmt == "columnar" else \
        SharedFileTopic(path)


def make_tail_reader(topic, line_offset: int = 0):
    """The matching incremental reader for either topic flavor."""
    if isinstance(topic, ColumnarFileTopic):
        return ColumnarTailReader(topic, line_offset)
    return TailReader(topic, line_offset)


class ColumnarFileTopic(SharedFileTopic):
    """A cross-process topic over one record-batch log file.

    Drop-in `SharedFileTopic` sibling: same constructor, same
    `append_many(...) -> bytes-written` contract, same
    `read_entries`/`read_from` record-offset semantics (JSON lines in
    the same file count one record each — the migration path), same
    fence sidecar and `FencedError` gate."""

    log_format = "columnar"

    # -------------------------------------------------- committed length

    def _clen_path(self) -> str:
        return self.path + ".clen"

    def _read_committed(self) -> Optional[int]:
        try:
            with open(self._clen_path()) as f:
                return int(json.load(f)["len"])
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def _write_committed(self, n: int) -> None:
        tmp = self._clen_path() + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"len": int(n)}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._clen_path())

    @staticmethod
    def _scan_clean_len(data: bytes) -> int:
        """Byte length of the longest prefix made of complete units
        (frames or newline-terminated lines) — the committed length of
        a topic that predates its sidecar (a migrated JSONL file)."""
        pos = 0
        for _kind, _idx, _cnt, _payload, end in iter_units(data):
            pos = end
        return pos

    # ----------------------------------------------------------- append

    def append_many(self, messages: List[Any],
                    fence: Optional[int] = None,
                    owner: Optional[str] = None,
                    lock_timeout_s: Optional[float] = None) -> int:
        """Append `messages` as ONE binary record-batch frame under the
        OS lock; returns the frame bytes written (0 for an empty batch,
        which still gates the fence — a deposed owner must learn it is
        deposed even with nothing to write)."""
        from .queue import flock_exclusive

        with open(self.path, "r+b") as f:
            with flock_exclusive(f, lock_timeout_s, self.path):
                self._gate_fence(fence, owner)
                f.seek(0, os.SEEK_END)
                size = f.tell()
                committed = self._read_committed()
                # The sidecar is a HINT that bounds the seal scan, not
                # an authority over the data: EXTEND it over any
                # complete units past it (JSON-era lines appended while
                # the farm ran the other format, frames whose sidecar
                # update was lost to a crash) so a format round-trip
                # can never truncate acknowledged records; only the
                # genuinely torn suffix (partial frame, unterminated
                # line) is sealed away — it was never acknowledged.
                start = 0 if committed is None else min(committed, size)
                f.seek(start)
                clean = start + self._scan_clean_len(f.read())
                if size > clean:
                    f.truncate(clean)
                if not messages:
                    if committed != clean:
                        self._write_committed(clean)
                    return 0
                cur_fence, cur_owner = self.latest_fence()
                frame = encode_batch(messages, fence=cur_fence,
                                     owner=cur_owner)
                check_disk_fault("topic")
                f.seek(clean)
                f.write(frame)
                f.flush()
                os.fsync(f.fileno())
                # Data is durable BEFORE the committed length names it.
                self._write_committed(clean + len(frame))
        # Event-driven consumers wake now (outside the lock, after
        # durability — queue.TopicDoorbell semantics, both formats).
        self._ring_doorbells()
        return len(frame)

    # ------------------------------------------------------------- read

    def _read_data(self) -> bytes:
        """The whole file; readers rely on the torn-unit rules (an
        incomplete frame or unterminated line is never consumed), so
        an in-flight append is naturally invisible and a stale sidecar
        can never hide acknowledged records. Complete units are never
        truncated by the seal path, so what a reader consumed stays
        consumed."""
        with open(self.path, "rb") as f:
            return f.read()

    def read_entries(self, offset: int,
                     max_count: Optional[int] = None
                     ) -> Tuple[List[Tuple[int, Any]], int]:
        """Same contract as `SharedFileTopic.read_entries`, over mixed
        frames + JSON lines: record offsets are stable (CRC-skipped
        batches and junk lines stay counted), torn units are never
        consumed, `max_count` caps the parsed entries taken."""
        data = self._read_data()
        if not data:
            return [], offset

        def capped():
            return max_count is not None and len(out) >= max_count

        out: List[Tuple[int, Any]] = []
        idx = 0
        for kind, idx0, cnt, payload, _end in iter_units(data):
            if capped():
                break
            idx = idx0 + cnt
            if kind == "batch":
                if payload is None or idx <= offset:
                    continue  # CRC-skipped or entirely below the offset
                recs = payload.records()
                for i in range(max(0, offset - idx0), cnt):
                    if capped():
                        break
                    out.append((idx0 + i, recs[i]))
            elif idx0 >= offset:
                line = payload.strip()
                if line:
                    try:
                        out.append((idx0, json.loads(line)))
                    except ValueError:
                        pass  # sealed junk from a crashed writer
        if capped():
            return out, (out[-1][0] + 1 if out else offset)
        return out, max(offset, idx)


class ColumnarTailReader:
    """Incremental reader over a `ColumnarFileTopic` (the `TailReader`
    role): remembers the byte position after the last fully-consumed
    unit, so each poll reads only NEW committed bytes — `read_entries`
    is O(file) per call, which would make a long-lived consumer
    O(file²) over its lifetime. Record offsets (`next_line`) are
    identical to `read_entries` offsets, and — like `TailReader` — a
    `line_offset` AHEAD of the file keeps `next_line == line_offset`
    (records below it are swallowed silently as they appear, never
    delivered).

    `poll()` yields decoded records for legacy consumers;
    `poll_batches()` yields raw `RecordBatch` objects (plus decoded
    stray JSON records from a migrated history) for the kernel deli's
    zero-JSON ingest. `max_count` is a batch-granular bound: a batch is
    always consumed whole, and no new batch starts once the cap is
    reached."""

    def __init__(self, topic: ColumnarFileTopic, line_offset: int = 0):
        self.topic = topic
        self.next_line = line_offset
        self._pos = 0  # byte position after the last consumed unit
        self._abs = 0  # record index of the unit at _pos
        if line_offset > 0:
            # One O(file) scan translates the record offset into a byte
            # position; everything after is incremental. Stops before
            # the unit CONTAINING the offset (mid-batch delivery is
            # handled record-wise in _poll_units).
            data = topic._read_data()
            for _kind, idx, cnt, _payload, end in iter_units(data):
                if idx + cnt > line_offset:
                    break
                self._pos = end
                self._abs = idx + cnt

    def _read_new(self) -> bytes:
        """Only the bytes past `_pos` (incremental tail); the torn-unit
        rules bound what of them is consumable."""
        try:
            with open(self.topic.path, "rb") as f:
                f.seek(self._pos)
                return f.read()
        except OSError:
            return b""

    def _poll_units(self, max_count: Optional[int]):
        data = self._read_new()
        if not data:
            return []
        units: List[tuple] = []  # ("batch", start_line, RecordBatch)
        #                        | ("rec", line, value)
        taken = 0
        consumed_bytes = 0
        for kind, rel_idx, cnt, payload, end in iter_units(
                data, self._abs):
            if max_count is not None and taken >= max_count:
                break
            consumed_bytes = end
            self._abs = rel_idx + cnt
            if kind == "batch":
                # Records below next_line (a checkpoint taken against a
                # longer topic) are swallowed without delivery.
                skip = max(0, min(cnt, self.next_line - rel_idx))
                if payload is not None and skip < cnt:
                    if skip == 0:
                        units.append(("batch", rel_idx, payload))
                    else:  # offset lands mid-batch: deliver the tail
                        recs = payload.records()
                        units.extend(
                            ("rec", rel_idx + i, recs[i])
                            for i in range(skip, cnt)
                        )
                    taken += cnt - skip
            elif rel_idx >= self.next_line:
                line = payload.strip()
                if line:
                    try:
                        units.append(("rec", rel_idx, json.loads(line)))
                        taken += 1
                    except ValueError:
                        pass  # sealed junk
            self.next_line = max(self.next_line, self._abs)
        self._pos += consumed_bytes
        return units

    def poll_batches(self, max_count: Optional[int] = None) -> List[tuple]:
        """New committed units as ``("batch", start_line, RecordBatch)``
        / ``("rec", line, value)`` tuples, in stream order."""
        return self._poll_units(max_count)

    def poll(self, max_count: Optional[int] = None
             ) -> List[Tuple[int, Any]]:
        """Decoded-records view (the `TailReader.poll` contract, with
        batch-granular `max_count`)."""
        out: List[Tuple[int, Any]] = []
        for unit in self._poll_units(max_count):
            if unit[0] == "batch":
                _, start, batch = unit
                recs = batch.records()
                out.extend((start + i, recs[i]) for i in range(batch.n))
            else:
                out.append((unit[1], unit[2]))
        return out
