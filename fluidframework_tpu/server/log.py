"""In-process ordered message log: the Kafka stand-in.

Plays the role the reference's `LocalKafka`
(server/routerlicious/packages/memory-orderer/src/localKafka.ts:17)
plays for the in-proc pipeline: an append-only log per topic with
offset-addressed reads, connecting the lambda chain
(alfred → rawdeltas → deli → deltas → scriptorium/broadcaster/scribe,
SURVEY.md §2.5). Consumers pull from an offset they own (checkpointed),
so a restarted lambda resumes exactly where it left off — the
replayability contract Kafka provides in production.

A C++ ring-buffer implementation with the same interface backs the
high-throughput path (fluidframework_tpu/native); this pure-Python
version is the reference and fallback.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional


def _encode_entry(entry: Any) -> Any:
    """Log entries carry protocol message + merge-tree op OBJECTS;
    tag-encode them so the durable journal is plain JSON and replay
    reconstructs the exact in-memory forms."""
    import dataclasses

    from ..protocol.messages import DocumentMessage, SequencedMessage

    if isinstance(entry, SequencedMessage):
        return {"__seqmsg__": {
            "seq": entry.sequence_number, "msn": entry.minimum_sequence_number,
            "client": entry.client_id, "cseq": entry.client_seq,
            "ref": entry.ref_seq, "type": entry.type.value,
            "contents": _encode_entry(entry.contents),
            "metadata": _encode_entry(entry.metadata),
            "address": entry.address, "ts": entry.timestamp,
        }}
    if isinstance(entry, DocumentMessage):
        return {"__docmsg__": {
            "cseq": entry.client_seq, "ref": entry.ref_seq,
            "type": entry.type.value,
            "contents": _encode_entry(entry.contents),
            "metadata": _encode_entry(entry.metadata),
            "address": entry.address,
        }}
    if dataclasses.is_dataclass(entry) and not isinstance(entry, type):
        from ..protocol.mergetree_ops import op_to_json

        return {"__op__": op_to_json(entry)}
    if isinstance(entry, dict):
        return {k: _encode_entry(v) for k, v in entry.items()}
    if isinstance(entry, list):
        return [_encode_entry(v) for v in entry]
    return entry


def _decode_entry(data: Any) -> Any:
    from ..protocol.messages import (
        DocumentMessage,
        MessageType,
        SequencedMessage,
    )

    if isinstance(data, dict):
        if "__seqmsg__" in data:
            d = data["__seqmsg__"]
            return SequencedMessage(
                sequence_number=d["seq"], minimum_sequence_number=d["msn"],
                client_id=d["client"], client_seq=d["cseq"],
                ref_seq=d["ref"], type=MessageType(d["type"]),
                contents=_decode_entry(d["contents"]),
                metadata=_decode_entry(d["metadata"]),
                address=d.get("address"), timestamp=d.get("ts", 0.0),
            )
        if "__docmsg__" in data:
            d = data["__docmsg__"]
            return DocumentMessage(
                client_seq=d["cseq"], ref_seq=d["ref"],
                type=MessageType(d["type"]),
                contents=_decode_entry(d["contents"]),
                metadata=_decode_entry(d["metadata"]),
                address=d.get("address"),
            )
        if "__op__" in data:
            from ..protocol.mergetree_ops import op_from_json

            return op_from_json(data["__op__"])
        return {k: _decode_entry(v) for k, v in data.items()}
    if isinstance(data, list):
        return [_decode_entry(v) for v in data]
    return data


# Stand-in for a journal record lost to in-place corruption (a
# CRC-failed frame, a junk line): replay keeps the SLOT so every later
# record keeps its offset — persisted lambda checkpoints cite absolute
# offsets, and dropping a corrupt unit would silently shift every
# consumer past it (the columnar readers' skip-but-COUNT rule, applied
# to the in-proc journal). Consumers treat it as a no-op record.
LOST_RECORD = {"kind": "__lost__", "doc": None}


def _replay_journal(path: str):
    """Replay a topic journal that may mix JSONL lines and columnar
    record-batch frames (`protocol.record_batch`) — the cross-format
    migration path: a journal written as JSONL keeps replaying after
    the server restarts with ``log_format="columnar"`` and vice versa.
    Corrupt units replay as `LOST_RECORD` placeholders (offsets stay
    stable). Returns ``(values, clean_len)``; bytes past `clean_len`
    are a torn tail (a writer died mid-append) the caller truncates
    before appending again."""
    import json

    from ..protocol.record_batch import iter_units

    with open(path, "rb") as f:
        data = f.read()
    vals: List[Any] = []
    clean_len = 0
    for kind, _idx, cnt, payload, end in iter_units(data):
        clean_len = end
        if kind == "batch":
            if payload is None:  # CRC failure: hold the slots
                vals.extend(LOST_RECORD for _ in range(cnt))
            else:
                vals.extend(payload.records())
        else:
            line = payload.strip()
            if not line:
                vals.append(LOST_RECORD)
                continue
            try:
                vals.append(json.loads(line))
            except ValueError:
                vals.append(LOST_RECORD)  # sealed junk: hold the slot
    return vals, clean_len


class LogTopic:
    """One append-only, offset-addressed message log. With a backing
    `path`, every append also journals to disk (flushed) and the topic
    replays from the journal on open — the Kafka topic retention that
    makes lambda restart/catch-up real across PROCESS restarts.

    `log_format` picks the journal wire form: "json" (one JSONL line
    per record) or "columnar" (one `protocol.record_batch` frame per
    append — the batched binary op-log). Replay reads BOTH, so a
    restart may switch formats mid-journal."""

    def __init__(self, name: str, path: Optional[str] = None,
                 log_format: str = "json"):
        self.name = name
        self.log_format = log_format
        self._messages: List[Any] = []
        self._subscribers: List[Callable[[int, Any], None]] = []
        self._path = path
        self._file = None
        if path and os.path.exists(path):
            vals, clean_len = _replay_journal(path)
            self._messages.extend(_decode_entry(v) for v in vals)
            if clean_len < os.path.getsize(path):
                # Seal the torn tail NOW (the crashed writer's partial
                # record was never acknowledged) so new appends start
                # on a clean unit boundary.
                with open(path, "r+b") as f:
                    f.truncate(clean_len)

    def append(self, message: Any) -> int:
        """Append; returns the message's offset."""
        return self.append_many([message])

    def append_many(self, messages: List[Any]) -> int:
        """Append a batch: ONE journal write + flush for the whole
        batch instead of one per record (the lambdas' per-pump output
        flush — the per-record encode/write/flush was the scalar
        pipeline's hidden hot path). Returns the first offset."""
        off = len(self._messages)
        if not messages:
            return off
        self._messages.extend(messages)
        if self._path is not None:
            if self._file is None:
                self._file = open(self._path, "ab")
            if self.log_format == "columnar":
                from ..protocol.record_batch import encode_batch

                self._file.write(
                    encode_batch([_encode_entry(m) for m in messages])
                )
            else:
                import json

                self._file.write(
                    "".join(
                        json.dumps(_encode_entry(m)) + "\n"
                        for m in messages
                    ).encode()
                )
            self._file.flush()
        for i, m in enumerate(messages):
            for fn in list(self._subscribers):
                fn(off + i, m)
        return off

    def sync(self) -> None:
        """fsync the journal (called at durability points: summary
        refs, checkpoint writes)."""
        if self._file is not None:
            os.fsync(self._file.fileno())

    def read(self, from_offset: int, max_count: Optional[int] = None) -> List[Any]:
        end = len(self._messages)
        if max_count is not None:
            end = min(end, from_offset + max_count)
        return self._messages[from_offset:end]

    def subscribe(self, fn: Callable[[int, Any], None]) -> None:
        """Push notification on append (the pipeline's pump)."""
        self._subscribers.append(fn)

    @property
    def head(self) -> int:
        return len(self._messages)


class MessageLog:
    """Named topics (the broker). With `directory`, topics journal to
    <directory>/<topic>.jsonl and replay on open (`log_format` picks
    JSONL lines vs columnar record-batch frames; the file name stays
    `.jsonl` either way so a restart can switch formats over the same
    journal — replay reads both)."""

    def __init__(self, directory: Optional[str] = None,
                 log_format: str = "json"):
        self.topics: Dict[str, LogTopic] = {}
        self.directory = directory
        self.log_format = log_format
        if directory:
            os.makedirs(directory, exist_ok=True)

    def topic(self, name: str) -> LogTopic:
        if name not in self.topics:
            path = (
                os.path.join(self.directory, f"{name}.jsonl")
                if self.directory else None
            )
            self.topics[name] = LogTopic(name, path, self.log_format)
        return self.topics[name]

    def sync(self) -> None:
        for t in self.topics.values():
            t.sync()


class LogConsumer:
    """An offset-owning reader of one topic (the rdkafka consumer role,
    services-ordering-rdkafka/src/rdkafkaConsumer.ts:37). `offset` is
    the consumer's checkpoint state."""

    def __init__(self, topic: LogTopic, offset: int = 0):
        self.topic = topic
        self.offset = offset

    def poll(self, max_count: Optional[int] = None) -> List[Any]:
        msgs = self.topic.read(self.offset, max_count)
        self.offset += len(msgs)
        return msgs

    def checkpoint(self) -> int:
        return self.offset
