"""In-process ordered message log: the Kafka stand-in.

Plays the role the reference's `LocalKafka`
(server/routerlicious/packages/memory-orderer/src/localKafka.ts:17)
plays for the in-proc pipeline: an append-only log per topic with
offset-addressed reads, connecting the lambda chain
(alfred → rawdeltas → deli → deltas → scriptorium/broadcaster/scribe,
SURVEY.md §2.5). Consumers pull from an offset they own (checkpointed),
so a restarted lambda resumes exactly where it left off — the
replayability contract Kafka provides in production.

A C++ ring-buffer implementation with the same interface backs the
high-throughput path (fluidframework_tpu/native); this pure-Python
version is the reference and fallback.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional


class LogTopic:
    """One append-only, offset-addressed message log."""

    def __init__(self, name: str):
        self.name = name
        self._messages: List[Any] = []
        self._subscribers: List[Callable[[int, Any], None]] = []

    def append(self, message: Any) -> int:
        """Append; returns the message's offset."""
        off = len(self._messages)
        self._messages.append(message)
        for fn in list(self._subscribers):
            fn(off, message)
        return off

    def read(self, from_offset: int, max_count: Optional[int] = None) -> List[Any]:
        end = len(self._messages)
        if max_count is not None:
            end = min(end, from_offset + max_count)
        return self._messages[from_offset:end]

    def subscribe(self, fn: Callable[[int, Any], None]) -> None:
        """Push notification on append (the pipeline's pump)."""
        self._subscribers.append(fn)

    @property
    def head(self) -> int:
        return len(self._messages)


class MessageLog:
    """Named topics (the broker)."""

    def __init__(self):
        self.topics: Dict[str, LogTopic] = {}

    def topic(self, name: str) -> LogTopic:
        if name not in self.topics:
            self.topics[name] = LogTopic(name)
        return self.topics[name]


class LogConsumer:
    """An offset-owning reader of one topic (the rdkafka consumer role,
    services-ordering-rdkafka/src/rdkafkaConsumer.ts:37). `offset` is
    the consumer's checkpoint state."""

    def __init__(self, topic: LogTopic, offset: int = 0):
        self.topic = topic
        self.offset = offset

    def poll(self, max_count: Optional[int] = None) -> List[Any]:
        msgs = self.topic.read(self.offset, max_count)
        self.offset += len(msgs)
        return msgs

    def checkpoint(self) -> int:
        return self.offset
