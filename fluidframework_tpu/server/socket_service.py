"""TCP socket front end for a LocalServer: a REAL process boundary.

The reference's client↔service boundary is a socket
(drivers/driver-base/src/documentDeltaConnection.ts:42 over socket.io;
alfred's WS door, lambdas/src/alfred/index.ts:211). Round 1's drivers
only ever met the server inside one interpreter; this module serves
the full lambda pipeline over TCP so a container in another PROCESS
(or host) collaborates through it via `drivers.socket_driver`.

Protocol: length-prefixed binary frames (server/framing.py: 4-byte
big-endian length + JSON payload).
- request:  {"id": n, "cmd": <name>, ...args}
- response: {"id": n, "result": ...} | {"id": n, "error": "..."}
- push (after "connect" on that socket):
    {"event": "op", "msg": <sequenced-wire>}
    {"event": "nack", "msg": {...}}

One TCP connection == one session: it may perform storage/control
calls and hold at most one delta connection. All server work is
serialized under one lock (the in-proc pipeline is single-threaded by
design, like the reference's per-partition lambdas).
"""

from __future__ import annotations

import base64
import json
import os
import queue
import socket
import socketserver
import threading
import time
from typing import Any, Optional

from ..drivers.file_driver import message_to_json
from ..protocol.messages import DocumentMessage, MessageType, NackMessage
from .framing import encode_frame, read_frame, write_frame


def document_message_from_json(data: dict) -> DocumentMessage:
    return DocumentMessage(
        client_seq=data["clientSequenceNumber"],
        ref_seq=data["referenceSequenceNumber"],
        type=MessageType(data["type"]),
        contents=data.get("contents"),
        metadata=data.get("metadata"),
        address=data.get("address"),
    )


def _wire_contents(contents):
    """Wire form of op contents: plain JSON types pass through
    untouched (the hot path); anything carrying dataclasses (in-proc
    merge-tree ops) round-trips through the wire encoder."""
    if contents is None or isinstance(contents, (str, int, float, bool)):
        return contents
    if isinstance(contents, dict) and all(
        v is None or isinstance(v, (str, int, float, bool))
        for v in contents.values()
    ):
        return contents
    from ..runtime.op_lifecycle import _dumps

    return json.loads(_dumps(contents))


def document_message_to_json(msg: DocumentMessage) -> dict:
    return {
        "clientSequenceNumber": msg.client_seq,
        "referenceSequenceNumber": msg.ref_seq,
        "type": msg.type.value,
        "contents": _wire_contents(msg.contents),
        "metadata": msg.metadata,
        "address": msg.address,
    }


class _Session(socketserver.StreamRequestHandler):
    # A stalled client (full TCP buffer) must not wedge the server:
    # its outbound queue fills and that session alone is evicted.
    timeout = 30
    OUTQ_MAX = 4096

    def setup(self) -> None:
        super().setup()
        self.connection.settimeout(30)
        # Nagle + delayed-ACK interaction stalls small request/response
        # frames ~40ms each; this is an RPC socket, not a bulk pipe.
        self.connection.setsockopt(
            socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
        )
        # Per-session outbound queue drained by a writer thread:
        # _send never blocks on the network, so pushes that run while
        # the dispatcher holds srv.lock cannot stall other sessions
        # (a global write lock would serialize every session behind
        # the slowest socket for up to the 30s timeout).
        self._outq: "queue.Queue" = queue.Queue(maxsize=self.OUTQ_MAX)
        self._dead = threading.Event()
        self._writer = threading.Thread(target=self._write_loop, daemon=True)
        self._writer.start()

    def _write_loop(self) -> None:
        while True:
            obj = self._outq.get()
            if obj is None:
                return
            try:
                if isinstance(obj, bytes):  # pre-encoded frame
                    self.wfile.write(obj)
                    self.wfile.flush()
                else:
                    write_frame(self.wfile, obj)
            except Exception:
                self._kill()
                return

    def _kill(self) -> None:
        self._dead.set()
        try:
            self.connection.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def handle(self) -> None:
        srv: "SocketDeltaServer" = self.server.owner  # type: ignore
        conn = None
        try:
            while True:
                req = read_frame(self.rfile)
                if req is None:
                    break
                try:
                    result, conn = self._dispatch(srv, req, conn)
                    self._send({"id": req.get("id"), "result": result})
                except Exception as exc:  # surfaced to the client
                    self._send({"id": req.get("id"), "error": str(exc)})
        except (ConnectionError, ValueError, OSError):
            pass
        finally:
            try:
                self._outq.put_nowait(None)  # stop the writer
            except queue.Full:
                pass  # writer already dead (_kill); nothing to stop
            if conn is not None:
                with srv.lock:
                    conn.disconnect()

    def _send_ops_batch(self, msgs, memo) -> None:
        """Batched broadcast push: ONE frame per broadcaster pump,
        encoded once per room (`memo` shared across the room's
        sessions when they accept the full batch)."""
        if memo is not None and "frame" in memo:
            data = memo["frame"]
        else:
            from .framing import KIND_OPS

            data = encode_frame(
                {"event": "ops",
                 "msgs": [message_to_json(m) for m in msgs]},
                kind=KIND_OPS,
            )
            if memo is not None:
                memo["frame"] = data
        self._send(data)

    def _send(self, obj) -> None:
        if self._dead.is_set():
            raise ConnectionError("session transport dead")
        try:
            self._outq.put_nowait(obj)
        except queue.Full:
            # Slow client: evict this session only (the broadcaster's
            # _deliver_safe catches this and keeps the room going).
            self._kill()
            raise ConnectionError("session outbound queue full")

    def _dispatch(self, srv: "SocketDeltaServer", req: dict, conn):
        cmd = req["cmd"]
        ls = srv.local_server
        if srv.tenants is not None:
            # Riddler gate: signed token bound to (tenant, document),
            # scopes checked per command class (alfred/index.ts:595).
            srv.tenants.authorize_command(
                cmd, req.get("token"), req.get("tenantId"),
                req.get("docId"),
            )
        with srv.lock:
            if cmd == "create_document":
                handle = ls.upload_summary(req["summary"])
                ls.storage.set_ref(req["docId"], handle)
                return True, conn
            if cmd == "load_document":
                return ls.download_summary(req["docId"]), conn
            if cmd == "ops_from":
                ops = ls.ops_from(req["docId"], req["fromSeq"])
                to_seq = req.get("toSeq")
                if to_seq is not None:  # server-side ranged read
                    ops = [m for m in ops if m.sequence_number <= to_seq]
                return [message_to_json(m) for m in ops], conn
            if cmd == "catchup":
                # Nearest summary + op tail in ONE round trip (the
                # summary-service join shape; see LocalServer.catchup).
                res = ls.catchup(req["docId"], req.get("fromSeq", 0))
                return {
                    "summary": res["summary"],
                    "summarySeq": res["summarySeq"],
                    "ops": [message_to_json(m) for m in res["ops"]],
                }, conn
            if cmd == "upload_blob":
                return ls.storage.put(base64.b64decode(req["data"])), conn
            if cmd == "read_blob":
                return base64.b64encode(
                    ls.storage.get(req["blobId"])
                ).decode(), conn
            if cmd == "connect":
                assert conn is None, "session already holds a connection"
                conn = ls.connect(req["docId"], req.get("clientId"))
                conn.listener = lambda m: self._send(
                    {"event": "op", "msg": message_to_json(m)}
                )
                conn.batch_listener = self._send_ops_batch
                conn.nack_listener = lambda n: self._send(
                    {"event": "nack",
                     "msg": {"clientId": n.client_id, "clientSeq": n.client_seq,
                             "code": n.code, "reason": n.reason}}
                )
                return {"clientId": conn.client_id,
                        "joinSeq": conn.join_seq}, conn
            if cmd == "catch_up":
                assert conn is not None
                return [
                    message_to_json(m) for m in conn.catch_up(req["fromSeq"])
                ], conn
            if cmd == "submit":
                assert conn is not None
                conn.submit(document_message_from_json(req["msg"]))
                return True, conn
            if cmd == "submit_batch":
                assert conn is not None
                conn.submit_batch(
                    [document_message_from_json(m) for m in req["msgs"]]
                )
                return True, conn
            if cmd == "disconnect":
                if conn is not None:
                    conn.disconnect()
                return True, None
        raise ValueError(f"unknown cmd {cmd!r}")


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class FarmTailPusher:
    """Doorbell-aware tail of a supervised-farm topic: the TCP front
    end's wakeup spine (PR 9 follow-up c — the poll loop retired).

    One background thread registers a `queue.TopicDoorbell` on the
    topic and sleeps on it with a BOUNDED timeout — the poll fallback
    that keeps every correctness property doorbell-independent — then
    drains the incremental tail reader and (a) fans new records out to
    per-doc subscribers, (b) advances the per-doc head seq and wakes
    anyone blocked in `wait_for`. Both the live push AND the catch-up
    long-poll therefore ride the same event wakeup: an `append_many`
    on the topic rings once, and every subscribed socket plus every
    pending catch-up response proceeds without a poll interval in the
    path."""

    def __init__(self, topic_path: str, log_format: Optional[str] = None,
                 poll_s: float = 0.05, batch: int = 4096):
        from .columnar_log import make_tail_reader, make_topic
        from .queue import TopicDoorbell, doorbells_enabled

        self.topic_path = topic_path
        self._reader = make_tail_reader(make_topic(topic_path, log_format))
        self._bell = None
        if doorbells_enabled():
            try:
                self._bell = TopicDoorbell(topic_path)
            except OSError:
                self._bell = None
        self.poll_s = poll_s
        self.batch = batch
        self._subs: dict = {}  # doc -> [fn(records), ...]
        self._cond = threading.Condition()
        self.head_seq: dict = {}  # doc -> newest seq seen
        self.delivered = 0
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> "FarmTailPusher":
        self._thread.start()
        return self

    # ----------------------------------------------------- subscriptions

    def subscribe(self, doc_id: str, fn) -> None:
        with self._cond:
            self._subs.setdefault(doc_id, []).append(fn)

    def unsubscribe(self, doc_id: str, fn) -> None:
        with self._cond:
            subs = self._subs.get(doc_id, [])
            if fn in subs:
                subs.remove(fn)
            if not subs:
                self._subs.pop(doc_id, None)

    def wait_for(self, doc_id: str, seq: int,
                 timeout_s: float = 5.0) -> bool:
        """Block until the topic holds `doc_id`'s seq >= `seq` (the
        catch-up long-poll: woken by the same doorbell ring that wakes
        the live push), bounded by `timeout_s`."""
        deadline = time.time() + timeout_s
        with self._cond:
            while self.head_seq.get(doc_id, 0) < seq:
                left = deadline - time.time()
                if left <= 0:
                    return False
                self._cond.wait(left)
        return True

    # ------------------------------------------------------------- loop

    def _loop(self) -> None:
        while not self._stopped.is_set():
            try:
                entries = self._reader.poll(self.batch)
            except OSError:
                entries = []
            if not entries:
                if self._bell is not None:
                    self._bell.wait(self.poll_s)
                else:
                    self._stopped.wait(self.poll_s)
                continue
            per_doc: dict = {}
            with self._cond:
                for _, rec in entries:
                    if not isinstance(rec, dict) or "doc" not in rec:
                        continue
                    doc = rec["doc"]
                    if rec.get("kind") == "op":
                        self.head_seq[doc] = max(
                            self.head_seq.get(doc, 0), int(rec["seq"])
                        )
                    per_doc.setdefault(doc, []).append(rec)
                self._cond.notify_all()
                # Snapshot the fan-out targets under the lock; deliver
                # outside it (a slow subscriber must not block
                # wait_for wakeups).
                targets = [
                    (fns[:], recs) for doc, recs in per_doc.items()
                    for fns in (self._subs.get(doc, []),) if fns
                ]
            for fns, recs in targets:
                for fn in fns:
                    try:
                        fn(recs)
                        self.delivered += len(recs)
                    except Exception:
                        # Dead subscriber: evict it, keep the room.
                        with self._cond:
                            docs = [d for d, subs in self._subs.items()
                                    if fn in subs]
                        for doc in docs:
                            self.unsubscribe(doc, fn)

    def stop(self) -> None:
        self._stopped.set()
        self._thread.join(timeout=5)
        if self._bell is not None:
            self._bell.close()


class _FarmSession(socketserver.StreamRequestHandler):
    """One farm-read TCP session: catch-up requests + live push."""

    timeout = 30

    def setup(self) -> None:
        super().setup()
        self.connection.settimeout(30)
        self.connection.setsockopt(
            socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
        )
        self._wlock = threading.Lock()
        self._push_docs: list = []
        self._nack_docs: list = []

    def _send(self, obj) -> None:
        with self._wlock:
            write_frame(self.wfile, obj)

    def _push(self, recs) -> None:
        try:
            self._send({"event": "recs", "recs": recs})
        except Exception:
            # Dead/stalled subscriber: tear the transport down so the
            # handler thread (parked in recv) exits too, then let the
            # pusher's eviction see the failure.
            try:
                self.connection.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            raise

    def _push_nacks(self, recs) -> None:
        # The front door's rejections (`server.ingress` nack records)
        # ride their own event so clients route them to the nack
        # handler, not the op stream.
        try:
            self._send({"event": "nacks", "recs": recs})
        except Exception:
            try:
                self.connection.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            raise

    def handle(self) -> None:
        srv: "FarmReadServer" = self.server.owner  # type: ignore
        try:
            while True:
                try:
                    req = read_frame(self.rfile)
                except socket.timeout:
                    # A passive SUBSCRIBER never sends requests; the
                    # recv timeout must not kill its live feed (pushes
                    # flow outbound; a dead client is reaped by the
                    # push path's send failure instead). Sessions with
                    # no subscription keep the idle-reap behavior.
                    if self._push_docs or self._nack_docs:
                        continue
                    break
                if req is None:
                    break
                try:
                    result = self._dispatch(srv, req)
                    self._send({"id": req.get("id"), "result": result})
                except Exception as exc:
                    self._send({"id": req.get("id"), "error": str(exc)})
        except (ConnectionError, ValueError, OSError):
            pass
        finally:
            for doc in self._push_docs:
                srv.pusher.unsubscribe(doc, self._push)
            if srv.nack_pusher is not None:
                for doc in self._nack_docs:
                    srv.nack_pusher.unsubscribe(doc, self._push_nacks)

    def _dispatch(self, srv: "FarmReadServer", req: dict):
        cmd = req["cmd"]
        if cmd == "catchup":
            # waitSeq long-poll: the response waits (bounded) for the
            # topic to hold that seq — woken by the SAME doorbell ring
            # that wakes the live push, so catch-up never polls.
            wait_seq = req.get("waitSeq")
            if wait_seq is not None:
                srv.pusher.wait_for(
                    req["docId"], int(wait_seq),
                    float(req.get("timeout", 5.0)),
                )
            return srv.catchup(req["docId"], req.get("fromSeq"))
        if cmd == "subscribe":
            doc = req["docId"]
            self._push_docs.append(doc)
            srv.pusher.subscribe(doc, self._push)
            if srv.nack_pusher is not None:
                # The same subscription tails the front door's nacks
                # topic: a rejected submit reaches its doc's sessions
                # as an {"event": "nacks"} push (the alfred nack edge).
                self._nack_docs.append(doc)
                srv.nack_pusher.subscribe(doc, self._push_nacks)
            return {"docId": doc,
                    "headSeq": srv.pusher.head_seq.get(doc, 0)}
        if cmd == "head":
            return {"docId": req["docId"],
                    "headSeq": srv.pusher.head_seq.get(req["docId"], 0)}
        raise ValueError(f"unknown cmd {cmd!r}")


class FarmReadServer:
    """The supervised farm's READ front end over TCP: summary catch-up
    (`server.summarizer.read_catchup` — nearest summary manifest +
    blob + op tail) and live broadcast fan-out, both driven by ONE
    doorbell-woken tail thread (`FarmTailPusher`). The write path
    stays the farm's raw topic; this serves the read-heavy side —
    joins and subscriptions — that PAPER.md names as the real traffic
    shape."""

    def __init__(self, shared_dir: str, host: str = "127.0.0.1",
                 port: int = 0, log_format: Optional[str] = None,
                 push_topic: str = "broadcast",
                 deltas_topic: str = "deltas",
                 nacks: bool = False):
        """`nacks=True` tails the front door's ``nacks`` topic with a
        second doorbell-woken pusher: every subscribed session also
        receives its doc's admission rejections (`server.ingress`
        auth/size/rate/backpressure nack records) as ``nacks``
        pushes — the alfred submit→nack feedback edge over TCP."""
        from .summarizer import SummaryIndex, open_summary_store

        self.shared_dir = shared_dir
        self.log_format = log_format
        self.deltas_topic = deltas_topic
        self.index = SummaryIndex(shared_dir, log_format)
        self.store = open_summary_store(shared_dir)
        self.pusher = FarmTailPusher(
            os.path.join(shared_dir, "topics", f"{push_topic}.jsonl"),
            log_format,
        )
        self.nack_pusher: Optional[FarmTailPusher] = (
            FarmTailPusher(
                os.path.join(shared_dir, "topics", "nacks.jsonl"),
                log_format,
            ) if nacks else None
        )
        self._tcp = _FarmTCPServer((host, port), _FarmSession)
        self._tcp.owner = self  # type: ignore
        self.host, self.port = self._tcp.server_address
        self._thread: Optional[threading.Thread] = None

    def catchup(self, doc_id: str,
                from_seq: Optional[int] = None) -> dict:
        """Summary-aware reconnect: a session at `from_seq` gets

        - ``from_seq >= newest summary seq`` (short gap): the op gap
          alone — no blob shipped, the tail seek is O(tail) via the
          manifest's byte offset;
        - ``from_seq < newest summary seq`` (long offline): the newest
          summary blob + the tail PAST it — the client REBOOTS from
          the summary instead of replaying the op gap, which with the
          retention plane on may no longer physically exist."""
        from .summarizer import read_catchup

        res = read_catchup(
            self.shared_dir, doc_id, self.log_format,
            index=self.index, store=self.store,
            deltas_topic=self.deltas_topic,
        )
        base = res["manifest"]["seq"] if res["manifest"] else 0
        ops = res["ops"]
        if from_seq is not None and from_seq >= base:
            ops = [r for r in ops if int(r["seq"]) > from_seq]
            return {"manifest": res["manifest"], "blob": None,
                    "ops": ops, "rebase": False}
        return {"manifest": res["manifest"], "blob": res["blob"],
                "ops": ops, "rebase": res["manifest"] is not None}

    def start(self) -> "FarmReadServer":
        self.pusher.start()
        if self.nack_pusher is not None:
            self.nack_pusher.start()
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()
        self.pusher.stop()
        if self.nack_pusher is not None:
            self.nack_pusher.stop()


class _FarmTCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class SocketDeltaServer:
    """Serve a LocalServer over TCP (the LocalDeltaConnectionServer →
    network door step)."""

    def __init__(self, local_server, host: str = "127.0.0.1", port: int = 0,
                 tenants=None, allow_anonymous: bool = False):
        """`tenants`: a `server.riddler.TenantManager`. When set, EVERY
        command must carry valid tenant credentials (tenantId + signed
        token bound to the document, with scopes covering the command)
        — the alfred token gate (alfred/index.ts:595); failures
        surface as error responses (the auth-nack path).

        SECURE BY DEFAULT (the reference validates tokens
        unconditionally): constructing without a TenantManager
        requires the explicit ``allow_anonymous=True`` opt-out — the
        tinylicious-style open dev mode cannot happen by accident."""
        if tenants is None and not allow_anonymous:
            raise ValueError(
                "SocketDeltaServer is secure by default: pass a "
                "TenantManager via tenants=, or opt out explicitly "
                "with allow_anonymous=True"
            )
        self.local_server = local_server
        self.tenants = tenants
        self.lock = threading.RLock()
        self._tcp = _TCPServer((host, port), _Session)
        self._tcp.owner = self  # type: ignore
        self.host, self.port = self._tcp.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "SocketDeltaServer":
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()
