"""Live ops endpoint: `/metrics` + `/healthz` over stdlib HTTP.

The reference service exposes per-pod health and metrics endpoints the
orchestrator and dashboards scrape; this module is that surface for
the in-proc `LocalServer` and the supervised farm
(`server.supervisor.ServiceSupervisor`):

- ``GET /metrics``       — Prometheus text exposition of the bound
  registry (per-stage op-latency histograms, kernel occupancy gauges,
  checkpoint/restart counters).
- ``GET /metrics.json``  — the same state as a JSON snapshot
  (`MetricsRegistry.snapshot()` form, consumable by
  tools/metrics_report.py).
- ``GET /healthz``       — liveness JSON from the bound health
  callback; HTTP 200 iff ``status == "ok"``, 503 otherwise.
- ``GET /slo``           — the tail-latency summary: every histogram
  with observations reduced to count/mean/p50/p95/p99
  (bucket-interpolated, `utils.metrics.slo_summary`), plus the
  admission feedback counters (``ingress_*`` nack/throttle/admit
  totals) so refused load is visible next to admitted latency.
- ``GET /traces``        — the slow-op flight recorder's span buffer
  (`utils.metrics.FlightRecorder`): the exact ops whose end-to-end
  latency crossed the threshold/rolling p99, with all their stage
  timestamps, so a tail regression report carries its evidence.

The registry may be passed as an instance or a zero-arg callable
returning one — the supervisor rebuilds its registry per scrape by
merging the children's heartbeat snapshots; `traces` likewise accepts
a zero-arg callable returning the span list (defaults to the process
flight recorder).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Union

from ..utils.metrics import (
    MetricsRegistry,
    get_flight_recorder,
    get_registry,
    slo_summary,
)

__all__ = ["MetricsServer"]


class MetricsServer:
    """Threaded HTTP server for `/metrics`, `/metrics.json`, `/healthz`.

    `registry`: a `MetricsRegistry`, or a callable returning one per
    scrape; defaults to the process registry. `health`: zero-arg
    callable returning a JSON-able dict; a ``"status"`` key of
    ``"ok"`` maps to HTTP 200, anything else to 503."""

    def __init__(
        self,
        registry: Union[MetricsRegistry, Callable[[], MetricsRegistry],
                        None] = None,
        health: Optional[Callable[[], Dict[str, Any]]] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        traces: Optional[Callable[[], List[dict]]] = None,
    ):
        self._registry = registry
        self._health = health
        self._traces = traces
        self.host = host
        self._requested_port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ state

    @property
    def port(self) -> int:
        if self._httpd is None:
            return self._requested_port
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def _resolve_registry(self) -> MetricsRegistry:
        reg = self._registry
        if reg is None:
            return get_registry()
        if callable(reg) and not hasattr(reg, "to_prometheus"):
            return reg()
        return reg

    def _resolve_health(self) -> Dict[str, Any]:
        if self._health is None:
            return {"status": "ok"}
        out = self._health()
        if "status" not in out:
            out = {"status": "ok", **out}
        return out

    def _resolve_traces(self) -> List[dict]:
        if self._traces is None:
            return get_flight_recorder().snapshot()
        return self._traces()

    # -------------------------------------------------------- lifecycle

    def start(self) -> "MetricsServer":
        assert self._httpd is None, "already started"
        server = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args) -> None:  # quiet scrapes
                pass

            def _reply(self, code: int, body: str,
                       ctype: str) -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self) -> None:  # noqa: N802 (stdlib contract)
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        self._reply(
                            200,
                            server._resolve_registry().to_prometheus(),
                            "text/plain; version=0.0.4",
                        )
                    elif path == "/metrics.json":
                        self._reply(
                            200,
                            json.dumps(
                                server._resolve_registry().snapshot()
                            ),
                            "application/json",
                        )
                    elif path == "/slo":
                        self._reply(
                            200,
                            json.dumps(slo_summary(
                                server._resolve_registry().snapshot()
                            )),
                            "application/json",
                        )
                    elif path == "/traces":
                        self._reply(
                            200,
                            json.dumps(
                                {"slow_ops": server._resolve_traces()}
                            ),
                            "application/json",
                        )
                    elif path == "/healthz":
                        health = server._resolve_health()
                        code = 200 if health.get("status") == "ok" else 503
                        self._reply(code, json.dumps(health),
                                    "application/json")
                    else:
                        self._reply(404, "not found\n", "text/plain")
                except (BrokenPipeError, ConnectionError):
                    return  # scraper went away mid-response: nothing to tell it
                except Exception as exc:  # scrape must never kill serving
                    try:
                        self._reply(500, f"{exc!r}\n", "text/plain")
                    except (BrokenPipeError, ConnectionError, OSError):
                        pass

        self._httpd = ThreadingHTTPServer(
            (self.host, self._requested_port), _Handler
        )
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="fluid-metrics-server",
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
