"""Sharded ordering fabric: a lease-balanced, multi-partition kernel-
deli farm with fenced partition handoff.

The reference scales routerlicious horizontally by splitting the
document space across Kafka partitions with ZooKeeper arbitrating
consumer ownership (SURVEY.md §2.5). This module is that topology over
the repo's own primitives — partitioning as a first-class subsystem
instead of the single-partition pipeline PRs 1–4 grew:

- **Document-space slicing** — `queue.partition_of` (consistent hash)
  maps every doc to one of N partitions; `ShardRouter` is the ingress
  edge (the lambdas-driver document-router role): one raw/sequenced
  topic pair PER partition (``rawdeltas-p{k}`` → ``deltas-p{k}``),
  boxcar records riding whole with their doc.
- **Lease-balanced ownership** — `ShardWorker` (one OS process) sweeps
  the partition leases (`queue.LeaseManager`, the zookeeper role) and
  runs ONE supervised deli role per owned partition
  (`supervisor.partitioned_role_class` over the scalar `DeliRole` or
  the device-batched `deli_kernel.KernelDeliRole`, either log format).
  Workers announce liveness in ``<dir>/workers/<slot>.json``; each
  targets ``ceil(N / alive_workers)`` partitions, so membership change
  IS the rebalance trigger: a joining worker makes peers shed surplus
  partitions (graceful release → immediate takeover), a dead worker's
  stale heartbeat raises the survivors' target and its expired leases
  are swept up.
- **Fenced handoff, exactly-once** — a partition changes hands through
  the PR-1 machinery unchanged: the new owner's lease carries a higher
  fence, its first output append binds that fence on ``deltas-p{k}``
  (a deposed owner's in-flight batch is REJECTED with `FencedError`),
  the loser's fenced checkpoint — per-doc sequencer state in
  `DocumentSequencer.checkpoint()` format, i.e. a `SeqPool` slice when
  the kernel deli wrote it — is restored by `_Role._recover`, and the
  exactly-once ``inOff`` scan replays the checkpoint→durable gap
  silently. A kill or rebalance mid-boxcar never dups or skips a
  sequence number (tests/test_chaos_recovery.py drives this with
  ``--faults kill,lease`` over the kernel+columnar fabric).
- **Supervision + observability** — `ShardFabricSupervisor` runs W
  workers as monitored children through the `ServiceSupervisor`
  machinery (heartbeat staleness, crash restart, fresh owner identity
  per generation); worker heartbeats carry per-partition-labeled
  metrics (``role="deli", partition="3"``) that the supervisor scrape
  merges into one registry.

`tools/shard_run.py` is the CLI; `testing.deli_bench.run_shard_bench`
proves the aggregate-throughput scaling (bench_configs
``config6_shard_scaling`` guards ≥1.5x at 4 partitions on ≥4-core
hosts); `tools/partition_worker_main.py` is now a thin wrapper over
`ShardWorker`.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from .columnar_log import (
    LOG_FORMATS,
    ColumnarFileTopic,
    default_log_format,
    make_tail_reader,
    make_topic,
)
from .queue import (
    FencedError,
    LeaseManager,
    RangeLeaseStore,
    doc_hash,
    lease_owners,
    lease_table,
    merge_ranges,
    partition_suffix,
    range_for_doc,
    record_partition,
    split_by_partition,
    split_ranges,
)
from .supervisor import (
    DELI_IMPLS,
    EXIT_FENCED,
    BroadcasterRole,
    ScribeRole,
    ScriptoriumBroadcasterRole,
    ScriptoriumRole,
    ServiceSupervisor,
    _topic_path,
    partitioned_role_class,
    resolve_role_class,
    trace_wire_enabled,
)

# Downstream-stage topologies a ShardWorker can run next to each owned
# deli partition ("the farm's other lambdas, partitioned like deli"):
# "fused" collapses scriptorium+broadcaster into the fused
# durable+broadcast consumer (one deltas read per partition), "split"
# runs them separately (the only shape the ELASTIC fabric supports —
# two-leg predecessor absorption is fused-only machinery that doesn't
# exist; `ranged_role_class` rejects the fused base loudly).
DOWNSTREAM_MODES = {
    "fused": (ScriptoriumBroadcasterRole, ScribeRole),
    "split": (ScriptoriumRole, BroadcasterRole, ScribeRole),
}

__all__ = [
    "AutoscalePolicy",
    "DOWNSTREAM_MODES",
    "MergedDeltasReader",
    "ShardFabricSupervisor",
    "ShardRouter",
    "ShardWorker",
    "control_result",
    "partition_lease_name",
    "range_lease_name",
    "ranged_role_class",
    "raw_topic_name",
    "deltas_topic_name",
    "request_topology_change",
    "serve_shard_worker",
    "spread_doc_names",
    "stage_p99s",
]


def raw_topic_name(partition: int) -> str:
    return partition_suffix("rawdeltas", partition)


def deltas_topic_name(partition: int) -> str:
    return partition_suffix("deltas", partition)


def partition_lease_name(partition: int) -> str:
    """The lease key partition ownership is arbitrated under — the
    partitioned deli role's name (`partitioned_role_class`), so the
    lease, heartbeat, checkpoint and fence all share one identity."""
    return partition_suffix("deli", partition)


def range_lease_name(rid: str) -> str:
    """The elastic twin of `partition_lease_name`: range `rid`'s lease
    key, role name and checkpoint key are all ``deli-{rid}`` — one
    identity per range incarnation, like the static fabric's
    ``deli-p{k}``."""
    return f"deli-{rid}"


# ---------------------------------------------------------------------------
# ranged roles (the elastic fabric's per-range deli)
# ---------------------------------------------------------------------------


class _RangedMixin:
    """Hash-range identity + predecessor absorption for a supervised
    role (the deli, and since the front-door PR any single-out-topic
    downstream stage — scriptorium, broadcaster, scribe — consuming a
    per-range topic).

    A ranged role is a partitioned role whose slice of the document
    space is a hash range ``[lo, hi)`` instead of a modulo class, and
    whose range may have PREDECESSORS — the range(s) a live split or
    merge replaced. The exactly-once story rests on one invariant the
    sequencer already has: **per-document independence**. A document's
    outputs are a pure function of that document's input order, and a
    document's inputs live in exactly one topic at a time (its range's
    raw topic, moving predecessor → successor exactly once, when the
    router observes the new epoch). So the successor may absorb each
    predecessor's tail as a unit — restore the predecessor's final
    fenced checkpoint sliced to this range, bind its (strictly higher,
    fabric-scoped) fence on the predecessor's output topic so the
    deposed owner's in-flight batch is REJECTED, scan for the durable
    output prefix, silently replay it, and emit only the missing tail
    — without reconstructing the wall-clock interleaving across
    ranges, because no document's order ever spans two sources in a
    way the parent-first replay doesn't reproduce.

    Outputs produced from predecessor inputs carry ``inSrc`` (the
    predecessor's rid) next to ``inOff``: inOff values live in a
    per-source offset space, and the recovery scans partition by
    source so a successor crash mid-absorption replays exactly."""

    # Filled in by `ranged_role_class`.
    rid: str = ""
    range_lo: int = 0
    range_hi: int = 0
    pred_rids: tuple = ()
    topo_epoch: int = 0
    # The UNSUFFIXED topic names a predecessor's pair derives from
    # (``{pred_in_base}-{prid}`` → ``{pred_out_base}-{prid}``): the
    # deli reads rawdeltas→deltas, a ranged scriptorium deltas→durable,
    # a ranged scribe deltas→(nothing — pred_out_base None skips every
    # output-side step of the absorption).
    pred_in_base: str = "rawdeltas"
    pred_out_base: Optional[str] = "deltas"

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        # Fences must be comparable ACROSS lease keys (a successor
        # binds on predecessor topics), so ranged roles allocate from
        # the fabric-wide counter instead of the per-key default.
        self.leases = LeaseManager(
            self.leases.dir, self.owner, self.leases.ttl_s,
            self.leases.claim_ttl_s, fence_scope="__fabric__",
        )
        self._preds: Dict[str, dict] = {}
        self._hash_cache: Dict[str, int] = {}
        # Cursor-retirement grace: a predecessor continuously quiescent
        # this long is declared fully absorbed and its cursor dropped
        # from new checkpoints (see `_retire_pred`). Two lease TTLs
        # comfortably covers the only writer that could still land a
        # record there — a router whose topology read raced the commit
        # (routers stat-refresh the epoch record per append).
        self.pred_retire_s = max(1.0, 2.0 * self.leases.ttl_s)
        self._m_preds_retired = self.metrics.counter(
            "shard_pred_cursors_retired_total", **self._metric_labels()
        )
        for prid in self.pred_rids:
            self._add_pred(prid, None)

    # ----------------------------------------------------------- slicing

    def _add_pred(self, prid: str, off: Optional[int]) -> None:
        p = self._preds.get(prid)
        if p is not None:
            if off is not None and (p["off"] is None or off < p["off"]):
                p["off"] = off
            return
        self._preds[prid] = {
            "off": off,
            "raw": make_topic(
                _topic_path(self.shared_dir,
                            f"{self.pred_in_base}-{prid}"),
                self.log_format,
            ),
            "deltas": (
                make_topic(
                    _topic_path(self.shared_dir,
                                f"{self.pred_out_base}-{prid}"),
                    self.log_format,
                ) if self.pred_out_base else None
            ),
            "reader": None,
            # Retirement state: "done" preds are fully absorbed (their
            # cursor is dropped from new checkpoints, replaced by a
            # done_preds tombstone); quiet_since tracks continuous
            # quiescence toward that declaration.
            "done": False,
            "quiet_since": None,
        }

    def _in_range(self, doc_id: str) -> bool:
        h = self._hash_cache.get(doc_id)
        if h is None:
            h = self._hash_cache[doc_id] = doc_hash(doc_id)
        return self.range_lo <= h < self.range_hi

    def _mine(self, rec) -> bool:
        return (isinstance(rec, dict) and isinstance(rec.get("doc"), str)
                and self._in_range(rec["doc"]))

    # ------------------------------------------------------- state shape

    def snapshot_state(self):
        st = {
            "__ranged__": 1,
            "docs": super().snapshot_state(),
            "preds": {prid: p["off"] for prid, p in self._preds.items()
                      if p["off"] is not None and not p["done"]},
            "epoch": self.topo_epoch,
        }
        done = sorted(p for p, e in self._preds.items() if e["done"])
        if done:
            # Tombstones, not cursors: a restart must know these were
            # ABSORBED (skip re-absorption entirely), not merely never
            # seen (which would rescan the predecessor from offset 0).
            st["done_preds"] = done
        return st

    def restore_state(self, state):
        if isinstance(state, dict) and state.get("__ranged__"):
            for prid, off in (state.get("preds") or {}).items():
                self._add_pred(prid, int(off))
            for prid in state.get("done_preds") or ():
                self._add_pred(prid, None)
                self._preds[prid]["done"] = True
            super().restore_state(state.get("docs"))
        else:
            super().restore_state(state)

    # --------------------------------------------------------- recovery

    def _recover(self) -> None:
        if self._preds and self.ckpt.load(self.name) is None:
            # First acquisition of this range: seed a checkpoint-zero
            # from the predecessors' final fenced checkpoints BEFORE
            # normal recovery, so a crash mid-absorption restarts from
            # the exact same state (idempotent by construction).
            self._seed_from_preds()
        super()._recover()
        if self._preds:
            self.checkpoint()

    def _pred_ckpt_key(self, prid: str) -> str:
        """Predecessor `prid`'s checkpoint key for THIS role family
        (``{role_base}-{prid}`` — the deli's is `range_lease_name`)."""
        return f"{self.role_base}-{prid}"

    def _seed_from_preds(self) -> None:
        docs: Dict[str, Any] = {}
        cursors: Dict[str, int] = {}
        for prid in self.pred_rids:
            env = self.ckpt.load(self._pred_ckpt_key(prid))
            st = (env or {}).get("state") or {}
            cursors[prid] = int(st.get("offset", 0))
            inner = st.get("state")
            if isinstance(inner, dict) and inner.get("__ranged__"):
                # The predecessor was itself a successor: inherit its
                # unfinished predecessor cursors (min on conflict —
                # reprocessing below another branch's cursor is
                # silenced by resubmission dedup) so no ancestor tail
                # is ever orphaned, however stale a router gets.
                for gprid, goff in (inner.get("preds") or {}).items():
                    if gprid in self.pred_rids:
                        continue  # a direct pred's own offset wins
                    goff = int(goff)
                    cur = cursors.get(gprid)
                    cursors[gprid] = goff if cur is None else \
                        min(cur, goff)
                inner = inner.get("docs") or {}
            for d, s in (inner or {}).items():
                if self._in_range(d):
                    docs[d] = s
        for prid, off in cursors.items():
            self._add_pred(prid, off)
        self.ckpt.save(
            self.name,
            {"offset": 0, "state": {
                "__ranged__": 1, "docs": docs, "preds": cursors,
                "epoch": self.topo_epoch,
            }},
            fence=self.fence, owner=self.owner,
        )

    def _absorb_predecessors(self) -> None:
        """The `_Role._recover` hook: absorb every predecessor's tail
        (fence bind → durable-prefix scan → silent replay → missing
        tail emitted) BEFORE the own-topic gap replay — a doc's own-
        topic records always postdate its predecessor records, so
        parent-first is the per-document input order (ancestors
        before descendants for the same reason). Retired (done) preds
        are skipped outright — their tombstone in the checkpoint says
        every record they ever held is already absorbed.

        Our fence binds on EVERY live predecessor's output topic
        FIRST, before any scan or emission for ANY of them: in a
        merge→split chain a predecessor may itself be a still-LIVE
        successor (the merged range's role mid-drain of an older
        range) — scanning the old range's re-emissions and only later
        deposing the live consumer would let it land more claimable
        records between the scan and the bind, and this role would
        re-emit them too (a durable-leg duplicate). With every pred
        topic bound up front, every producer that could still emit a
        record this role will claim is demonstrably FencedError-
        deposed before the first scan. (Two sibling successors race
        their binds; the lower fence is rejected, exits, and retries
        under a fresh — higher — lease fence, the usual takeover
        dance.)"""
        preds = self._ordered_preds()
        for prid in preds:
            p = self._preds[prid]
            if p["deltas"] is not None:
                self._durable(lambda t=p["deltas"]: t.append_many(
                    [], fence=self.fence, owner=self.owner
                ))
        for prid in preds:
            self._absorb_pred(prid)

    def _pred_done_counts(self, prid: str, start: int) -> Dict[int, int]:
        """Durable outputs per `prid`-space input offset: the
        predecessor's own (untagged) outputs, plus `inSrc`-tagged
        re-emissions in THIS role's topic and every predecessor topic
        (a predecessor that was itself a successor may have died with
        tagged outputs beyond its checkpointed cursor)."""
        done: Dict[int, int] = {}

        def scan(topic, tagged: bool):
            entries, _ = topic.read_entries(0)
            for _i, r in entries:
                if not isinstance(r, dict) or r.get("inOff", -1) < start:
                    continue
                if tagged:
                    if r.get("inSrc") != prid:
                        continue
                elif r.get("inSrc") is not None:
                    continue
                if not self._mine(r):
                    # ALWAYS slice by range, tagged or not: a
                    # predecessor that was itself a successor holds
                    # tagged records for docs OUTSIDE this child's
                    # range (its range was wider), and counting them
                    # would inflate max_done past this range's true
                    # durable prefix — a clipped record of ours would
                    # then never be re-emitted.
                    continue
                done[r["inOff"]] = done.get(r["inOff"], 0) + 1

        scan(self._preds[prid]["deltas"], tagged=False)
        scan(self.out_topic, tagged=True)
        for orid, op in self._preds.items():
            if orid != prid and op["deltas"] is not None:
                scan(op["deltas"], tagged=True)
        return done

    def _absorb_pred(self, prid: str) -> None:
        p = self._preds[prid]
        if p["off"] is None:
            p["off"] = 0  # predecessor died before its first checkpoint
        if p["deltas"] is None:
            # Output-less role (scribe): state+offset commit atomically
            # in the checkpoint, so absorption is just a silent fold of
            # the pred tail — nothing to fence-bind or re-emit.
            gap, next_off = p["raw"].read_entries(p["off"])
            sink: List[dict] = []
            for i, rec in gap:
                if self._mine(rec):
                    self.process(i, rec, sink)
            self.flush_batch(sink)
            p["off"] = next_off
            p["reader"] = None
            return
        # Our fence is already bound on this (and every) pred topic by
        # `_absorb_predecessors`' pre-pass, so the deposed owner's
        # in-flight batch is rejected and the scan below sees the
        # final durable prefix.
        done = self._pred_done_counts(prid, p["off"])
        gap, next_off = p["raw"].read_entries(p["off"])
        mine = [(i, rec) for i, rec in gap if self._mine(rec)]
        out: List[dict] = []
        live = mine
        if done:
            max_done = max(done)
            sink: List[dict] = []
            for i, rec in mine:
                if i <= max_done:
                    self.process(i, rec, sink)  # silent: already durable
            self.flush_batch(sink)
            # Only the LAST durable input can have been clipped
            # mid-append; re-emit exactly its missing suffix.
            tail = [r for r in sink if r.get("inOff") == max_done]
            out.extend(tail[done.get(max_done, 0):])
            live = [(i, rec) for i, rec in mine if i > max_done]
        sink2: List[dict] = []
        for i, rec in live:
            self.process(i, rec, sink2)
        self.flush_batch(sink2)
        out.extend(sink2)
        for r in out:
            r["inSrc"] = prid
        if out:
            self._durable(lambda: self.out_topic.append_many(
                out, fence=self.fence, owner=self.owner
            ))
        p["off"] = next_off
        p["reader"] = None

    # ------------------------------------------------------ steady state

    def step(self, idle_sleep: float = 0.01) -> int:
        """One quantum with the happens-before the range chain needs:
        per document, predecessor-topic records strictly precede
        own-topic records (the router moves a doc exactly once per
        epoch), so the OWN batch is read first but processed LAST —
        buffered while every predecessor tail is drained to
        quiescence. Any pred record of a doc in the buffered batch was
        appended before the doc's own record, hence before the drain
        started, hence is consumed by it; processing pred-then-buffer
        therefore reproduces every doc's true input order no matter
        how the wall clock interleaved the topics."""
        if self.fence is None or not self._ordered_preds():
            # No predecessors left to watch (none ever, or all retired
            # as fully absorbed): the classic single-topic quantum —
            # retirement also removes the per-step pred tail polls.
            return super().step(idle_sleep)
        self._renew_or_die()
        if self._reader is None or self._reader.next_line != self.offset:
            self._reader = make_tail_reader(self.in_topic, self.offset)
        # 1. READ (don't process) one own-topic batch. The batch-start
        # byte anchor (`_Role._in_pos`) is captured HERE and restored
        # after the pred drains below clobber it to None.
        in_pos0 = getattr(self._reader, "_pos", None)
        if self.ingest_batches and hasattr(self._reader, "poll_batches"):
            units = self._reader.poll_batches(self.batch)
        else:
            units = [("rec", i, rec)
                     for i, rec in self._reader.poll(self.batch)]
        # 2. Drain every predecessor past the read point.
        pred_moved = self._pump_preds()
        # 3. Process the buffered own batch.
        self._in_pos = in_pos0
        out: List[dict] = []
        moved = 0
        for unit in units:
            if unit[0] == "batch":
                moved += unit[2].n
                self.process_batch(unit[1], unit[2], out)
            else:
                moved += 1
                self.process(unit[1], unit[2], out)
        next_off = self._reader.next_line
        if not moved:
            if next_off != self.offset:
                self.offset = next_off
                self._ckpt_dirty = True
            try:
                self.maybe_checkpoint()
            except FencedError as exc:
                self._fenced_exit(exc)
            self.heartbeat()
            if not pred_moved:
                self._idle_wait(idle_sleep)
            return pred_moved
        self.flush_batch(out)
        try:
            if self.out_topic is not None:
                self._ckpt_pending_bytes += self._durable(
                    lambda: self.out_topic.append_many(
                        out, fence=self.fence, owner=self.owner
                    )
                )
            self.offset = next_off
            self._ckpt_dirty = True
            self.maybe_checkpoint()
        except FencedError as exc:
            self._fenced_exit(exc)
        self._m_pump.observe(moved)
        self._m_records.inc(moved)
        self.heartbeat()
        return moved + pred_moved

    def _fenced_exit(self, exc: FencedError) -> None:
        self._m_fenced.inc()
        self.heartbeat(force=True)
        print(f"FENCED {self.name} {self.owner}: {exc}", flush=True)
        raise SystemExit(EXIT_FENCED)

    def _ordered_preds(self) -> List[str]:
        """LIVE (non-retired) predecessors oldest-first (birth epoch
        off the rid tag): in a chain — grandparent inherited from a
        split-of-a-split — the older range's records precede the
        newer's per doc, so drains run ancestors before descendants."""
        def birth(rid: str) -> int:
            head, sep, tail = rid.rpartition("-e")
            return int(tail) if sep and tail.isdigit() else 1

        return sorted(
            (p for p, e in self._preds.items() if not e["done"]),
            key=birth,
        )

    def _retire_pred(self, prid: str) -> None:
        """Declare `prid` fully absorbed and drop its cursor from new
        checkpoints (ROADMAP item-2 follow-up). Two facts make this
        safe: (1) the topology history marks every pred DEAD by
        construction — this role only exists because the epoch commit
        replaced them, and a committed range never returns (a merge
        recreating its bounds is a fresh incarnation with a fresh
        rid); (2) the pred's raw topic has been continuously quiescent
        for `pred_retire_s` (two lease TTLs past the last record),
        which outlasts the only straggler writer possible — a router
        whose per-append topology stat raced the commit. From here the
        checkpoint carries a tombstone instead of a cursor, restarts
        skip re-absorption, and the steady-state pump stops polling
        the dead tail."""
        p = self._preds[prid]
        p["done"] = True
        p["reader"] = None
        self._ckpt_dirty = True
        self._m_preds_retired.inc()

    def _pump_preds(self) -> int:
        """Drain every predecessor tail to QUIESCENCE: full passes
        over the preds (oldest epoch first) until one pass delivers
        nothing — every pred record appended before that pass began is
        then consumed, which is what the buffered-own-batch ordering
        rests on. Lease renewal stays live inside the loop (a huge
        absorb must not let the lease lapse)."""
        total = 0
        while True:
            pass_moved = 0
            for prid in self._ordered_preds():
                pass_moved += self._pump_one_pred(prid)
            total += pass_moved
            if pass_moved == 0:
                return total

    def _pump_one_pred(self, prid: str) -> int:
        p = self._preds[prid]
        if p["done"] or p["off"] is None:
            return 0  # retired / absorbed at recovery before any pump
        taken = 0
        while True:
            reader = p["reader"]
            if reader is None or reader.next_line != p["off"]:
                reader = p["reader"] = make_tail_reader(
                    p["raw"], p["off"]
                )
            entries = reader.poll(self.batch)
            if not entries:
                if reader.next_line != p["off"]:
                    p["off"] = reader.next_line
                    self._ckpt_dirty = True
                    p["quiet_since"] = None  # junk lines still count
                elif taken == 0:
                    # A fully quiet pass: start (or continue) the
                    # retirement clock; past the grace, the cursor is
                    # dropped from future checkpoints.
                    now = time.time()
                    if p["quiet_since"] is None:
                        p["quiet_since"] = now
                    elif now - p["quiet_since"] >= self.pred_retire_s:
                        self._retire_pred(prid)
                else:
                    p["quiet_since"] = None
                return taken
            p["quiet_since"] = None
            # Pred records have no own-topic byte anchor: a manifest
            # emitted from this drain carries byteOff None (readers
            # fall back to the unbounded backward scan).
            self._in_pos = None
            out: List[Any] = []
            src_emit = isinstance(self.out_topic, ColumnarFileTopic)
            if src_emit:
                # Columnar out topic: the frame-level FLAG_SRC stamp
                # (`append_many(src=prid)`) carries the inSrc tag, so
                # a columnar-emitting role (the kernel deli) keeps its
                # `encode_columns` fast path through a pred drain —
                # elastic splits no longer force the `_dict_emit`
                # fallback (ROADMAP item-1 follow-up b). Dict-path
                # strays in the same flush pick the tag up at decode
                # identically.
                for i, rec in entries:
                    if self._mine(rec):
                        self.process(i, rec, out)
                self.flush_batch(out)
            else:
                # JSON out topic: per-record dict tagging (there is no
                # frame to carry the tag).
                self._dict_emit = True
                try:
                    for i, rec in entries:
                        if self._mine(rec):
                            self.process(i, rec, out)
                    self.flush_batch(out)
                finally:
                    self._dict_emit = False
                for r in out:
                    r["inSrc"] = prid
            try:
                if out and self.out_topic is not None:
                    if src_emit:
                        self._ckpt_pending_bytes += self._durable(
                            lambda: self.out_topic.append_many(
                                out, fence=self.fence,
                                owner=self.owner, src=prid,
                            )
                        )
                    else:
                        self._ckpt_pending_bytes += self._durable(
                            lambda: self.out_topic.append_many(
                                out, fence=self.fence, owner=self.owner
                            )
                        )
                p["off"] = reader.next_line
                self._ckpt_dirty = True
                self.maybe_checkpoint()
            except FencedError as exc:
                self._fenced_exit(exc)
            taken += len(entries)
            self._renew_or_die()
            self.heartbeat()


def ranged_role_class(base: type, entry: dict, epoch: int) -> type:
    """The elastic form of `partitioned_role_class`: same role code,
    hash-range identity. Lease key, heartbeat file, checkpoint key and
    topic pair all derive from the base role's names + the range id
    (the deli's ``deli-{rid}`` over ``rawdeltas-{rid}`` →
    ``deltas-{rid}``; a ranged scriptorium's ``scriptorium-{rid}``
    over ``deltas-{rid}`` → ``durable-{rid}``), the role only touches
    documents hashing into ``[lo, hi)``, and the entry's `preds` name
    the range(s) it absorbs (split parent / merge parents)."""
    if getattr(base, "bc_topic_name", None):
        raise ValueError(
            f"{base.__name__} has a second output leg "
            f"({base.bc_topic_name!r}): two-leg predecessor absorption "
            f"is not implemented — run the split "
            f"scriptorium+broadcaster pair on the elastic fabric"
        )
    rid = entry["rid"]
    return type(
        f"{base.__name__}Range", (_RangedMixin, base), {
            "name": f"{base.name}-{rid}",
            "in_topic_name": f"{base.in_topic_name}-{rid}",
            "out_topic_name": (f"{base.out_topic_name}-{rid}"
                               if base.out_topic_name else None),
            "partition": rid,  # metric label: {role: base, partition: rid}
            "role_base": base.name,
            "rid": rid,
            "range_lo": int(entry["lo"]),
            "range_hi": int(entry["hi"]),
            "pred_rids": tuple(entry.get("preds") or ()),
            "topo_epoch": int(epoch),
            "pred_in_base": base.in_topic_name,
            "pred_out_base": base.out_topic_name,
        },
    )


# ---------------------------------------------------------------------------
# topology-change control channel
# ---------------------------------------------------------------------------


def _control_dir(shared_dir: str) -> str:
    return os.path.join(shared_dir, "control")


def request_topology_change(shared_dir: str, cmd: dict) -> str:
    """Stage a split/merge command for the worker that owns the target
    range (the fabric's admin channel — the supervisor's
    `request_split`/`request_merge` and the chaos harness both write
    here). Returns the command id; `control_result` reports completion
    (the executing worker writes a ``.done`` marker with the new
    epoch)."""
    d = _control_dir(shared_dir)
    os.makedirs(d, exist_ok=True)
    cid = f"cmd-{time.time_ns():020d}-{os.getpid()}"
    tmp = os.path.join(d, f".{cid}.tmp")
    with open(tmp, "w") as f:
        json.dump(cmd, f)
    os.replace(tmp, os.path.join(d, f"{cid}.json"))
    return cid


def control_result(shared_dir: str, cmd_id: str) -> Optional[dict]:
    try:
        with open(os.path.join(_control_dir(shared_dir),
                               f"{cmd_id}.done.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def spread_doc_names(n_docs: int, n_partitions: int,
                     prefix: str = "doc") -> List[str]:
    """`n_docs` deterministic doc names that cover the partitions as
    evenly as the hash allows (scan names, round-robin the partition
    quota — the workload builders' answer to small-N hash clumping;
    real traffic gets the same balance from volume)."""
    from .queue import partition_of

    if n_partitions <= 1:
        return [f"{prefix}{i}" for i in range(n_docs)]
    per = {p: 0 for p in range(n_partitions)}
    quota = math.ceil(n_docs / n_partitions)
    out: List[str] = []
    i = 0
    while len(out) < n_docs and i < 10_000 * max(1, n_docs):
        name = f"{prefix}{i}"
        i += 1
        p = partition_of(name, n_partitions)
        if per[p] < quota:
            per[p] += 1
            out.append(name)
    return out


# ---------------------------------------------------------------------------
# ingress router
# ---------------------------------------------------------------------------


class ShardRouter:
    """The fabric's ingress edge: appends each raw record to its doc's
    partition topic (the document-router role). Boxcar-aware — a wire
    boxcar names one doc and rides whole, so its atomicity survives
    routing. Appends are grouped per partition per call (one fenced
    frame/lock per partition, not per record), and arrival order is
    preserved WITHIN each partition — the only order the per-document
    sequencing contract needs, since a doc lives in exactly one
    partition.

    `elastic=True` routes by ``(epoch, hash(doc))`` instead of
    ``doc % N``: the live hash-range topology (`queue.RangeLeaseStore`)
    is re-read whenever its record changes on disk, so a split/merge
    redirects NEW records to the child ranges within one append — and
    any record a momentarily-stale router still lands on a retired
    range's topic is absorbed by the successor's predecessor tail
    (`_RangedMixin`), so staleness costs latency, never order."""

    def __init__(self, shared_dir: str, n_partitions: int,
                 log_format: Optional[str] = None,
                 elastic: bool = False):
        if n_partitions < 1:
            raise ValueError(f"n_partitions must be >= 1: {n_partitions}")
        self.shared_dir = shared_dir
        self.n_partitions = n_partitions
        self.log_format = default_log_format(log_format)
        self.elastic = bool(elastic)
        if self.elastic:
            self.store = RangeLeaseStore(shared_dir, "__router__")
            self.topology = self.store.ensure_topology(n_partitions)
            self._topo_sig: Optional[tuple] = None
            self._topic_cache: Dict[str, Any] = {}
            self.topics: List[Any] = []  # static-mode surface only
        else:
            self.topics = [
                make_topic(_topic_path(shared_dir, raw_topic_name(p)),
                           self.log_format)
                for p in range(n_partitions)
            ]

    # ------------------------------------------------- topology refresh

    def _refresh(self) -> None:
        """Adopt a newer topology epoch if the record changed on disk
        (one stat per call — the epoch flip is visible within one
        append, no polling thread)."""
        if not self.elastic:
            return
        try:
            st = os.stat(self.store.topology_path)
        except OSError:
            return
        sig = (st.st_mtime_ns, st.st_size)
        if sig != self._topo_sig:
            topo = self.store.read_topology()
            if topo is not None:
                self.topology = topo
            self._topo_sig = sig

    def _topic(self, name: str):
        t = self._topic_cache.get(name)
        if t is None:
            t = self._topic_cache[name] = make_topic(
                _topic_path(self.shared_dir, name), self.log_format
            )
        return t

    # ----------------------------------------------------------- routing

    def partition(self, rec: Any) -> int:
        return record_partition(rec, self.n_partitions)

    def split(self, records: List[Any]) -> Dict[int, List[Any]]:
        """Records grouped by partition, input order preserved within
        each group (pure routing — no I/O)."""
        return split_by_partition(records, self.n_partitions)

    def split_elastic(self, records: List[Any]) -> Dict[str, List[Any]]:
        """Records grouped by live range id under the CURRENT epoch
        (doc-less junk pins to the first range — any single consistent
        home keeps offsets deterministic)."""
        out: Dict[str, List[Any]] = {}
        for rec in records:
            doc = rec.get("doc") if isinstance(rec, dict) else None
            if isinstance(doc, str):
                entry = range_for_doc(self.topology, doc)
            else:
                entry = self.topology["ranges"][0]
            out.setdefault(entry["rid"], []).append(rec)
        return out

    def append(self, records: List[Any],
               fence: Optional[int] = None,
               owner: Optional[str] = None) -> Dict[Any, int]:
        """Route + append one ingress batch; returns records appended
        per partition (keyed by index, or by range id when elastic).
        `fence`/`owner` gate every leg's append (the supervised
        ingress role routes under its own fence, so a deposed front
        door's in-flight batch is rejected on the topic).

        Elastic appends are epoch-rechecked AFTER landing: if the
        topology moved while this batch was in flight (a router stalled
        between its refresh and its appends can outlive even the
        pred-cursor retirement grace — the one hole pure tail-draining
        can't cover), the batch is re-routed under the new epoch. The
        duplicate delivery is safe by construction: if a successor
        still drains the old topic, resubmission dedup silences the
        second copy (per-client clientSeq); if the old range's cursor
        was already retired, the first copy is simply never read and
        the re-route is the only live one. Bounded: one re-route per
        epoch change observed, and epochs only advance."""
        counts: Dict[Any, int] = {}
        if self.elastic:
            self._refresh()
            for _ in range(64):  # paranoia bound; epochs move rarely
                epoch = self.topology["epoch"]
                by_rid = self.split_elastic(records)
                rid_to_raw = {e["rid"]: e["raw"]
                              for e in self.topology["ranges"]}
                counts = {}
                for rid, recs in by_rid.items():
                    self._topic(rid_to_raw[rid]).append_many(
                        recs, fence=fence, owner=owner
                    )
                    counts[rid] = len(recs)
                self._refresh()
                if self.topology["epoch"] == epoch:
                    return counts
            return counts
        for p, recs in self.split(records).items():
            self.topics[p].append_many(recs, fence=fence, owner=owner)
            counts[p] = len(recs)
        return counts

    # ------------------------------------------------------ read surface

    def stage_topic_names(self, base: str = "deltas") -> List[str]:
        """Every topic name stage `base` has EVER written across this
        fabric — live ranges plus retired ones (topology history), so
        records written under epoch E stay readable after E+1. The
        per-partition downstream stages share the naming rule
        (``durable-p{k}`` / ``broadcast-{rid}`` ...), so one helper
        serves every stage's merged read surface."""
        if self.elastic:
            self._refresh()
            return [f"{base}-{rid}"
                    for rid in self.topology.get("history", [])]
        return [partition_suffix(base, p)
                for p in range(self.n_partitions)]

    def deltas_topic_names(self) -> List[str]:
        return self.stage_topic_names("deltas")

    def deltas_topics(self) -> List[Any]:
        """Every partition's sequenced-output topic (the merged read
        surface convergence checks and catch-up readers use)."""
        if self.elastic:
            return [self._topic(n) for n in self.deltas_topic_names()]
        return [
            make_topic(_topic_path(self.shared_dir, deltas_topic_name(p)),
                       self.log_format)
            for p in range(self.n_partitions)
        ]

    def live_raw_topics(self) -> List[Any]:
        """The LIVE ranges' ingress topics (fault-injection surface)."""
        if self.elastic:
            self._refresh()
            return [self._topic(e["raw"])
                    for e in self.topology["ranges"]]
        return list(self.topics)

    def merged_reader(self, base: str = "deltas") -> "MergedDeltasReader":
        return MergedDeltasReader(self, base=base)


class MergedDeltasReader:
    """The merged catch-up read: one cursor PER RANGE TOPIC across the
    whole topology history, polled incrementally. A split or merge
    adds cursors (new ranges) without invalidating old ones, so a
    consumer riding this surface sees every record exactly once no
    matter how often N changes mid-stream — re-reading every file from
    zero per poll would be O(file²) at bench scale. Retired ranges'
    topics quiesce once their successor binds, so each costs one
    empty incremental poll per pass; history grows only by
    operator-initiated epochs, which bounds the per-poll fan-out.

    `base` picks the stage surface: "deltas" (default) reads the
    sequenced stream; "durable"/"broadcast" read the per-partition
    downstream legs the same elastic way (the catch-up surface a
    split hands a range's downstream legs over on)."""

    def __init__(self, router: ShardRouter, base: str = "deltas"):
        self.router = router
        self.base = base
        self._readers: Dict[str, Any] = {}

    def poll(self, max_count_per_range: Optional[int] = None
             ) -> List[Any]:
        out: List[Any] = []
        for name in self.router.stage_topic_names(self.base):
            reader = self._readers.get(name)
            if reader is None:
                reader = self._readers[name] = make_tail_reader(
                    self.router._topic(name) if self.router.elastic
                    else make_topic(
                        _topic_path(self.router.shared_dir, name),
                        self.router.log_format,
                    ),
                    0,
                )
            out.extend(v for _i, v in reader.poll(max_count_per_range))
        return out


# ---------------------------------------------------------------------------
# the worker
# ---------------------------------------------------------------------------


class ShardWorker:
    """One fabric node: sweeps partition leases toward its fair share
    and pumps a supervised deli role per owned partition.

    Balance is emergent, not orchestrated: each worker computes
    ``target = ceil(n_partitions / alive_workers)`` from the worker
    heartbeat directory and (a) gracefully RELEASES surplus partitions
    — final fenced checkpoint, then lease release with expires=0 so the
    successor takes over without waiting out the TTL — and (b) acquires
    free/expired partitions up to target. Ownership changes always run
    through the fence: the successor's recovery (`_Role._recover`)
    binds its higher fence on the output topic FIRST, so anything the
    deposed owner still has in flight is rejected, then restores the
    fenced checkpoint and closes the append-vs-checkpoint window with
    the exactly-once ``inOff`` scan."""

    def __init__(self, shared_dir: str, slot: str,
                 owner: Optional[str] = None, n_partitions: int = 4,
                 deli_impl: Optional[str] = None,
                 log_format: Optional[str] = None, ttl_s: float = 1.0,
                 batch: int = 512, max_partitions: Optional[int] = None,
                 ckpt_interval_s: float = 0.25,
                 ckpt_bytes: int = 256 * 1024, ckpt_duty: float = 0.2,
                 worker_ttl_s: Optional[float] = None,
                 deli_devices: Optional[int] = None,
                 elastic: bool = False, summarize: bool = False,
                 summary_ops: Optional[int] = None,
                 downstream: Optional[str] = None,
                 device_plane: Optional[str] = None,
                 plane_column: Optional[int] = None):
        """`elastic=True` swaps fixed modulo-N partitions for the
        hash-range topology (`queue.RangeLeaseStore`): the worker
        sweeps RANGE leases toward its fair share of the LIVE range
        set (which changes epoch to epoch), executes staged
        split/merge commands for ranges it owns, and releases any
        role whose range a committed topology change retired.
        `n_partitions` then only seeds the bootstrap topology.

        `summarize=True` runs a per-partition summary service next to
        each owned deli (`summarizer.SummarizerRole` under
        `partitioned_role_class`: ``deltas-p{k}`` → ``summaries-p{k}``
        + content-addressed blobs in the shared store), following deli
        ownership for locality but fenced under its own
        ``summarizer-p{k}`` lease. On the ELASTIC fabric the
        summarizer is ranged like the deli (`ranged_role_class` over
        the same topology entry, ``deltas-{rid}`` →
        ``summaries-{rid}``): its per-doc fold state is a flat map, so
        a split/merge successor ABSORBS its predecessors' fold dicts
        sliced to its hash range through the generic predecessor
        machinery — summaries ride every topology
        (`SummaryIndex(topics=router.stage_topic_names("summaries"))`
        is the merged manifest read surface).

        `downstream` ("fused" | "split") promotes the farm's OTHER
        lambdas to per-partition supervised consumers riding deli
        ownership: each owned partition gets its own
        ``deltas-p{k}``-consuming scriptorium+broadcaster (fused or
        split) and scribe under their own fenced leases — the
        routerlicious every-stage-partitioned topology. On the
        ELASTIC fabric the stages are ranged like the deli
        (`ranged_role_class` over the same topology entry): a split
        hands each range's durable/broadcast legs to the successors
        through the same predecessor-absorption machinery,
        exactly-once. Elastic + "fused" is a loud config error (the
        fused role's two output legs have no two-leg absorption)."""
        self.summarize = bool(summarize)
        self.summary_ops = summary_ops
        if downstream is not None and downstream not in DOWNSTREAM_MODES:
            raise ValueError(
                f"downstream {downstream!r} not in "
                f"{sorted(DOWNSTREAM_MODES)}"
            )
        if downstream == "fused" and elastic:
            raise ValueError(
                "downstream='fused' is static-partition only: the "
                "fused consumer's two output legs have no two-leg "
                "predecessor absorption — use downstream='split' on "
                "the elastic fabric"
            )
        self.downstream = downstream
        self.shared_dir = shared_dir
        self.slot = slot
        self.owner = owner or slot
        self.n_partitions = int(n_partitions)
        self.elastic = bool(elastic)
        self.deli_impl = deli_impl or os.environ.get("FLUID_DELI", "scalar")
        if self.deli_impl not in DELI_IMPLS:
            raise ValueError(
                f"deli_impl {self.deli_impl!r} not in {DELI_IMPLS}"
            )
        # Multi-device deli per partition: every owned partition's
        # kernel role shards its pool over the same process-wide
        # N-device mesh — "one partition = one worker process" and
        # "one doc slab = one device" compose, they don't compete.
        self.deli_devices = (
            int(deli_devices) if deli_devices is not None else None
        )
        if self.deli_devices is not None and self.deli_devices > 1 \
                and self.deli_impl != "kernel":
            raise ValueError(
                f"deli_devices={self.deli_devices} needs "
                f"deli_impl='kernel'; got {self.deli_impl!r}"
            )
        # 2-D device plane: one partition = one worker = one mesh
        # slice — this worker's delis order documents on model column
        # `plane_column` (default: a stable hash of the worker slot)
        # while its summarizers' folds span the whole plane.
        self.device_plane = device_plane
        if device_plane is not None:
            if self.deli_impl != "kernel":
                raise ValueError(
                    f"device_plane={device_plane!r} needs "
                    f"deli_impl='kernel'; got {self.deli_impl!r}"
                )
            if self.deli_devices is not None and self.deli_devices > 1:
                raise ValueError(
                    "deli_devices and device_plane are exclusive on "
                    "a worker: the plane's docs axis IS the deli's "
                    "device slice"
                )
        self.plane_column = plane_column
        self.log_format = default_log_format(log_format)
        self.ttl_s = ttl_s
        self.batch = batch
        self.max_partitions = max_partitions
        self.ckpt_interval_s = ckpt_interval_s
        self.ckpt_bytes = ckpt_bytes
        self.ckpt_duty = ckpt_duty
        # A worker is presumed dead once its heartbeat is older than
        # this (decoupled from the per-partition lease TTL: membership
        # flaps should be rarer than lease renewals).
        self.worker_ttl_s = worker_ttl_s or 3.0 * ttl_s
        self.workers_dir = os.path.join(shared_dir, "workers")
        self.leases_dir = os.path.join(shared_dir, "leases")
        os.makedirs(self.workers_dir, exist_ok=True)
        # Read-only ownership probe (owner_of takes no claim).
        self._probe = LeaseManager(self.leases_dir, self.owner, ttl_s)
        if self.elastic:
            self.store: Optional[RangeLeaseStore] = RangeLeaseStore(
                shared_dir, self.owner, ttl_s
            )
            self.topology: Optional[dict] = self.store.ensure_topology(
                self.n_partitions
            )
        else:
            self.store = None
            self.topology = None
        # Role keys: partition ints (static) or range ids (elastic).
        self.roles: Dict[Any, Any] = {}
        # Per-partition summary services (summarize=True): mirror deli
        # ownership, own fenced lease per partition.
        self.summ_roles: Dict[Any, Any] = {}
        # Per-partition downstream stages (downstream=): key -> list of
        # role instances, mirroring deli ownership like summarizers.
        self.down_roles: Dict[Any, List[Any]] = {}
        self.events: List[str] = []
        self._hb_t = 0.0
        self._sweep_t = 0.0
        from ..utils.metrics import get_registry

        self.metrics = get_registry()
        self._m_owned = self.metrics.gauge(
            "shard_partitions_owned", worker=self.slot
        )
        self._m_handoffs = self.metrics.counter(
            "shard_partition_releases_total", worker=self.slot
        )
        self._m_drops = self.metrics.counter(
            "shard_partition_deposed_total", worker=self.slot
        )

    # -------------------------------------------------------- membership

    def _event(self, text: str) -> None:
        self.events.append(text)

    def _hb_path(self) -> str:
        return os.path.join(self.workers_dir, f"{self.slot}.json")

    def heartbeat(self) -> None:
        """Worker-level liveness + the fabric's metrics channel: ONE
        snapshot of this process's registry (per-partition labels keep
        every owned partition's series distinct), so the supervisor
        scrape merges one file per worker with no double counting.
        `degraded` lists partitions currently inside a storage-fault
        retry budget (ENOSPC/stall backoff) — limping, not dead — for
        `ShardFabricSupervisor.health()` to surface. In wire-trace
        mode the worker's slow-op flight-recorder buffer rides along
        too (the per-partition broadcaster stages — fused or split —
        run in THIS process and feed the process recorder, each span
        tagged with its partition), so `/traces` is populated on the
        elastic fabric exactly like the classic farm."""
        tmp = self._hb_path() + f".tmp.{os.getpid()}"
        hb = {
            "t": time.time(), "slot": self.slot, "owner": self.owner,
            "pid": os.getpid(),
            "partitions": sorted(
                p for p, r in self.roles.items()
                if r.fence is not None
            ),
            "degraded": sorted(
                p for p, r in self.roles.items()
                if getattr(r, "degraded", False)
            ),
            "epoch": (self.topology or {}).get("epoch"),
            "metrics": self.metrics.snapshot(),
        }
        if trace_wire_enabled():
            from ..utils.metrics import get_flight_recorder

            spans = get_flight_recorder().snapshot()
            if spans:
                hb["slow_ops"] = spans
        with open(tmp, "w") as f:
            json.dump(hb, f)
        os.replace(tmp, self._hb_path())
        self._hb_t = time.time()

    def alive_workers(self, now: Optional[float] = None) -> int:
        """Workers with a fresh heartbeat (self always counts)."""
        now = time.time() if now is None else now
        alive = 0
        saw_self = False
        try:
            names = os.listdir(self.workers_dir)
        except OSError:
            names = []
        for fn in names:
            if not fn.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.workers_dir, fn)) as f:
                    hb = json.load(f)
            except (OSError, ValueError):
                continue
            if now - float(hb.get("t", 0)) <= self.worker_ttl_s:
                alive += 1
                if fn == f"{self.slot}.json":
                    saw_self = True
        return alive if saw_self else alive + 1

    def _keys(self) -> List[Any]:
        """The current partition key space: fixed indices, or the live
        range ids of the topology epoch this worker last read."""
        if not self.elastic:
            return list(range(self.n_partitions))
        return [e["rid"] for e in self.topology["ranges"]]

    def _lease_name(self, key: Any) -> str:
        return (range_lease_name(key) if self.elastic
                else partition_lease_name(key))

    def _entry(self, rid: str) -> dict:
        return next(e for e in self.topology["ranges"]
                    if e["rid"] == rid)

    def target_partitions(self) -> int:
        """This worker's fair share of the partition space (the LIVE
        range count when elastic — a split raises everyone's target,
        a merge lowers it: capacity follows the topology)."""
        t = math.ceil(len(self._keys()) / max(1, self.alive_workers()))
        if self.max_partitions is not None:
            t = min(t, self.max_partitions)
        return t

    # ------------------------------------------------------- role plumbing

    def _make_role(self, key: Any):
        base = resolve_role_class("deli", self.deli_impl)
        if self.elastic:
            cls = ranged_role_class(
                base, self._entry(key), self.topology["epoch"]
            )
        else:
            cls = partitioned_role_class(base, key)
        kw = {}
        if self.deli_devices is not None and self.deli_devices > 1:
            kw["deli_devices"] = self.deli_devices
        if self.device_plane is not None:
            # One worker = one mesh slice: every deli this worker
            # runs orders on the SAME model column of the plane
            # (explicit column, or a stable hash of the worker slot).
            from ..parallel.device_plane import plane_column_of, \
                resolve_plane

            kw["device_plane"] = self.device_plane
            kw["plane_column"] = (
                self.plane_column if self.plane_column is not None
                else plane_column_of(
                    self.slot,
                    resolve_plane(self.device_plane).model,
                )
            )
        role = cls(
            self.shared_dir, self.owner, ttl_s=self.ttl_s,
            batch=self.batch, ckpt_interval_s=self.ckpt_interval_s,
            ckpt_bytes=self.ckpt_bytes, log_format=self.log_format,
            ckpt_duty=self.ckpt_duty, **kw,
        )
        # The WORKER heartbeat (whole-registry snapshot, throttled) is
        # the fabric's liveness/metrics channel; per-partition role
        # heartbeats are debugging surface only, so throttle their
        # per-step registry-snapshot writes to the same cadence.
        role.hb_interval_s = self.ttl_s / 3
        return role

    def _make_summ_role(self, key: Any):
        from .summarizer import SummarizerRole

        if self.elastic:
            # Ranged summarizer: same topology entry as the deli, so a
            # split/merge successor seeds from the predecessors' final
            # fold checkpoints sliced to its range and re-emits only
            # the clipped manifest tail (the `_RangedMixin` contract;
            # the old "static-partition only" ValueError is gone).
            cls = ranged_role_class(
                SummarizerRole, self._entry(key), self.topology["epoch"]
            )
        else:
            cls = partitioned_role_class(SummarizerRole, key)
        kw = {}
        if self.summary_ops is not None:
            kw["summary_ops"] = self.summary_ops
        if self.device_plane is not None:
            # Summarizer folds span the WHOLE plane (the sequencers
            # tile it column-wise) — both tenants, one chip pool.
            kw["device_plane"] = self.device_plane
        role = cls(
            self.shared_dir, self.owner, ttl_s=self.ttl_s,
            batch=self.batch, ckpt_interval_s=self.ckpt_interval_s,
            ckpt_bytes=self.ckpt_bytes, log_format=self.log_format,
            ckpt_duty=self.ckpt_duty, **kw,
        )
        role.hb_interval_s = self.ttl_s / 3
        return role

    def _sweep_summarizers(self) -> None:
        """Summarizers follow deli ownership (the partition's deltas
        live here anyway); their own lease/fence keeps a deposed
        worker's late manifest append rejected like any other role."""
        for k in list(self.summ_roles):
            if k not in self.roles:
                self._release_summ(k, "deli released")
        for k in self.roles:
            if k not in self.summ_roles:
                self.summ_roles[k] = self._make_summ_role(k)

    def _release_summ(self, key: Any, why: str) -> None:
        role = self.summ_roles.pop(key, None)
        if role is None:
            return
        role.close_doorbell()
        if role.fence is not None:
            try:
                role.checkpoint()
            except (FencedError, OSError):
                pass
            role.leases.release(role.name)
        self._event(f"released summarizer {self._kname(key)} ({why})")

    def _make_down_roles(self, key: Any) -> List[Any]:
        roles = []
        for base in DOWNSTREAM_MODES[self.downstream]:
            if self.elastic:
                cls = ranged_role_class(
                    base, self._entry(key), self.topology["epoch"]
                )
            else:
                cls = partitioned_role_class(base, key)
            role = cls(
                self.shared_dir, self.owner, ttl_s=self.ttl_s,
                batch=self.batch, ckpt_interval_s=self.ckpt_interval_s,
                ckpt_bytes=self.ckpt_bytes, log_format=self.log_format,
                ckpt_duty=self.ckpt_duty,
            )
            role.hb_interval_s = self.ttl_s / 3
            roles.append(role)
        return roles

    def _sweep_downstream(self) -> None:
        """Downstream stages follow deli ownership (the partition's
        deltas are written here anyway); each stage holds its OWN
        fenced lease (``scriptorium-p{k}`` / ``broadcaster-{rid}`` ...)
        so a deposed worker's late downstream append is rejected like
        any other role's."""
        for k in list(self.down_roles):
            if k not in self.roles:
                self._release_down(k, "deli released")
        for k in self.roles:
            if k not in self.down_roles:
                self.down_roles[k] = self._make_down_roles(k)

    def _release_down(self, key: Any, why: str) -> None:
        roles = self.down_roles.pop(key, None)
        if not roles:
            return
        for role in roles:
            role.close_doorbell()
            if role.fence is not None:
                try:
                    role.checkpoint()
                except (FencedError, OSError):
                    pass
                role.leases.release(role.name)
        self._event(f"released downstream {self._kname(key)} ({why})")

    def _release(self, key: Any, why: str) -> None:
        """Graceful fenced handoff: final checkpoint under our (still
        valid) fence, then release with expires=0 — the successor's
        next sweep takes over immediately, restores this checkpoint,
        and its recovery scan replays any durable gap silently."""
        role = self.roles.pop(key, None)
        if role is None:
            return
        role.close_doorbell()
        if role.fence is not None:
            try:
                role.checkpoint()
            except (FencedError, OSError):
                pass  # a successor already holds the fence: its state wins
            role.leases.release(role.name)
            # Count only REAL handoffs: dropping a role instance that
            # never acquired its lease released nothing.
            self._m_handoffs.inc()
        self._event(f"released {self._kname(key)} ({why})")

    @staticmethod
    def _kname(key: Any) -> str:
        return f"p{key}" if isinstance(key, int) else str(key)

    def sweep(self) -> None:
        """One balance pass: (elastic) adopt the newest topology epoch,
        execute staged split/merge commands, retire dead ranges; then
        shed surplus, prune lost races, acquire toward target."""
        if self.elastic:
            topo = self.store.read_topology()
            if topo is not None and (
                    self.topology is None
                    or topo["epoch"] != self.topology["epoch"]):
                self._event(f"topology epoch {topo['epoch']}")
                self.topology = topo
            self._process_controls()
            # A committed split/merge retires its source range(s):
            # release NOW (final fenced checkpoint) instead of pumping
            # until the successor's fence rejects us.
            live = set(self._keys())
            for k in [k for k in self.roles if k not in live]:
                self._release(k, "topology-retired")
        keys = self._keys()
        target = self.target_partitions()
        # Shed surplus (highest key first: deterministic, so two
        # overfull workers don't thrash the same partition).
        while len(self.roles) > target:
            self._release(sorted(self.roles)[-1], "rebalance")
        # Prune instances that never acquired while a live foreign
        # owner holds the lease (we lost the race).
        for p, role in list(self.roles.items()):
            if role.fence is None:
                owner = self._probe.owner_of(self._lease_name(p))
                if owner is not None and owner != self.owner:
                    self.roles.pop(p)
                    role.close_doorbell()
        # Acquire free/expired partitions up to target, scanning from a
        # slot-dependent start so peers spread instead of colliding.
        if len(self.roles) < target and keys:
            # crc32, not hash(): per-process salt would make the scan
            # start differ between a worker and its restarted self.
            start = zlib.crc32(self.slot.encode()) % len(keys)
            for i in range(len(keys)):
                if len(self.roles) >= target:
                    break
                p = keys[(start + i) % len(keys)]
                if p in self.roles:
                    continue
                owner = self._probe.owner_of(self._lease_name(p))
                if owner is None or owner == self.owner:
                    self.roles[p] = self._make_role(p)
        if self.summarize:
            self._sweep_summarizers()
        if self.downstream:
            self._sweep_downstream()
        self._m_owned.set(len(self.roles))
        self._sweep_t = time.time()

    # --------------------------------------------- split/merge execution

    def _process_controls(self) -> None:
        """Execute staged topology commands for ranges this worker
        owns (`request_topology_change` writes them; whoever owns the
        target executes and writes the ``.done`` marker). A lost
        commit CAS leaves the command pending for the next sweep."""
        cdir = _control_dir(self.shared_dir)
        try:
            names = sorted(os.listdir(cdir))
        except OSError:
            return
        for fn in names:
            if not fn.endswith(".json") or fn.endswith(".done.json"):
                continue
            path = os.path.join(cdir, fn)
            done_path = path[:-len(".json")] + ".done.json"
            if os.path.exists(done_path):
                continue
            try:
                with open(path) as f:
                    cmd = json.load(f)
            except (OSError, ValueError):
                continue
            op = cmd.get("op") if isinstance(cmd, dict) else None
            if op == "split":
                self._control_split(cmd, done_path)
            elif op == "merge":
                self._control_merge(cmd, done_path)
            else:
                self._done_marker(done_path, error=f"unknown op {op!r}")

    def _done_marker(self, done_path: str, **result) -> None:
        tmp = done_path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({
                "by": self.owner,
                "epoch": (self.topology or {}).get("epoch"),
                **result,
            }, f)
        os.replace(tmp, done_path)

    def _control_split(self, cmd: dict, done_path: str) -> None:
        live = {e["rid"] for e in self.topology["ranges"]}
        rid = cmd.get("rid")
        if rid is None:
            # No target named: capacity follows load — split the widest
            # range this worker owns.
            owned = [k for k, r in self.roles.items()
                     if r.fence is not None]
            if not owned:
                return
            rid = max(owned, key=lambda k: (
                self._entry(k)["hi"] - self._entry(k)["lo"]
            ))
        if rid not in live:
            self._done_marker(done_path, error=f"range {rid} not live")
            return
        role = self.roles.get(rid)
        if role is None or role.fence is None:
            return  # not ours (yet): the owner executes
        try:
            # The parent's FINAL fenced checkpoint — what both children
            # seed from. Written BEFORE the topology commit, so a crash
            # between the two leaves the old epoch fully intact.
            role.checkpoint()
        except (FencedError, OSError):
            self.roles.pop(rid, None)
            self._m_drops.inc()
            return
        topo2 = split_ranges(self.topology, rid, cmd.get("at"))
        if self.store.commit_topology(topo2, self.topology["epoch"]):
            role.leases.release(role.name)
            self.roles.pop(rid, None)
            self._m_handoffs.inc()
            self.topology = self.store.read_topology()
            self._event(
                f"split {rid} -> epoch {self.topology['epoch']}"
            )
            self._done_marker(done_path, op="split", rid=rid)
        else:
            self.topology = self.store.read_topology() or self.topology

    def _control_merge(self, cmd: dict, done_path: str) -> None:
        rids = cmd.get("rids") or []
        if len(rids) != 2:
            self._done_marker(done_path, error=f"merge needs 2 rids: "
                                               f"{rids}")
            return
        live = {e["rid"]: e for e in self.topology["ranges"]}
        if rids[0] not in live or rids[1] not in live:
            self._done_marker(done_path,
                              error=f"ranges {rids} not all live")
            return
        a, b = sorted(rids, key=lambda r: live[r]["lo"])
        if live[a]["hi"] != live[b]["lo"]:
            self._done_marker(done_path,
                              error=f"ranges {rids} not adjacent")
            return
        role_a = self.roles.get(a)
        if role_a is None or role_a.fence is None:
            # Executor rule: the LEFT range's owner executes; the right
            # owner hands its range off the moment it sees the command
            # so the executor's acquisition never waits out a TTL.
            if b in self.roles:
                self._release(b, "merge-handoff")
            return
        lm = self.store.leases
        role_b = self.roles.get(b)
        if role_b is not None and role_b.fence is not None:
            # We own both: final-checkpoint b and KEEP its lease bound
            # through the commit — releasing first would open a window
            # where a peer's sweep acquires the about-to-retire range
            # (try_acquire's already-ours short-circuit would hand the
            # released lease back without re-arming it).
            try:
                role_b.checkpoint()
            except (FencedError, OSError):
                pass  # a successor's fence already won: its state stands
            self.roles.pop(b, None)
            self._m_handoffs.inc()
            self._event(f"released {self._kname(b)} (merge-handoff)")
        else:
            if role_b is not None:
                self.roles.pop(b, None)  # never acquired: nothing held
            if lm.try_acquire(range_lease_name(b)) is None:
                return  # right owner hasn't handed off yet: next sweep
        try:
            role_a.checkpoint()  # the left parent's final checkpoint
        except (FencedError, OSError):
            self.roles.pop(a, None)
            self._m_drops.inc()
            lm.release(range_lease_name(b))
            return
        topo2 = merge_ranges(self.topology, a, b)
        if self.store.commit_topology(topo2, self.topology["epoch"]):
            role_a.leases.release(role_a.name)
            self.roles.pop(a, None)
            self._m_handoffs.inc()
            self.topology = self.store.read_topology()
            self._event(
                f"merge {a}+{b} -> epoch {self.topology['epoch']}"
            )
            self._done_marker(done_path, op="merge", rids=[a, b])
        else:
            self.topology = self.store.read_topology() or self.topology
        lm.release(range_lease_name(b))

    # -------------------------------------------------------------- pump

    def step(self) -> int:
        """One fabric quantum: pump every owned partition once, then
        (throttled) heartbeat + rebalance sweep. Returns records
        moved. A deposed/fenced partition drops OUT of this worker —
        never the worker itself: the other partitions it owns must
        keep sequencing (contrast `serve_role`, where the process IS
        the partition)."""
        moved = 0
        for p, role in list(self.roles.items()):
            try:
                moved += role.step(idle_sleep=0)
            except SystemExit as exc:
                self.roles.pop(p, None)
                role.close_doorbell()
                self._m_drops.inc()
                self._event(f"dropped {self._kname(p)} (exit={exc.code})")
            except FencedError as exc:
                self.roles.pop(p, None)
                role.close_doorbell()
                self._m_drops.inc()
                self._event(f"dropped {self._kname(p)} (fenced: {exc})")
        for p, role in list(self.summ_roles.items()):
            try:
                moved += role.step(idle_sleep=0)
            except (SystemExit, FencedError) as exc:
                self.summ_roles.pop(p, None)
                role.close_doorbell()
                self._event(
                    f"dropped summarizer {self._kname(p)} ({exc})"
                )
        for p, roles in list(self.down_roles.items()):
            for role in list(roles):
                try:
                    moved += role.step(idle_sleep=0)
                except (SystemExit, FencedError) as exc:
                    # Drop the whole partition's downstream set (the
                    # deposed stage's siblings released gracefully):
                    # the key leaves down_roles, so the next sweep
                    # recreates fresh instances while we still own the
                    # deli — a single deposed stage must not leave the
                    # partition's durable leg unowned forever.
                    roles.remove(role)
                    role.close_doorbell()
                    self._event(
                        f"dropped downstream {role.name} ({exc})"
                    )
                    self._release_down(p, f"{role.name} deposed")
                    break  # the key's remaining roles just released
        now = time.time()
        if now - self._sweep_t > self.ttl_s / 2:
            self.sweep()
        if now - self._hb_t > self.ttl_s / 3:
            self.heartbeat()
        return moved

    def idle_wait(self, timeout_s: float) -> None:
        """The worker's idle quantum: wait on ALL owned partitions'
        input-topic doorbells at once (any append wakes the next
        step), bounded by `timeout_s` so the sweep/heartbeat cadence
        and the poll fallback are unaffected."""
        from .queue import wait_doorbells

        import itertools

        bells = [b for b in (
            r.doorbell() for r in itertools.chain(
                self.roles.values(), self.summ_roles.values(),
                *self.down_roles.values(),
            )
        ) if b is not None]
        if bells:
            # Bounded stretch (the _Role.bell_wait_s rationale), capped
            # so the sweep/heartbeat cadence (ttl/2, ttl/3) still runs
            # on time.
            wait_doorbells(
                bells, min(max(timeout_s, 0.05), self.ttl_s / 6)
            )
        else:
            time.sleep(timeout_s)

    def stop(self) -> None:
        """Graceful exit: hand every partition off now instead of
        making successors wait out the lease TTL."""
        for p in sorted(self.summ_roles):
            self._release_summ(p, "shutdown")
        for p in sorted(self.down_roles):
            self._release_down(p, "shutdown")
        for p in sorted(self.roles):
            self._release(p, "shutdown")
        try:
            os.remove(self._hb_path())
        except OSError:
            pass


def serve_shard_worker(shared_dir: str, slot: str,
                       owner: Optional[str] = None,
                       idle_sleep: float = 0.01, **kw) -> None:
    """Child-process entry: run one shard worker until killed."""
    w = ShardWorker(shared_dir, slot, owner=owner, **kw)
    w.heartbeat()
    w.sweep()
    # Bare "READY <slot>" when slot IS the owner (the standalone CLI
    # contract tools/partition_worker_main.py keeps); supervised
    # children append their generation owner for the event log.
    banner = f"READY {slot}" + (
        f" {w.owner}" if w.owner != slot else ""
    )
    print(banner, flush=True)
    while True:
        if w.step() == 0:
            w.idle_wait(idle_sleep)


# ---------------------------------------------------------------------------
# load-driven autoscaling
# ---------------------------------------------------------------------------


def stage_p99s(snap: dict, stage: str
               ) -> Tuple[Optional[float], Dict[str, float]]:
    """(farm_p99, {partition: p99}) for one wire-trace stage off a
    metrics snapshot. Per-partition series come straight from the
    ``op_stage_ms{stage=...,partition=k}`` histograms the ranged roles
    observe; the FARM-WIDE quantile is estimated over the bucket-wise
    SUM of every matching histogram (label-less classic series
    included), so it stays one quantile of one distribution rather
    than a quantile of quantiles. Beyond-last-bucket estimates are
    dropped, not faked."""
    from ..utils.metrics import histogram_quantile

    merged: Optional[dict] = None
    per: Dict[str, float] = {}
    for h in snap.get("histograms", ()):
        if (h.get("name") != "op_stage_ms"
                or (h.get("labels") or {}).get("stage") != stage
                or not h.get("count")):
            continue
        part = h["labels"].get("partition")
        if part is not None:
            v = histogram_quantile(h, 0.99)
            if v != float("inf"):
                per[part] = v
        if merged is None:
            merged = {"buckets": list(h["buckets"]),
                      "counts": list(h["counts"]),
                      "count": int(h["count"])}
        elif merged["buckets"] == list(h["buckets"]):
            merged["counts"] = [a + b for a, b in
                                zip(merged["counts"], h["counts"])]
            merged["count"] += int(h["count"])
    farm = None
    if merged is not None and merged["count"]:
        v = histogram_quantile(merged, 0.99)
        if v != float("inf"):
            farm = v
    return farm, per


class AutoscalePolicy:
    """The closed autoscaling loop: a supervisor-side policy watching
    per-partition deli throughput (``role_records_total{role="deli",
    partition=rid}`` rates off the merged worker-heartbeat registry)
    and the farm's ``/slo`` p99 (``op_stage_ms`` quantiles, wire-trace
    runs), issuing `request_split` on sustained HOT ranges and
    `request_merge` on sustained COLD adjacent pairs over the EXISTING
    control channel — capacity follows load, no human in the loop.

    Anti-flap machinery, all three layers deliberate:

    - **hysteresis** — the split threshold (`split_rate`) sits well
      above the merge threshold (`merge_rate`), so a range oscillating
      near either line never qualifies for both;
    - **sustain** — a range must hold its hot/cold verdict for
      `sustain_s` continuous seconds before the policy acts (one
      bursty pump is not load);
    - **min-interval** — at most one topology change per
      `min_interval_s`, and never while a previously issued command is
      still pending, so the fabric always finishes absorbing one epoch
      before the policy can stage the next.

    Pure decision logic: `observe()` takes the sampled state and
    returns at most one staged command dict — the supervisor owns the
    sampling cadence and the control-channel write, and the chaos
    harness gates a policy-driven split mid-boxcar bit-identical like
    any operator-driven one."""

    def __init__(self, split_rate: float = 2000.0,
                 merge_rate: float = 50.0, sustain_s: float = 3.0,
                 min_interval_s: float = 10.0, max_ranges: int = 16,
                 min_ranges: int = 1,
                 p99_hot_ms: Optional[float] = None,
                 p99_stage: str = "submit_to_stamp",
                 p99_per_partition: bool = False):
        """`split_rate`/`merge_rate`: records/s per range above/below
        which a range counts hot/cold. `p99_hot_ms` (optional): when
        the farm-wide `op_stage_ms{stage=p99_stage}` p99 exceeds it,
        the HIGHEST-rate range counts hot too — the latency-driven
        trigger for load the rate threshold alone misses (one huge doc
        in an otherwise quiet range). Needs wire tracing to populate;
        None disables the latency trigger.

        `p99_per_partition=True` sharpens the latency trigger to the
        PER-RANGE quantiles (the ``op_stage_ms{stage=...,partition=k}``
        series the ranged roles observe into their worker heartbeats):
        a range whose OWN p99 exceeds `p99_hot_ms` counts hot,
        regardless of the farm-wide quantile or where the record rate
        is highest — one hot range in a quiet farm triggers its own
        split instead of hiding inside a healthy farm-wide p99 (or
        splitting the wrong, merely-busiest, range)."""
        if merge_rate >= split_rate:
            raise ValueError(
                f"hysteresis requires merge_rate < split_rate "
                f"(got {merge_rate} >= {split_rate})"
            )
        self.split_rate = float(split_rate)
        self.merge_rate = float(merge_rate)
        self.sustain_s = float(sustain_s)
        self.min_interval_s = float(min_interval_s)
        self.max_ranges = int(max_ranges)
        self.min_ranges = int(min_ranges)
        self.p99_hot_ms = p99_hot_ms
        self.p99_stage = p99_stage
        self.p99_per_partition = bool(p99_per_partition)
        self._last_sample: Optional[Tuple[float, Dict[str, float]]] = None
        self.hot_since: Dict[str, float] = {}
        self.cold_since: Dict[str, float] = {}
        # None until the FIRST action: min-interval paces actions
        # apart, it must not delay the first one.
        self.last_action_t: Optional[float] = None
        self.actions: List[dict] = []  # staged commands, for operators

    # ------------------------------------------------------------ sample

    def rates(self, now: float,
              counts: Dict[str, float]) -> Optional[Dict[str, float]]:
        """Per-range records/s from successive counter samples (None
        until two samples exist). Clamped at zero: a restarted worker
        resets its counters and a raw diff would go negative."""
        prev = self._last_sample
        self._last_sample = (now, dict(counts))
        if prev is None:
            return None
        dt = now - prev[0]
        if dt <= 0:
            return None
        return {
            rid: max(0.0, (counts.get(rid, 0.0) - prev[1].get(rid, 0.0))
                     ) / dt
            for rid in counts
        }

    # ------------------------------------------------------------ decide

    def observe(self, now: float, rates: Dict[str, float],
                topo: dict,
                p99_ms: Optional[float] = None,
                p99_by_partition: Optional[Dict[str, float]] = None,
                ) -> Optional[dict]:
        """Fold one sample; returns a command dict ({"op": "split",
        "rid": ...} / {"op": "merge", "rids": [...]}) when the policy
        fires, else None. The caller stages it and must not call
        `observe` with a pending unexecuted command. `p99_by_partition`
        (range id -> that range's own stage p99, ms) feeds the
        `p99_per_partition` trigger; ignored otherwise."""
        ranges = sorted(topo["ranges"], key=lambda e: e["lo"])
        live = {e["rid"] for e in ranges}
        for d in (self.hot_since, self.cold_since):
            for rid in [r for r in d if r not in live]:
                d.pop(rid)
        hottest = max(rates, key=lambda r: rates[r]) if rates else None
        latency_hot = (
            not self.p99_per_partition
            and self.p99_hot_ms is not None and p99_ms is not None
            and p99_ms > self.p99_hot_ms
        )
        per_p99 = p99_by_partition or {}
        for rid in live:
            rate = rates.get(rid, 0.0)
            own_p99 = per_p99.get(rid)
            own_hot = (
                self.p99_per_partition and self.p99_hot_ms is not None
                and own_p99 is not None and own_p99 > self.p99_hot_ms
            )
            if rate > self.split_rate or own_hot \
                    or (latency_hot and rid == hottest):
                self.hot_since.setdefault(rid, now)
            else:
                self.hot_since.pop(rid, None)
            if rate < self.merge_rate:
                self.cold_since.setdefault(rid, now)
            else:
                self.cold_since.pop(rid, None)
        if self.last_action_t is not None \
                and now - self.last_action_t < self.min_interval_s:
            return None
        # Split the longest-sustained hot range first.
        hot = [(now - t0, rid) for rid, t0 in self.hot_since.items()
               if now - t0 >= self.sustain_s]
        if hot and len(ranges) < self.max_ranges:
            _, rid = max(hot)
            self.last_action_t = now
            self.hot_since.pop(rid, None)
            cmd = {"op": "split", "rid": rid, "why": "autoscale-hot"}
            self.actions.append({"t": now, **cmd})
            return cmd
        # Merge the first adjacent pair that is cold on BOTH sides.
        if len(ranges) > max(1, self.min_ranges):
            for a, b in zip(ranges, ranges[1:]):
                if a["hi"] != b["lo"]:
                    continue
                ta = self.cold_since.get(a["rid"])
                tb = self.cold_since.get(b["rid"])
                if ta is None or tb is None:
                    continue
                if min(now - ta, now - tb) >= self.sustain_s:
                    self.last_action_t = now
                    self.cold_since.pop(a["rid"], None)
                    self.cold_since.pop(b["rid"], None)
                    cmd = {"op": "merge",
                           "rids": [a["rid"], b["rid"]],
                           "why": "autoscale-cold"}
                    self.actions.append({"t": now, **cmd})
                    return cmd
        return None


# ---------------------------------------------------------------------------
# the fabric supervisor
# ---------------------------------------------------------------------------


class ShardFabricSupervisor(ServiceSupervisor):
    """W shard workers as supervised children over N partitions.

    Reuses the `ServiceSupervisor` monitor machinery wholesale (process
    exit + heartbeat staleness, paced respawn, fresh owner identity per
    generation) — a "role" here is a worker SLOT (``shard-w0``…), its
    heartbeat the worker file `ShardWorker.heartbeat` writes. A
    restarted worker re-enters the lease sweep and the fabric
    rebalances around it; per-partition metrics ride the worker
    heartbeats and merge at `collect_metrics` exactly like the classic
    farm's role metrics."""

    def __init__(self, shared_dir: str, n_workers: int = 2,
                 n_partitions: int = 4,
                 max_partitions: Optional[int] = None,
                 worker_ttl_s: Optional[float] = None,
                 elastic: bool = False, summarize: bool = False,
                 downstream: Optional[str] = None,
                 ingress: bool = False,
                 autoscale: Any = None,
                 **kw):
        """`downstream` ("fused"|"split") runs per-partition
        scriptorium/broadcaster/scribe consumers inside each worker
        (see `ShardWorker`). `ingress=True` adds the supervised
        admission front door (`server.ingress.IngressRole`) as an
        extra child routing the ``ingress`` topic into the fabric's
        raw partitions. `autoscale` (an `AutoscalePolicy`, or True
        for defaults; elastic only) closes the scaling loop: the
        supervisor samples per-partition throughput each monitor pass
        and stages policy-driven splits/merges on the control
        channel."""
        if n_workers < 1:
            raise ValueError(f"n_workers must be >= 1: {n_workers}")
        self.n_partitions = int(n_partitions)
        self.max_partitions = max_partitions
        self.worker_ttl_s = worker_ttl_s
        self.elastic = bool(elastic)
        self.summarize = bool(summarize)
        if downstream is not None and downstream not in DOWNSTREAM_MODES:
            raise ValueError(
                f"downstream {downstream!r} not in "
                f"{sorted(DOWNSTREAM_MODES)}"
            )
        if downstream == "fused" and self.elastic:
            raise ValueError(
                "downstream='fused' is static-partition only "
                "(use 'split' on the elastic fabric)"
            )
        self.downstream = downstream
        if autoscale and not self.elastic:
            raise ValueError(
                "autoscale needs elastic=True (the policy issues "
                "live range splits/merges)"
            )
        self.autoscale: Optional[AutoscalePolicy] = (
            autoscale if isinstance(autoscale, AutoscalePolicy)
            else (AutoscalePolicy() if autoscale else None)
        )
        self._autoscale_t = 0.0
        self._autoscale_pending: Optional[str] = None
        roles = tuple(f"shard-w{i}" for i in range(n_workers))
        if ingress:
            roles = ("ingress",) + roles
        self.ingress_enabled = bool(ingress)
        super().__init__(shared_dir, roles=roles, **kw)
        os.makedirs(os.path.join(shared_dir, "workers"), exist_ok=True)
        if self.elastic:
            # Bootstrap the topology before any child spawns, so the
            # router/workers/harness all adopt one epoch-1 record.
            self.store: Optional[RangeLeaseStore] = RangeLeaseStore(
                shared_dir, "__supervisor__"
            )
            self.store.ensure_topology(self.n_partitions)
        else:
            self.store = None

    def _child_cmd(self, role: str, owner: str) -> List[str]:
        if role == "ingress":
            # The front door is a classic supervised role child
            # (server.supervisor main), pointed at the fabric's
            # partition topology so its router writes the same raw
            # topics the workers consume.
            cmd = [self.python, "-c",
                   "from fluidframework_tpu.server.supervisor import "
                   "main; main()",
                   "--role", "ingress", "--dir", self.shared_dir,
                   "--owner", owner, "--ttl", str(self.ttl_s),
                   "--batch", str(self.batch),
                   "--log-format", self.log_format,
                   "--ckpt-interval", str(self.ckpt_interval_s),
                   "--ckpt-bytes", str(self.ckpt_bytes),
                   "--ckpt-duty", str(self.ckpt_duty),
                   "--ingress-partitions", str(self.n_partitions)]
            if self.elastic:
                cmd += ["--ingress-elastic"]
            if self.hb_interval_s is not None:
                cmd += ["--hb-interval", str(self.hb_interval_s)]
            return cmd
        cmd = [self.python, "-c",
               "from fluidframework_tpu.server.shard_fabric import main; "
               "main()",
               "--dir", self.shared_dir, "--slot", role,
               "--owner", owner,
               "--partitions", str(self.n_partitions),
               "--ttl", str(self.ttl_s), "--batch", str(self.batch),
               "--impl", self.deli_impl,
               "--log-format", self.log_format,
               "--ckpt-interval", str(self.ckpt_interval_s),
               "--ckpt-bytes", str(self.ckpt_bytes),
               "--ckpt-duty", str(self.ckpt_duty)]
        if self.max_partitions is not None:
            cmd += ["--max-partitions", str(self.max_partitions)]
        if self.worker_ttl_s is not None:
            cmd += ["--worker-ttl", str(self.worker_ttl_s)]
        if self.deli_devices is not None:
            cmd += ["--deli-devices", str(self.deli_devices)]
        if self.device_plane is not None:
            # One worker = one mesh slice: worker i orders on model
            # column i (mod model) of the shared plane.
            cmd += ["--device-plane", self.device_plane]
            try:
                col = int(role.rsplit("w", 1)[1])
            except (IndexError, ValueError):
                col = 0
            cmd += ["--plane-column", str(col)]
        if self.elastic:
            cmd += ["--elastic"]
        if self.summarize:
            cmd += ["--summarize"]
            if self.summary_ops is not None:
                cmd += ["--summary-ops", str(self.summary_ops)]
        if self.downstream:
            cmd += ["--downstream", self.downstream]
        return cmd

    def _hb_file(self, role: str) -> str:
        if role == "ingress":
            # The front door heartbeats like a classic role child, not
            # a worker slot.
            return os.path.join(self.shared_dir, "hb", "ingress.json")
        return os.path.join(self.shared_dir, "workers", f"{role}.json")

    def partition_owners(self) -> Dict[str, str]:
        """Live {``deli-p{k}`` | ``deli-{rid}``: owner} — the
        operator's ownership view (`queue.lease_owners` over the
        fabric's lease directory)."""
        return lease_owners(os.path.join(self.shared_dir, "leases"))

    def partition_leases(self) -> Dict[str, dict]:
        """The full lease view — owner AND fence/expiry per partition
        (`queue.lease_table`): the fence is how a reader tells a stale
        pre-split owner from the live one."""
        return lease_table(os.path.join(self.shared_dir, "leases"))

    def topology(self) -> Optional[dict]:
        """The live hash-range topology record (None when static)."""
        return self.store.read_topology() if self.elastic else None

    def request_split(self, rid: Optional[str] = None,
                      at: Optional[int] = None) -> str:
        """Stage a live split of range `rid` (default: the owner's
        widest range) at hash `at` (default: midpoint). Returns the
        command id; the owning worker executes it on its next sweep
        and `control_result(shared_dir, cmd_id)` reports the new
        epoch."""
        if not self.elastic:
            raise ValueError("request_split needs elastic=True")
        cmd: Dict[str, Any] = {"op": "split"}
        if rid is not None:
            cmd["rid"] = rid
        if at is not None:
            cmd["at"] = int(at)
        return request_topology_change(self.shared_dir, cmd)

    def request_merge(self, rid_a: str, rid_b: str) -> str:
        """Stage a live merge of adjacent ranges `rid_a`/`rid_b`."""
        if not self.elastic:
            raise ValueError("request_merge needs elastic=True")
        return request_topology_change(
            self.shared_dir, {"op": "merge", "rids": [rid_a, rid_b]}
        )

    def control_result(self, cmd_id: str) -> Optional[dict]:
        return control_result(self.shared_dir, cmd_id)

    # ------------------------------------------------------- autoscaling

    def poll_once(self) -> List[str]:
        acted = super().poll_once()
        if self.autoscale is not None:
            self.autoscale_tick()
        return acted

    def autoscale_tick(self, force: bool = False) -> Optional[str]:
        """One autoscale sample/decide pass, throttled to ~half the
        lease TTL (`force` bypasses the throttle, for tests): sample
        per-partition deli record counters off the worker heartbeats,
        wait out any previously staged command (one epoch change in
        flight at a time — the fabric must finish absorbing it), and
        stage at most one policy-driven split/merge on the control
        channel. Returns the staged command id, if any."""
        pol = self.autoscale
        if pol is None:
            return None
        now = time.time()
        if not force and now - self._autoscale_t < max(
                0.25, self.ttl_s / 2):
            return None
        self._autoscale_t = now
        if self._autoscale_pending is not None:
            if self.control_result(self._autoscale_pending) is None:
                return None  # previous command still executing
            self._autoscale_pending = None
        topo = self.topology()
        if topo is None:
            return None
        counts: Dict[str, float] = {}
        for snap in self.child_metrics().values():
            for c in snap.get("counters", ()):
                if (c.get("name") == "role_records_total"
                        and c.get("labels", {}).get("role") == "deli"):
                    rid = c["labels"].get("partition")
                    if rid is not None:
                        counts[rid] = (counts.get(rid, 0.0)
                                       + float(c["value"]))
        rates = pol.rates(now, counts)
        if rates is None:
            return None  # need two samples for a rate
        p99 = None
        p99_by_part: Optional[Dict[str, float]] = None
        if pol.p99_hot_ms is not None:
            snap = self.collect_metrics().snapshot()
            p99, p99_by_part = stage_p99s(snap, pol.p99_stage)
        cmd = pol.observe(now, rates, topo, p99_ms=p99,
                          p99_by_partition=p99_by_part)
        if cmd is None:
            return None
        why = cmd.pop("why", "autoscale")
        cid = request_topology_change(self.shared_dir, cmd)
        self._autoscale_pending = cid
        self._event(
            f"autoscale: staged {cmd.get('op')} "
            f"{cmd.get('rid') or cmd.get('rids')} ({why})"
        )
        return cid

    def degraded_partitions(self) -> List[str]:
        """Partitions currently limping through a storage-fault retry
        budget: the `degraded` lists the worker heartbeats export,
        UNION the fresh per-role heartbeats — a role stuck inside its
        backoff cannot return to the worker loop, so its own forced
        heartbeat (`_Role._durable`) is the prompt signal; worker
        heartbeats catch up between steps. Role files older than the
        heartbeat timeout are ignored (a crashed role must not pin
        the fabric degraded forever)."""
        out = set()
        for role in self.roles:
            try:
                with open(self._hb_file(role)) as f:
                    hb = json.load(f)
            except (OSError, ValueError):
                continue
            out.update(str(p) for p in hb.get("degraded") or [])
        hb_dir = os.path.join(self.shared_dir, "hb")
        now = time.time()
        try:
            names = os.listdir(hb_dir)
        except OSError:
            names = []
        for fn in names:
            if not fn.endswith(".json"):
                continue
            try:
                with open(os.path.join(hb_dir, fn)) as f:
                    hb = json.load(f)
            except (OSError, ValueError):
                continue
            if (hb.get("degraded")
                    and now - float(hb.get("t", 0))
                    <= self.heartbeat_timeout_s):
                out.add(fn[:-len(".json")])
        return sorted(out)

    def health(self) -> Dict[str, Any]:
        h = super().health()
        owners = self.partition_owners()
        topo = self.topology()
        expected = (len(topo["ranges"]) if topo is not None
                    else self.n_partitions)
        h["n_partitions"] = expected
        h["partition_owners"] = owners
        h["partition_leases"] = self.partition_leases()
        if topo is not None:
            h["epoch"] = topo["epoch"]
            h["ranges"] = [e["rid"] for e in topo["ranges"]]
        limping = self.degraded_partitions()
        h["degraded_partitions"] = limping
        h["downstream"] = self.downstream
        h["ingress"] = self.ingress_enabled
        if self.autoscale is not None:
            h["autoscale_actions"] = len(self.autoscale.actions)
        # Degraded until every partition has a live owner (boot,
        # takeover, split/merge windows — unowned partitions buffer,
        # not lose) and none is inside a storage-fault retry budget:
        # an operator should see either gap.
        if len(owners) < expected or limping:
            h["status"] = "degraded"
        return h

    def collect_metrics(self):
        reg = super().collect_metrics()
        leases = self.partition_leases()
        topo = self.topology()
        reg.gauge("shard_partitions_total").set(
            len(topo["ranges"]) if topo is not None
            else self.n_partitions
        )
        reg.gauge("shard_partitions_owned_live").set(len(leases))
        if topo is not None:
            reg.gauge("shard_topology_epoch").set(topo["epoch"])
        if self.autoscale is not None:
            reg.gauge("shard_autoscale_actions").set(
                len(self.autoscale.actions)
            )
        for name, info in leases.items():
            # The lease FENCE next to the owner (satellite of the
            # lease_table fix): a scrape can tell a stale pre-split
            # owner's series from the live one's.
            reg.gauge("shard_partition_fence", partition=name).set(
                info["fence"]
            )
        return reg


# ---------------------------------------------------------------------------
# child entry
# ---------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)

    def _take(flag: str, default: Optional[str] = None) -> Optional[str]:
        if flag in args:
            i = args.index(flag)
            val = args[i + 1]
            del args[i:i + 2]
            return val
        return default

    elastic = "--elastic" in args
    if elastic:
        args.remove("--elastic")
    summarize = "--summarize" in args
    if summarize:
        args.remove("--summarize")
    summary_ops_s = _take("--summary-ops")
    downstream = _take("--downstream")
    shared_dir = _take("--dir")
    slot = _take("--slot")
    owner = _take("--owner")
    n_partitions = int(_take("--partitions", "4"))
    ttl = float(_take("--ttl", "1.0"))
    batch = int(_take("--batch", "512"))
    impl = _take("--impl") or os.environ.get("FLUID_DELI", "scalar")
    log_format = _take("--log-format")
    ckpt_interval = float(_take("--ckpt-interval", "0.25"))
    ckpt_bytes = int(_take("--ckpt-bytes", str(256 * 1024)))
    ckpt_duty = float(_take("--ckpt-duty", "0.2"))
    max_p = _take("--max-partitions")
    worker_ttl = _take("--worker-ttl")
    devices_s = _take("--deli-devices")
    device_plane_s = _take("--device-plane")
    plane_col_s = _take("--plane-column")
    if (shared_dir is None or slot is None or args
            or impl not in DELI_IMPLS
            or (log_format is not None and log_format not in LOG_FORMATS)
            or (devices_s is not None and not devices_s.isdigit())
            or (plane_col_s is not None and not plane_col_s.isdigit())
            or (downstream is not None
                and downstream not in DOWNSTREAM_MODES)
            or (summary_ops_s is not None
                and not summary_ops_s.isdigit())):
        print(
            "usage: python -m fluidframework_tpu.server.shard_fabric "
            "--dir D --slot S [--owner O] [--partitions N] [--ttl S] "
            "[--batch N] [--impl scalar|kernel] "
            "[--log-format json|columnar] [--max-partitions K] "
            "[--worker-ttl S] [--deli-devices N] "
            "[--device-plane DxM] [--plane-column K] [--elastic] "
            "[--summarize] [--summary-ops N] [--downstream fused|split] "
            "[--ckpt-interval S] [--ckpt-bytes N] [--ckpt-duty F]",
            file=sys.stderr,
        )
        raise SystemExit(2)
    serve_shard_worker(
        shared_dir, slot, owner=owner, n_partitions=n_partitions,
        deli_impl=impl, log_format=log_format, ttl_s=ttl, batch=batch,
        max_partitions=int(max_p) if max_p else None,
        ckpt_interval_s=ckpt_interval, ckpt_bytes=ckpt_bytes,
        ckpt_duty=ckpt_duty,
        worker_ttl_s=float(worker_ttl) if worker_ttl else None,
        deli_devices=int(devices_s) if devices_s else None,
        elastic=elastic, summarize=summarize,
        summary_ops=int(summary_ops_s) if summary_ops_s else None,
        downstream=downstream,
        device_plane=device_plane_s,
        plane_column=int(plane_col_s) if plane_col_s else None,
    )


if __name__ == "__main__":
    main()
