"""Sharded ordering fabric: a lease-balanced, multi-partition kernel-
deli farm with fenced partition handoff.

The reference scales routerlicious horizontally by splitting the
document space across Kafka partitions with ZooKeeper arbitrating
consumer ownership (SURVEY.md §2.5). This module is that topology over
the repo's own primitives — partitioning as a first-class subsystem
instead of the single-partition pipeline PRs 1–4 grew:

- **Document-space slicing** — `queue.partition_of` (consistent hash)
  maps every doc to one of N partitions; `ShardRouter` is the ingress
  edge (the lambdas-driver document-router role): one raw/sequenced
  topic pair PER partition (``rawdeltas-p{k}`` → ``deltas-p{k}``),
  boxcar records riding whole with their doc.
- **Lease-balanced ownership** — `ShardWorker` (one OS process) sweeps
  the partition leases (`queue.LeaseManager`, the zookeeper role) and
  runs ONE supervised deli role per owned partition
  (`supervisor.partitioned_role_class` over the scalar `DeliRole` or
  the device-batched `deli_kernel.KernelDeliRole`, either log format).
  Workers announce liveness in ``<dir>/workers/<slot>.json``; each
  targets ``ceil(N / alive_workers)`` partitions, so membership change
  IS the rebalance trigger: a joining worker makes peers shed surplus
  partitions (graceful release → immediate takeover), a dead worker's
  stale heartbeat raises the survivors' target and its expired leases
  are swept up.
- **Fenced handoff, exactly-once** — a partition changes hands through
  the PR-1 machinery unchanged: the new owner's lease carries a higher
  fence, its first output append binds that fence on ``deltas-p{k}``
  (a deposed owner's in-flight batch is REJECTED with `FencedError`),
  the loser's fenced checkpoint — per-doc sequencer state in
  `DocumentSequencer.checkpoint()` format, i.e. a `SeqPool` slice when
  the kernel deli wrote it — is restored by `_Role._recover`, and the
  exactly-once ``inOff`` scan replays the checkpoint→durable gap
  silently. A kill or rebalance mid-boxcar never dups or skips a
  sequence number (tests/test_chaos_recovery.py drives this with
  ``--faults kill,lease`` over the kernel+columnar fabric).
- **Supervision + observability** — `ShardFabricSupervisor` runs W
  workers as monitored children through the `ServiceSupervisor`
  machinery (heartbeat staleness, crash restart, fresh owner identity
  per generation); worker heartbeats carry per-partition-labeled
  metrics (``role="deli", partition="3"``) that the supervisor scrape
  merges into one registry.

`tools/shard_run.py` is the CLI; `testing.deli_bench.run_shard_bench`
proves the aggregate-throughput scaling (bench_configs
``config6_shard_scaling`` guards ≥1.5x at 4 partitions on ≥4-core
hosts); `tools/partition_worker_main.py` is now a thin wrapper over
`ShardWorker`.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time
import zlib
from typing import Any, Dict, List, Optional

from .columnar_log import LOG_FORMATS, default_log_format, make_topic
from .queue import (
    FencedError,
    LeaseManager,
    lease_table,
    partition_suffix,
    record_partition,
    split_by_partition,
)
from .supervisor import (
    DELI_IMPLS,
    ServiceSupervisor,
    _topic_path,
    partitioned_role_class,
    resolve_role_class,
)

__all__ = [
    "ShardFabricSupervisor",
    "ShardRouter",
    "ShardWorker",
    "partition_lease_name",
    "raw_topic_name",
    "deltas_topic_name",
    "serve_shard_worker",
    "spread_doc_names",
]


def raw_topic_name(partition: int) -> str:
    return partition_suffix("rawdeltas", partition)


def deltas_topic_name(partition: int) -> str:
    return partition_suffix("deltas", partition)


def partition_lease_name(partition: int) -> str:
    """The lease key partition ownership is arbitrated under — the
    partitioned deli role's name (`partitioned_role_class`), so the
    lease, heartbeat, checkpoint and fence all share one identity."""
    return partition_suffix("deli", partition)


def spread_doc_names(n_docs: int, n_partitions: int,
                     prefix: str = "doc") -> List[str]:
    """`n_docs` deterministic doc names that cover the partitions as
    evenly as the hash allows (scan names, round-robin the partition
    quota — the workload builders' answer to small-N hash clumping;
    real traffic gets the same balance from volume)."""
    from .queue import partition_of

    if n_partitions <= 1:
        return [f"{prefix}{i}" for i in range(n_docs)]
    per = {p: 0 for p in range(n_partitions)}
    quota = math.ceil(n_docs / n_partitions)
    out: List[str] = []
    i = 0
    while len(out) < n_docs and i < 10_000 * max(1, n_docs):
        name = f"{prefix}{i}"
        i += 1
        p = partition_of(name, n_partitions)
        if per[p] < quota:
            per[p] += 1
            out.append(name)
    return out


# ---------------------------------------------------------------------------
# ingress router
# ---------------------------------------------------------------------------


class ShardRouter:
    """The fabric's ingress edge: appends each raw record to its doc's
    partition topic (the document-router role). Boxcar-aware — a wire
    boxcar names one doc and rides whole, so its atomicity survives
    routing. Appends are grouped per partition per call (one fenced
    frame/lock per partition, not per record), and arrival order is
    preserved WITHIN each partition — the only order the per-document
    sequencing contract needs, since a doc lives in exactly one
    partition."""

    def __init__(self, shared_dir: str, n_partitions: int,
                 log_format: Optional[str] = None):
        if n_partitions < 1:
            raise ValueError(f"n_partitions must be >= 1: {n_partitions}")
        self.shared_dir = shared_dir
        self.n_partitions = n_partitions
        self.log_format = default_log_format(log_format)
        self.topics = [
            make_topic(_topic_path(shared_dir, raw_topic_name(p)),
                       self.log_format)
            for p in range(n_partitions)
        ]

    def partition(self, rec: Any) -> int:
        return record_partition(rec, self.n_partitions)

    def split(self, records: List[Any]) -> Dict[int, List[Any]]:
        """Records grouped by partition, input order preserved within
        each group (pure routing — no I/O)."""
        return split_by_partition(records, self.n_partitions)

    def append(self, records: List[Any]) -> Dict[int, int]:
        """Route + append one ingress batch; returns records appended
        per partition."""
        counts: Dict[int, int] = {}
        for p, recs in self.split(records).items():
            self.topics[p].append_many(recs)
            counts[p] = len(recs)
        return counts

    def deltas_topics(self) -> List[Any]:
        """Every partition's sequenced-output topic (the merged read
        surface convergence checks and catch-up readers use)."""
        return [
            make_topic(_topic_path(self.shared_dir, deltas_topic_name(p)),
                       self.log_format)
            for p in range(self.n_partitions)
        ]


# ---------------------------------------------------------------------------
# the worker
# ---------------------------------------------------------------------------


class ShardWorker:
    """One fabric node: sweeps partition leases toward its fair share
    and pumps a supervised deli role per owned partition.

    Balance is emergent, not orchestrated: each worker computes
    ``target = ceil(n_partitions / alive_workers)`` from the worker
    heartbeat directory and (a) gracefully RELEASES surplus partitions
    — final fenced checkpoint, then lease release with expires=0 so the
    successor takes over without waiting out the TTL — and (b) acquires
    free/expired partitions up to target. Ownership changes always run
    through the fence: the successor's recovery (`_Role._recover`)
    binds its higher fence on the output topic FIRST, so anything the
    deposed owner still has in flight is rejected, then restores the
    fenced checkpoint and closes the append-vs-checkpoint window with
    the exactly-once ``inOff`` scan."""

    def __init__(self, shared_dir: str, slot: str,
                 owner: Optional[str] = None, n_partitions: int = 4,
                 deli_impl: Optional[str] = None,
                 log_format: Optional[str] = None, ttl_s: float = 1.0,
                 batch: int = 512, max_partitions: Optional[int] = None,
                 ckpt_interval_s: float = 0.25,
                 ckpt_bytes: int = 256 * 1024, ckpt_duty: float = 0.2,
                 worker_ttl_s: Optional[float] = None,
                 deli_devices: Optional[int] = None):
        self.shared_dir = shared_dir
        self.slot = slot
        self.owner = owner or slot
        self.n_partitions = int(n_partitions)
        self.deli_impl = deli_impl or os.environ.get("FLUID_DELI", "scalar")
        if self.deli_impl not in DELI_IMPLS:
            raise ValueError(
                f"deli_impl {self.deli_impl!r} not in {DELI_IMPLS}"
            )
        # Multi-device deli per partition: every owned partition's
        # kernel role shards its pool over the same process-wide
        # N-device mesh — "one partition = one worker process" and
        # "one doc slab = one device" compose, they don't compete.
        self.deli_devices = (
            int(deli_devices) if deli_devices is not None else None
        )
        if self.deli_devices is not None and self.deli_devices > 1 \
                and self.deli_impl != "kernel":
            raise ValueError(
                f"deli_devices={self.deli_devices} needs "
                f"deli_impl='kernel'; got {self.deli_impl!r}"
            )
        self.log_format = default_log_format(log_format)
        self.ttl_s = ttl_s
        self.batch = batch
        self.max_partitions = max_partitions
        self.ckpt_interval_s = ckpt_interval_s
        self.ckpt_bytes = ckpt_bytes
        self.ckpt_duty = ckpt_duty
        # A worker is presumed dead once its heartbeat is older than
        # this (decoupled from the per-partition lease TTL: membership
        # flaps should be rarer than lease renewals).
        self.worker_ttl_s = worker_ttl_s or 3.0 * ttl_s
        self.workers_dir = os.path.join(shared_dir, "workers")
        self.leases_dir = os.path.join(shared_dir, "leases")
        os.makedirs(self.workers_dir, exist_ok=True)
        # Read-only ownership probe (owner_of takes no claim).
        self._probe = LeaseManager(self.leases_dir, self.owner, ttl_s)
        self.roles: Dict[int, Any] = {}
        self.events: List[str] = []
        self._hb_t = 0.0
        self._sweep_t = 0.0
        from ..utils.metrics import get_registry

        self.metrics = get_registry()
        self._m_owned = self.metrics.gauge(
            "shard_partitions_owned", worker=self.slot
        )
        self._m_handoffs = self.metrics.counter(
            "shard_partition_releases_total", worker=self.slot
        )
        self._m_drops = self.metrics.counter(
            "shard_partition_deposed_total", worker=self.slot
        )

    # -------------------------------------------------------- membership

    def _event(self, text: str) -> None:
        self.events.append(text)

    def _hb_path(self) -> str:
        return os.path.join(self.workers_dir, f"{self.slot}.json")

    def heartbeat(self) -> None:
        """Worker-level liveness + the fabric's metrics channel: ONE
        snapshot of this process's registry (per-partition labels keep
        every owned partition's series distinct), so the supervisor
        scrape merges one file per worker with no double counting."""
        tmp = self._hb_path() + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({
                "t": time.time(), "slot": self.slot, "owner": self.owner,
                "pid": os.getpid(),
                "partitions": sorted(
                    p for p, r in self.roles.items() if r.fence is not None
                ),
                "metrics": self.metrics.snapshot(),
            }, f)
        os.replace(tmp, self._hb_path())
        self._hb_t = time.time()

    def alive_workers(self, now: Optional[float] = None) -> int:
        """Workers with a fresh heartbeat (self always counts)."""
        now = time.time() if now is None else now
        alive = 0
        saw_self = False
        try:
            names = os.listdir(self.workers_dir)
        except OSError:
            names = []
        for fn in names:
            if not fn.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.workers_dir, fn)) as f:
                    hb = json.load(f)
            except (OSError, ValueError):
                continue
            if now - float(hb.get("t", 0)) <= self.worker_ttl_s:
                alive += 1
                if fn == f"{self.slot}.json":
                    saw_self = True
        return alive if saw_self else alive + 1

    def target_partitions(self) -> int:
        """This worker's fair share of the partition space."""
        t = math.ceil(self.n_partitions / max(1, self.alive_workers()))
        if self.max_partitions is not None:
            t = min(t, self.max_partitions)
        return t

    # ------------------------------------------------------- role plumbing

    def _make_role(self, partition: int):
        cls = partitioned_role_class(
            resolve_role_class("deli", self.deli_impl), partition
        )
        kw = {}
        if self.deli_devices is not None and self.deli_devices > 1:
            kw["deli_devices"] = self.deli_devices
        role = cls(
            self.shared_dir, self.owner, ttl_s=self.ttl_s,
            batch=self.batch, ckpt_interval_s=self.ckpt_interval_s,
            ckpt_bytes=self.ckpt_bytes, log_format=self.log_format,
            ckpt_duty=self.ckpt_duty, **kw,
        )
        # The WORKER heartbeat (whole-registry snapshot, throttled) is
        # the fabric's liveness/metrics channel; per-partition role
        # heartbeats are debugging surface only, so throttle their
        # per-step registry-snapshot writes to the same cadence.
        role.hb_interval_s = self.ttl_s / 3
        return role

    def _release(self, partition: int, why: str) -> None:
        """Graceful fenced handoff: final checkpoint under our (still
        valid) fence, then release with expires=0 — the successor's
        next sweep takes over immediately, restores this checkpoint,
        and its recovery scan replays any durable gap silently."""
        role = self.roles.pop(partition, None)
        if role is None:
            return
        if role.fence is not None:
            try:
                role.checkpoint()
            except (FencedError, OSError):
                pass  # a successor already holds the fence: its state wins
            role.leases.release(role.name)
            # Count only REAL handoffs: dropping a role instance that
            # never acquired its lease released nothing.
            self._m_handoffs.inc()
        self._event(f"released p{partition} ({why})")

    def sweep(self) -> None:
        """One balance pass: shed surplus, prune lost races, acquire
        toward target."""
        target = self.target_partitions()
        # Shed surplus (highest partition first: deterministic, so two
        # overfull workers don't thrash the same partition).
        while len(self.roles) > target:
            self._release(max(self.roles), "rebalance")
        # Prune instances that never acquired while a live foreign
        # owner holds the lease (we lost the race).
        for p, role in list(self.roles.items()):
            if role.fence is None:
                owner = self._probe.owner_of(partition_lease_name(p))
                if owner is not None and owner != self.owner:
                    self.roles.pop(p)
        # Acquire free/expired partitions up to target, scanning from a
        # slot-dependent start so peers spread instead of colliding.
        if len(self.roles) < target:
            # crc32, not hash(): per-process salt would make the scan
            # start differ between a worker and its restarted self.
            start = zlib.crc32(self.slot.encode()) % self.n_partitions
            for i in range(self.n_partitions):
                if len(self.roles) >= target:
                    break
                p = (start + i) % self.n_partitions
                if p in self.roles:
                    continue
                owner = self._probe.owner_of(partition_lease_name(p))
                if owner is None or owner == self.owner:
                    self.roles[p] = self._make_role(p)
        self._m_owned.set(len(self.roles))
        self._sweep_t = time.time()

    # -------------------------------------------------------------- pump

    def step(self) -> int:
        """One fabric quantum: pump every owned partition once, then
        (throttled) heartbeat + rebalance sweep. Returns records
        moved. A deposed/fenced partition drops OUT of this worker —
        never the worker itself: the other partitions it owns must
        keep sequencing (contrast `serve_role`, where the process IS
        the partition)."""
        moved = 0
        for p, role in list(self.roles.items()):
            try:
                moved += role.step(idle_sleep=0)
            except SystemExit as exc:
                self.roles.pop(p, None)
                self._m_drops.inc()
                self._event(f"dropped p{p} (exit={exc.code})")
            except FencedError as exc:
                self.roles.pop(p, None)
                self._m_drops.inc()
                self._event(f"dropped p{p} (fenced: {exc})")
        now = time.time()
        if now - self._sweep_t > self.ttl_s / 2:
            self.sweep()
        if now - self._hb_t > self.ttl_s / 3:
            self.heartbeat()
        return moved

    def stop(self) -> None:
        """Graceful exit: hand every partition off now instead of
        making successors wait out the lease TTL."""
        for p in sorted(self.roles):
            self._release(p, "shutdown")
        try:
            os.remove(self._hb_path())
        except OSError:
            pass


def serve_shard_worker(shared_dir: str, slot: str,
                       owner: Optional[str] = None,
                       idle_sleep: float = 0.01, **kw) -> None:
    """Child-process entry: run one shard worker until killed."""
    w = ShardWorker(shared_dir, slot, owner=owner, **kw)
    w.heartbeat()
    w.sweep()
    # Bare "READY <slot>" when slot IS the owner (the standalone CLI
    # contract tools/partition_worker_main.py keeps); supervised
    # children append their generation owner for the event log.
    banner = f"READY {slot}" + (
        f" {w.owner}" if w.owner != slot else ""
    )
    print(banner, flush=True)
    while True:
        if w.step() == 0:
            time.sleep(idle_sleep)


# ---------------------------------------------------------------------------
# the fabric supervisor
# ---------------------------------------------------------------------------


class ShardFabricSupervisor(ServiceSupervisor):
    """W shard workers as supervised children over N partitions.

    Reuses the `ServiceSupervisor` monitor machinery wholesale (process
    exit + heartbeat staleness, paced respawn, fresh owner identity per
    generation) — a "role" here is a worker SLOT (``shard-w0``…), its
    heartbeat the worker file `ShardWorker.heartbeat` writes. A
    restarted worker re-enters the lease sweep and the fabric
    rebalances around it; per-partition metrics ride the worker
    heartbeats and merge at `collect_metrics` exactly like the classic
    farm's role metrics."""

    def __init__(self, shared_dir: str, n_workers: int = 2,
                 n_partitions: int = 4,
                 max_partitions: Optional[int] = None,
                 worker_ttl_s: Optional[float] = None, **kw):
        if n_workers < 1:
            raise ValueError(f"n_workers must be >= 1: {n_workers}")
        self.n_partitions = int(n_partitions)
        self.max_partitions = max_partitions
        self.worker_ttl_s = worker_ttl_s
        roles = tuple(f"shard-w{i}" for i in range(n_workers))
        super().__init__(shared_dir, roles=roles, **kw)
        os.makedirs(os.path.join(shared_dir, "workers"), exist_ok=True)

    def _child_cmd(self, role: str, owner: str) -> List[str]:
        cmd = [self.python, "-c",
               "from fluidframework_tpu.server.shard_fabric import main; "
               "main()",
               "--dir", self.shared_dir, "--slot", role,
               "--owner", owner,
               "--partitions", str(self.n_partitions),
               "--ttl", str(self.ttl_s), "--batch", str(self.batch),
               "--impl", self.deli_impl,
               "--log-format", self.log_format,
               "--ckpt-interval", str(self.ckpt_interval_s),
               "--ckpt-bytes", str(self.ckpt_bytes),
               "--ckpt-duty", str(self.ckpt_duty)]
        if self.max_partitions is not None:
            cmd += ["--max-partitions", str(self.max_partitions)]
        if self.worker_ttl_s is not None:
            cmd += ["--worker-ttl", str(self.worker_ttl_s)]
        if self.deli_devices is not None:
            cmd += ["--deli-devices", str(self.deli_devices)]
        return cmd

    def _hb_file(self, role: str) -> str:
        return os.path.join(self.shared_dir, "workers", f"{role}.json")

    def partition_owners(self) -> Dict[str, str]:
        """Live {``deli-p{k}``: owner} — the operator's ownership view
        (`queue.lease_table` over the fabric's lease directory)."""
        return lease_table(os.path.join(self.shared_dir, "leases"))

    def health(self) -> Dict[str, Any]:
        h = super().health()
        owners = self.partition_owners()
        h["n_partitions"] = self.n_partitions
        h["partition_owners"] = owners
        # Degraded until every partition has a live owner (boot,
        # takeover windows): unowned partitions buffer, not lose, but
        # an operator should see the gap.
        if len(owners) < self.n_partitions:
            h["status"] = "degraded"
        return h

    def collect_metrics(self):
        reg = super().collect_metrics()
        owners = self.partition_owners()
        reg.gauge("shard_partitions_total").set(self.n_partitions)
        reg.gauge("shard_partitions_owned_live").set(len(owners))
        return reg


# ---------------------------------------------------------------------------
# child entry
# ---------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)

    def _take(flag: str, default: Optional[str] = None) -> Optional[str]:
        if flag in args:
            i = args.index(flag)
            val = args[i + 1]
            del args[i:i + 2]
            return val
        return default

    shared_dir = _take("--dir")
    slot = _take("--slot")
    owner = _take("--owner")
    n_partitions = int(_take("--partitions", "4"))
    ttl = float(_take("--ttl", "1.0"))
    batch = int(_take("--batch", "512"))
    impl = _take("--impl") or os.environ.get("FLUID_DELI", "scalar")
    log_format = _take("--log-format")
    ckpt_interval = float(_take("--ckpt-interval", "0.25"))
    ckpt_bytes = int(_take("--ckpt-bytes", str(256 * 1024)))
    ckpt_duty = float(_take("--ckpt-duty", "0.2"))
    max_p = _take("--max-partitions")
    worker_ttl = _take("--worker-ttl")
    devices_s = _take("--deli-devices")
    if (shared_dir is None or slot is None or args
            or impl not in DELI_IMPLS
            or (log_format is not None and log_format not in LOG_FORMATS)
            or (devices_s is not None and not devices_s.isdigit())):
        print(
            "usage: python -m fluidframework_tpu.server.shard_fabric "
            "--dir D --slot S [--owner O] [--partitions N] [--ttl S] "
            "[--batch N] [--impl scalar|kernel] "
            "[--log-format json|columnar] [--max-partitions K] "
            "[--worker-ttl S] [--deli-devices N] [--ckpt-interval S] "
            "[--ckpt-bytes N] [--ckpt-duty F]",
            file=sys.stderr,
        )
        raise SystemExit(2)
    serve_shard_worker(
        shared_dir, slot, owner=owner, n_partitions=n_partitions,
        deli_impl=impl, log_format=log_format, ttl_s=ttl, batch=batch,
        max_partitions=int(max_p) if max_p else None,
        ckpt_interval_s=ckpt_interval, ckpt_bytes=ckpt_bytes,
        ckpt_duty=ckpt_duty,
        worker_ttl_s=float(worker_ttl) if worker_ttl else None,
        deli_devices=int(devices_s) if devices_s else None,
    )


if __name__ == "__main__":
    main()
