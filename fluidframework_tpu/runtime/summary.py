"""Summary tree: the checkpoint data model.

Mirrors the reference's `ISummaryTree`/`ISummaryBlob` protocol types
(common/lib/protocol-definitions/src/summary.ts) and the
`SummaryTreeBuilder` helper (packages/runtime/runtime-utils/src/
summaryUtils.ts). A summary is a git-like tree: internal nodes are
trees, leaves are blobs (str/bytes/JSON-able). `flatten()` yields the
path → blob mapping `ChannelStorage` reads; `to_json`/`from_json` give
a storable wire form (the role the git tree encoding plays for
gitrest, server/gitrest).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, Tuple, Union


@dataclass
class SummaryBlob:
    content: Union[str, bytes]


@dataclass
class SummaryTree:
    entries: Dict[str, Union["SummaryTree", SummaryBlob]] = field(default_factory=dict)

    def add_blob(self, key: str, content: Union[str, bytes]) -> "SummaryTree":
        self.entries[key] = SummaryBlob(content)
        return self

    def add_tree(self, key: str, tree: "SummaryTree") -> "SummaryTree":
        self.entries[key] = tree
        return self

    def get_tree(self, key: str) -> "SummaryTree":
        node = self.entries[key]
        assert isinstance(node, SummaryTree), f"{key} is a blob"
        return node

    def get_blob(self, key: str) -> Union[str, bytes]:
        node = self.entries[key]
        assert isinstance(node, SummaryBlob), f"{key} is a tree"
        return node.content

    # ------------------------------------------------------------ walking

    def flatten(self, prefix: str = "") -> Dict[str, Union[str, bytes]]:
        """Path → blob content for every leaf (the IChannelStorageService
        read view, channel.ts:201)."""
        out: Dict[str, Union[str, bytes]] = {}
        for key, node in self.entries.items():
            path = f"{prefix}{key}"
            if isinstance(node, SummaryBlob):
                out[path] = node.content
            else:
                out.update(node.flatten(path + "/"))
        return out

    def walk(self) -> Iterator[Tuple[str, SummaryBlob]]:
        yield from self.flatten().items()

    def stats(self) -> Tuple[int, int]:
        """(tree_nodes, blob_nodes) — reference ISummaryStats."""
        trees, blobs = 1, 0
        for node in self.entries.values():
            if isinstance(node, SummaryBlob):
                blobs += 1
            else:
                t, b = node.stats()
                trees += t
                blobs += b
        return trees, blobs

    # ---------------------------------------------------------- wire form

    def to_json(self) -> str:
        def enc(node):
            if isinstance(node, SummaryBlob):
                if isinstance(node.content, bytes):
                    return {"type": "blob", "encoding": "latin1",
                            "content": node.content.decode("latin1")}
                return {"type": "blob", "content": node.content}
            return {
                "type": "tree",
                "entries": {k: enc(v) for k, v in node.entries.items()},
            }

        return json.dumps(enc(self))

    @classmethod
    def from_json(cls, data: str) -> "SummaryTree":
        def dec(obj):
            if obj["type"] == "blob":
                if obj.get("encoding") == "latin1":
                    return SummaryBlob(obj["content"].encode("latin1"))
                return SummaryBlob(obj["content"])
            t = cls()
            t.entries = {k: dec(v) for k, v in obj["entries"].items()}
            return t

        return dec(json.loads(data))


class SummaryTreeBuilder:
    """Fluent builder (reference SummaryTreeBuilder, summaryUtils.ts)."""

    def __init__(self):
        self._tree = SummaryTree()

    def add_blob(self, key: str, content: Union[str, bytes]) -> "SummaryTreeBuilder":
        self._tree.add_blob(key, content)
        return self

    def add_json_blob(self, key: str, value: Any) -> "SummaryTreeBuilder":
        self._tree.add_blob(key, json.dumps(value))
        return self

    def add_tree(self, key: str, tree: SummaryTree) -> "SummaryTreeBuilder":
        self._tree.add_tree(key, tree)
        return self

    @property
    def summary(self) -> SummaryTree:
        return self._tree


class SummarizerNodeCache:
    """Incremental-summary dirty tracking (the reference's
    summarizerNode subsystem, container-runtime/src/summary/
    summarizerNode/): the summarizer holds this across summaries; a
    channel whose last-change sequence number is unchanged since the
    previous summary REUSES its serialized subtree instead of
    re-running summarizeCore. `reused`/`reserialized` count the last
    summarize pass (observability + tests)."""

    def __init__(self):
        # (datastore_id, channel_id) -> (change_seq, subtree)
        self.entries: Dict[Tuple[str, str], Tuple[int, "SummaryTree"]] = {}
        self.reused = 0
        self.reserialized = 0

    def begin_pass(self) -> None:
        self.reused = 0
        self.reserialized = 0

    def lookup(self, key, change_seq):
        hit = self.entries.get(key)
        if hit is not None and hit[0] == change_seq:
            self.reused += 1
            return hit[1]
        return None

    def store(self, key, change_seq, subtree) -> None:
        self.reserialized += 1
        self.entries[key] = (change_seq, subtree)
