"""SharedObject: the abstract DDS base class.

Mirrors `SharedObjectCore`/`SharedObject` (reference
packages/dds/shared-object-base/src/sharedObject.ts:42,583): attach/load
lifecycle, local-op submission, inbound routing to `process_core`, and
the summarize hooks. Concrete DDSes (map, sequence, matrix, ...)
subclass this and plug in behind the channel seam.

Lifecycle states (reference AttachState): a channel starts *detached*
(`initialize_local`), may accumulate local state, then *connects* to a
delta stream (`connect`) or is *loaded* from a summary (`load`). Ops
submitted while detached are applied locally only; on connect the DDS
keeps its state and starts submitting.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ..protocol.messages import SequencedMessage
from ..utils.events import EventEmitter
from .channel import ChannelAttributes, ChannelServices, ChannelStorage


class SharedObject(EventEmitter):
    """Abstract DDS base (reference SharedObjectCore, sharedObject.ts:42)."""

    def __init__(self, channel_id: str, runtime: Any, attributes: ChannelAttributes):
        super().__init__()
        self.id = channel_id
        self.runtime = runtime
        self.attributes = attributes
        self.services: Optional[ChannelServices] = None

    # ---------------------------------------------------------- lifecycle

    @property
    def handle(self) -> dict:
        """Serialized reference to this channel (the IFluidHandle role;
        GC edges are discovered by scanning summaries for these)."""
        from .gc import make_handle

        return make_handle(f"/{self.runtime.id}/{self.id}")

    @property
    def is_attached(self) -> bool:
        return self.services is not None

    def initialize_local(self) -> None:
        """Fresh detached channel (factory create path,
        IChannelFactory.create channel.ts:269)."""
        self.initialize_local_core()

    def load(self, services: ChannelServices) -> None:
        """Rehydrate from a summary then connect (factory load path,
        IChannelFactory.load channel.ts:287 → SharedObjectCore.load
        sharedObject.ts:308)."""
        self.load_core(services.storage)
        self._attach_delta_handler(services)

    def connect(self, services: ChannelServices) -> None:
        """Attach a live delta stream to this channel
        (SharedObjectCore.connect → attachDeltaHandler,
        sharedObject.ts:423,448)."""
        self._attach_delta_handler(services)

    def _attach_delta_handler(self, services: ChannelServices) -> None:
        self.services = services
        services.delta_connection.attach(self)  # self implements DeltaHandler
        self.did_attach()

    # ------------------------------------------------------ outbound path

    def submit_local_message(self, content: Any, local_metadata: Any = None) -> None:
        """Apply-locally-then-submit tail (sharedObject.ts:350
        submitLocalMessage). Detached channels swallow the op — their
        state is captured wholesale by the attach summary."""
        if self.services is not None:
            self.services.delta_connection.submit(content, local_metadata)

    # ------------------------------------------------- inbound (DeltaHandler)

    def process(self, msg: SequencedMessage, local: bool, local_metadata: Any) -> None:
        self.process_core(msg, local, local_metadata)

    def resubmit(self, content: Any, local_metadata: Any) -> None:
        """Reconnect path: re-send a pending op against current state
        (sharedObject.ts:385 reSubmitCore; merge-tree overrides to
        rebase, client.ts:917). Default: submit unchanged."""
        self.submit_local_message(content, local_metadata)

    def rollback(self, content: Any, local_metadata: Any) -> None:
        """Undo a just-applied local op (orderSequentially abort path,
        containerRuntime.ts:1996). DDSes that support it override."""
        raise NotImplementedError(f"{type(self).__name__} cannot roll back")

    def apply_stashed_op(self, content: Any) -> Any:
        """Apply an op recovered from a closed session's pending state
        (IDeltaHandler.applyStashedOp channel.ts:153); returns the
        local metadata to track it as pending."""
        raise NotImplementedError(f"{type(self).__name__} cannot apply stashed ops")

    # ---------------------------------------------------------- summaries

    def get_attach_summary(self):
        """Summary of current state for attach/summarize (reference
        SharedObject.getAttachSummary → summarizeCore,
        sharedObject.ts:583,722). Returns a SummaryTree (runtime.summary)."""
        return self.summarize_core()

    # ------------------------------------------------ subclass obligations

    def initialize_local_core(self) -> None:  # pragma: no cover - trivial
        pass

    def did_attach(self) -> None:  # pragma: no cover - trivial
        pass

    def on_connected(self) -> None:
        """The hosting container went live on a connection: the session
        client id is now known (reference setConnectionState plumbing).
        DDSes that track a collaborating identity override."""
        pass

    def load_core(self, storage: ChannelStorage) -> None:
        raise NotImplementedError

    def process_core(self, msg: SequencedMessage, local: bool, local_metadata: Any) -> None:
        raise NotImplementedError

    def summarize_core(self):
        raise NotImplementedError
