"""Outbound op lifecycle: batch compression, chunking, reassembly.

The reference pipeline (packages/runtime/container-runtime/src/
opLifecycle/): `OpCompressor` (opCompressor.ts:20) compresses a
batch's contents when it exceeds a size threshold — the first message
carries the packed payload, the rest become empty placeholders so
every op keeps its own sequence number; `OpSplitter` (opSplitter.ts:22)
splits any single wire message above the service's op-size cap into
chunk ops reassembled runtime-side (`RemoteMessageProcessor` order:
reassemble chunks → decompress → route). The reference codec is LZ4;
zlib plays that role here (stdlib; same contract, different codec —
the codec name rides the wire so another can be added).

Wire forms (inside DocumentMessage.contents):
- packed batch head: {"packedContents": <b64>, "compression": "zlib"}
- packed batch placeholder: {"placeholder": true}
- chunk: {"chunkedOp": <i>, "total": <T>, "data": <b64 piece>}
"""

from __future__ import annotations

import base64
import json
import zlib
from typing import Any, List, Optional, Tuple

COMPRESSION_ALGO = "zlib"


def _wire_default(obj: Any) -> Any:
    """JSON fallback for in-proc payloads: merge-tree op dataclasses
    serialize to their wire-dict form (protocol.mergetree_ops), which
    every DDS's process path already accepts — so a decompressed op
    arriving as a dict routes identically to the in-proc object."""
    from ..protocol.mergetree_ops import MergeTreeOp, op_to_json

    if isinstance(obj, MergeTreeOp):
        return op_to_json(obj)
    return str(obj)


def _dumps(value: Any) -> str:
    return json.dumps(value, default=_wire_default)


def approx_wire_size(obj: Any, budget: int) -> int:
    """Conservative (over-estimating) wire-size bound with early exit:
    returns a value > `budget` as soon as the bound crosses it, or -1
    for payload types it cannot bound (caller falls back to exact
    serialization). Lets the outbox skip per-op json for the common
    small-batch case — the sizes only gate compression/chunking, and
    both thresholds are orders of magnitude above typical ops."""
    # Exact-type dispatch (hot path: called per value per flush);
    # subclasses fall through to -1 = exact serialization, which is
    # always safe.
    t = type(obj)
    if t is str:
        if obj.isascii():
            if obj.isprintable():
                # Printable ASCII escapes only \ and " (2 bytes each).
                return 2 + 2 * len(obj)
            # Control chars render as \u00XX (6 bytes/char).
            return 2 + 6 * len(obj)
        # ensure_ascii renders non-ASCII as \uXXXX (6 bytes/char;
        # surrogate pairs 12, still <= 12*len).
        return 2 + 12 * len(obj)
    if t is int:
        # json renders arbitrary-precision ints in full; only bound
        # the machine-word range.
        if -(1 << 53) < obj < (1 << 53):
            return 24
        return -1
    if obj is None or t is bool:
        return 5
    if t is float:
        return 32
    if t is dict:
        total = 2
        for k, v in obj.items():
            if type(k) is not str:
                return -1
            # Keys bound like any string (control/non-ASCII chars
            # render as \uXXXX) + ': ' separator (2 bytes — json's
            # default separators emit two bytes for ': ' and ', ').
            total += approx_wire_size(k, budget - total) + 2
            s = approx_wire_size(v, budget - total)
            if s < 0:
                return -1
            total += s + 2  # ', ' between items (over-counts the last)
            if total > budget:
                return total
        return total
    if t is list or t is tuple:
        total = 2
        for v in obj:
            s = approx_wire_size(v, budget - total)
            if s < 0:
                return -1
            total += s + 2  # ', ' between items (over-counts the last)
            if total > budget:
                return total
        return total
    return -1


def wire_size(contents: Any) -> int:
    try:
        return len(_dumps(contents))
    except (TypeError, ValueError):
        return 0


def compress_batch(contents_list: List[Any]) -> List[Any]:
    """Pack a batch's contents into its head message (opCompressor.ts:20
    semantics: payload on message 0, placeholders after)."""
    return compress_batch_serialized([_dumps(c) for c in contents_list])


def compress_batch_serialized(dumped: List[str]) -> List[Any]:
    """As compress_batch, over already-serialized contents (the flush
    hot path serializes once and reuses the strings for sizing,
    compression, and the chunking test)."""
    payload = base64.b64encode(
        zlib.compress(("[" + ",".join(dumped) + "]").encode(), 1)
    ).decode()
    packed: List[Any] = [
        {"packedContents": payload, "compression": COMPRESSION_ALGO}
    ]
    packed.extend({"placeholder": True} for _ in dumped[1:])
    return packed


def decompress_batch(head_contents: dict) -> List[Any]:
    algo = head_contents.get("compression")
    if algo != COMPRESSION_ALGO:
        raise ValueError(f"unknown compression {algo!r}")
    raw = zlib.decompress(base64.b64decode(head_contents["packedContents"]))
    return json.loads(raw)


def is_packed_head(contents: Any) -> bool:
    return isinstance(contents, dict) and "packedContents" in contents


def is_placeholder(contents: Any) -> bool:
    return isinstance(contents, dict) and contents.get("placeholder") is True


def split_contents(contents: Any, max_bytes: int) -> Optional[List[dict]]:
    """Split one oversized wire contents into chunk ops
    (opSplitter.ts:22). Returns None if it fits in max_bytes."""
    return split_serialized(_dumps(contents), max_bytes)


def split_serialized(blob: str, max_bytes: int) -> Optional[List[dict]]:
    if len(blob) <= max_bytes:
        return None
    data = base64.b64encode(zlib.compress(blob.encode())).decode()
    piece = max(1, max_bytes // 2)  # b64 pieces, margin for envelope
    pieces = [data[i: i + piece] for i in range(0, len(data), piece)]
    total = len(pieces)
    return [
        {"chunkedOp": i, "total": total, "data": p}
        for i, p in enumerate(pieces)
    ]


def is_chunk(contents: Any) -> bool:
    return isinstance(contents, dict) and "chunkedOp" in contents


class ChunkReassembler:
    """Per-client chunk accumulation (RemoteMessageProcessor /
    opSplitter processRemoteMessage): feed chunks in sequence order;
    the final chunk yields the original contents."""

    def __init__(self):
        self._buffers = {}

    def feed(self, client_id: int, contents: dict) -> Tuple[bool, Any]:
        """Returns (complete, original_contents | None).

        Inconsistent sequences are DROPPED, not raised: a client that
        disconnected mid-stream and restarted (same explicit client id)
        begins a fresh stream at chunk 0 — raising here would crash
        every remote replica's process() on a condition only the sender
        misbehaved on. A fresh chunk 0 discards the stale partial; any
        other gap discards the buffer and ignores the orphan chunk
        (the restarted sender will resubmit from its pending queue)."""
        buf = self._buffers.setdefault(client_id, [])
        if contents["chunkedOp"] != len(buf):
            del self._buffers[client_id]
            if contents["chunkedOp"] != 0:
                return False, None
            buf = self._buffers.setdefault(client_id, [])
        buf.append(contents["data"])
        if len(buf) < contents["total"]:
            return False, None
        del self._buffers[client_id]
        blob = zlib.decompress(base64.b64decode("".join(buf)))
        return True, json.loads(blob)

    def reset(self, client_id: Optional[int] = None) -> None:
        if client_id is None:
            self._buffers.clear()
        else:
            self._buffers.pop(client_id, None)
