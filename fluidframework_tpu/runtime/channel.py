"""The channel seam: the plugin boundary DDSes register behind.

Mirrors the roles of the reference's interface-only package
`@fluidframework/datastore-definitions`:

- `ChannelFactory` — `IChannelFactory.create/load`
  (packages/runtime/datastore-definitions/src/channel.ts:243,269,287):
  how a runtime instantiates a DDS of a given type, fresh or from a
  summary.
- `DeltaConnection` — `IDeltaConnection` (channel.ts:166): the channel's
  window onto the op stream (submit outbound; the runtime drives
  process/resubmit/rollback/applyStashedOp inbound via the handler the
  channel attaches, `IDeltaHandler` channel.ts:119).
- `ChannelStorage` — `IChannelStorageService` (channel.ts:201): read
  access to the channel's subtree of a summary.

The TPU backend plugs in *here*: a DDS whose hot path runs as JAX
kernels registers an ordinary `ChannelFactory`; everything above the
seam is storage/ordering plumbing that never sees device arrays.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Protocol, TYPE_CHECKING

from ..protocol.messages import SequencedMessage

if TYPE_CHECKING:  # pragma: no cover
    from .datastore import DataStoreRuntime
    from .shared_object import SharedObject


@dataclass(frozen=True)
class ChannelAttributes:
    """Identifies the DDS type + format of a stored channel
    (reference IChannelAttributes, channel.ts:217)."""

    type: str
    snapshot_format_version: str = "1"
    package_version: str = "tpu-0.1"


class DeltaHandler(Protocol):
    """What a channel exposes to the runtime for inbound traffic
    (reference IDeltaHandler, channel.ts:119)."""

    def process(self, msg: SequencedMessage, local: bool, local_metadata: Any) -> None: ...
    def resubmit(self, content: Any, local_metadata: Any) -> None: ...
    def rollback(self, content: Any, local_metadata: Any) -> None: ...
    def apply_stashed_op(self, content: Any) -> Any: ...


class DeltaConnection:
    """Channel ↔ datastore-runtime op pipe (reference IDeltaConnection
    channel.ts:166 / ChannelDeltaConnection,
    packages/runtime/datastore/src/channelDeltaConnection.ts)."""

    def __init__(
        self,
        submit_fn: Callable[[Any, Any], None],
        dirty_fn: Optional[Callable[[], None]] = None,
    ):
        self._submit = submit_fn
        self._dirty = dirty_fn
        self.connected = True
        self.handler: Optional[DeltaHandler] = None

    def attach(self, handler: DeltaHandler) -> None:
        self.handler = handler

    def submit(self, content: Any, local_metadata: Any = None) -> None:
        self._submit(content, local_metadata)

    def dirty(self) -> None:
        if self._dirty is not None:
            self._dirty()

    # Runtime-side dispatch (ChannelDeltaConnection.process guards that
    # a handler is attached before ops flow).
    def process(self, msg: SequencedMessage, local: bool, local_metadata: Any) -> None:
        assert self.handler is not None, "channel not attached to delta stream"
        self.handler.process(msg, local, local_metadata)

    def resubmit(self, content: Any, local_metadata: Any) -> None:
        assert self.handler is not None
        self.handler.resubmit(content, local_metadata)

    def rollback(self, content: Any, local_metadata: Any) -> None:
        assert self.handler is not None
        self.handler.rollback(content, local_metadata)

    def apply_stashed_op(self, content: Any) -> Any:
        assert self.handler is not None
        return self.handler.apply_stashed_op(content)


class ChannelStorage:
    """Read view of one channel's summary subtree (reference
    IChannelStorageService channel.ts:201). Blobs are a flat
    path → bytes/str mapping; `SummaryTree.flatten()` produces it."""

    def __init__(self, blobs: Optional[Dict[str, Any]] = None):
        self._blobs = dict(blobs or {})

    def contains(self, path: str) -> bool:
        return path in self._blobs

    def read(self, path: str) -> Any:
        return self._blobs[path]

    def list(self) -> list:
        return sorted(self._blobs)


@dataclass
class ChannelServices:
    """What a channel needs to go live (reference IChannelServices,
    channel.ts:313): a delta connection and its storage."""

    delta_connection: DeltaConnection
    storage: ChannelStorage = field(default_factory=ChannelStorage)


class ChannelFactory:
    """Base channel factory (reference IChannelFactory, channel.ts:243).

    Subclasses set `type_name` and `channel_class`; `create` makes a
    fresh detached channel, `load` rehydrates one from storage then
    connects it.
    """

    type_name: str = ""
    channel_class: type = None  # type: ignore[assignment]

    @property
    def attributes(self) -> ChannelAttributes:
        return ChannelAttributes(type=self.type_name)

    def create(self, runtime: "DataStoreRuntime", channel_id: str) -> "SharedObject":
        ch = self.channel_class(channel_id, runtime, self.attributes)
        ch.initialize_local()
        return ch

    def load(
        self,
        runtime: "DataStoreRuntime",
        channel_id: str,
        services: ChannelServices,
        attributes: ChannelAttributes,
    ) -> "SharedObject":
        ch = self.channel_class(channel_id, runtime, self.attributes)
        ch.load(services)
        return ch


class ChannelRegistry:
    """type name → factory (reference ISharedObjectRegistry,
    packages/runtime/datastore/src/dataStoreRuntime.ts:104 ctor arg)."""

    def __init__(self, factories: Optional[list] = None):
        self._by_type: Dict[str, ChannelFactory] = {}
        for f in factories or []:
            self.register(f)

    def register(self, factory: ChannelFactory) -> None:
        self._by_type[factory.type_name] = factory

    def get(self, type_name: str) -> ChannelFactory:
        if type_name not in self._by_type:
            raise KeyError(f"no channel factory registered for {type_name!r}")
        return self._by_type[type_name]
