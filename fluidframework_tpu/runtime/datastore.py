"""DataStoreRuntime: hosts a set of channels (DDS instances).

Mirrors `FluidDataStoreRuntime` (reference
packages/runtime/datastore/src/dataStoreRuntime.ts:104): creates
channels through the registry, routes inbound channel ops
(`process` :591 → `ChannelDeltaConnection.process`,
remoteChannelContext.ts:131), forwards outbound channel ops up to the
container runtime, and summarizes per-channel subtrees with channel
`.attributes` metadata blobs.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, Optional

from ..protocol.messages import SequencedMessage
from .channel import (
    ChannelAttributes,
    ChannelRegistry,
    ChannelServices,
    ChannelStorage,
    DeltaConnection,
)
from .shared_object import SharedObject
from .summary import SummaryTree, SummaryTreeBuilder

ATTRIBUTES_BLOB = ".attributes"


class DataStoreRuntime:
    """One datastore's channel host.

    `submit_fn(channel_id, content, local_metadata)` sends an op up to
    the container runtime (FluidDataStoreContext.submitMessage path);
    standalone use (unit tests, single-datastore documents) may wire it
    straight to an ordering-service connection.
    """

    def __init__(
        self,
        datastore_id: str,
        registry: ChannelRegistry,
        submit_fn: Optional[Callable[[str, Any, Any], None]] = None,
    ):
        self.id = datastore_id
        self.registry = registry
        self._submit_fn = submit_fn
        self.channels: Dict[str, SharedObject] = {}
        # Channels loaded from a summary but not yet materialized:
        # cid -> (type_name, SummaryTree). Ops for them queue in
        # _pending_channel_ops until first access (the
        # RemoteChannelContext lazy-load contract,
        # remoteChannelContext.ts:39,131 / snapshotV1.ts:31-37): a
        # container boots and catches up touching only channel
        # HEADERS-worth of work; bodies parse on first read.
        self._unrealized: Dict[str, tuple] = {}
        self._pending_channel_ops: Dict[str, list] = {}
        self._local_metadata: Dict[str, Any] = {}
        self.connected = False
        # Back-reference to the hosting container runtime (None when
        # standalone); set by ContainerRuntime.create_datastore.
        self.container = None
        # GC root flag (reference: root/aliased datastores are GC roots).
        self.is_root = True

    @property
    def handle(self) -> dict:
        """Serialized reference to this datastore (GC edge)."""
        from .gc import make_handle

        return make_handle(f"/{self.id}")

    @property
    def client_id(self) -> Optional[int]:
        """The session client id once the container connects (reference
        IFluidDataStoreRuntime.clientId)."""
        return self.container.client_id if self.container is not None else None

    # -------------------------------------------------------- channel mgmt

    def create_channel(self, channel_id: str, type_name: str) -> SharedObject:
        """Create a fresh detached channel (dataStoreRuntime.ts:253
        createChannel)."""
        if channel_id in self.channels:
            raise KeyError(f"channel {channel_id!r} exists")
        factory = self.registry.get(type_name)
        ch = factory.create(self, channel_id)
        self.channels[channel_id] = ch
        return ch

    def get_channel(self, channel_id: str) -> SharedObject:
        if channel_id not in self.channels and channel_id in self._unrealized:
            self._realize(channel_id)
        return self.channels[channel_id]

    def has_channel(self, channel_id: str) -> bool:
        return channel_id in self.channels or channel_id in self._unrealized

    @property
    def realized_channels(self) -> list:
        """Materialized channel ids (unrealized ones queue their ops)."""
        return sorted(self.channels)

    def _realize(self, channel_id: str) -> None:
        """Materialize a lazily-loaded channel and replay its queued
        ops (RemoteChannelContext.getChannel → load + pending apply,
        remoteChannelContext.ts:131)."""
        tname, node = self._unrealized.pop(channel_id)
        storage = ChannelStorage(
            {
                k: v
                for k, v in node.flatten().items()
                if k != ATTRIBUTES_BLOB
            }
        )
        services = ChannelServices(self._connection_for(channel_id), storage)
        factory = self.registry.get(tname)
        ch = factory.load(
            self, channel_id, services, ChannelAttributes(type=tname)
        )
        self.channels[channel_id] = ch
        if self.client_id is not None:
            ch.on_connected()
        for msg, local, md in self._pending_channel_ops.pop(channel_id, []):
            ch.services.delta_connection.process(msg, local, md)

    def _connection_for(self, channel_id: str) -> DeltaConnection:
        return DeltaConnection(
            submit_fn=lambda content, md: self._submit_channel_op(
                channel_id, content, md
            )
        )

    def attach_channel(self, channel: SharedObject) -> None:
        """Bind a detached channel to the op stream
        (dataStoreRuntime.ts bindChannel)."""
        channel.connect(ChannelServices(self._connection_for(channel.id)))

    def attach_all(self) -> None:
        self.connected = True
        for ch in self.channels.values():
            if not ch.is_attached:
                self.attach_channel(ch)
            if self.client_id is not None:
                ch.on_connected()

    # ----------------------------------------------------------- outbound

    def _submit_channel_op(self, channel_id: str, content: Any, md: Any) -> None:
        if self._submit_fn is None:
            raise RuntimeError("datastore runtime has no submit path")
        self._submit_fn(channel_id, content, md)

    # ------------------------------------------------------------ inbound

    def process(self, channel_id: str, msg: SequencedMessage, local: bool,
                local_metadata: Any) -> None:
        """Route one sequenced channel op (dataStoreRuntime.ts:591
        process → channel delta handler). Ops for unrealized channels
        queue until first access — catch-up never forces a body parse
        (remoteChannelContext.ts:131)."""
        if channel_id not in self.channels and channel_id in self._unrealized:
            self._pending_channel_ops.setdefault(channel_id, []).append(
                (msg, local, local_metadata)
            )
            return
        ch = self.channels[channel_id]
        assert ch.services is not None, f"channel {channel_id} not attached"
        ch.services.delta_connection.process(msg, local, local_metadata)

    def resubmit(self, channel_id: str, content: Any, local_metadata: Any) -> None:
        ch = self.get_channel(channel_id)
        assert ch.services is not None
        ch.services.delta_connection.resubmit(content, local_metadata)

    def rollback(self, channel_id: str, content: Any, local_metadata: Any) -> None:
        ch = self.get_channel(channel_id)
        assert ch.services is not None
        ch.services.delta_connection.rollback(content, local_metadata)

    def apply_stashed_op(self, channel_id: str, content: Any) -> Any:
        ch = self.get_channel(channel_id)
        assert ch.services is not None
        return ch.services.delta_connection.apply_stashed_op(content)

    # ---------------------------------------------------------- summaries

    def summarize(self, cache=None) -> SummaryTree:
        """Per-channel subtrees + attributes blobs (the shape
        FluidDataStoreRuntime.summarize produces from channel
        summarizeCore outputs). With `cache`, channels unchanged since
        the cache's recorded sequence reuse their serialized subtree
        (summarizerNode dirty tracking)."""
        builder = SummaryTreeBuilder()
        change_seqs = (
            self.container.channel_change_seq
            if self.container is not None
            else {}
        )
        # Unrealized channels with queued ops must materialize to
        # summarize; clean ones reuse their loaded subtree verbatim
        # (they cannot have changed — the incremental-summary fast
        # path for never-touched channels).
        for cid in list(self._unrealized):
            if self._pending_channel_ops.get(cid):
                self._realize(cid)
        for cid, (tname, node) in self._unrealized.items():
            builder.add_tree(cid, node)
        for cid, ch in self.channels.items():
            key = (self.id, cid)
            change_seq = change_seqs.get(key, 0)
            if cache is not None:
                hit = cache.lookup(key, change_seq)
                if hit is not None:
                    builder.add_tree(cid, hit)
                    continue
            sub = ch.get_attach_summary()
            sub.add_blob(
                ATTRIBUTES_BLOB,
                json.dumps(
                    {
                        "type": ch.attributes.type,
                        "snapshotFormatVersion": ch.attributes.snapshot_format_version,
                    }
                ),
            )
            if cache is not None:
                cache.store(key, change_seq, sub)
            builder.add_tree(cid, sub)
        return builder.summary

    def load(self, summary: SummaryTree) -> None:
        """Register every channel from a datastore summary subtree
        WITHOUT materializing it (the RemoteChannelContext lazy-load
        path, remoteChannelContext.ts:39): boot reads one attributes
        blob per channel; bodies parse on first `get_channel`, and
        catch-up ops queue per channel until then."""
        for cid, node in summary.entries.items():
            assert isinstance(node, SummaryTree), f"unexpected blob {cid}"
            attrs = json.loads(node.get_blob(ATTRIBUTES_BLOB))
            self._unrealized[cid] = (attrs["type"], node)
        self.connected = True
