"""Runtime layer: the container/datastore orchestration around DDSes.

TPU-native analog of the reference client runtime stack (SURVEY.md §1
L4): `ContainerRuntime` (packages/runtime/container-runtime) routes the
totally ordered op stream to datastores, batches outbound ops, and
replays pending state on reconnect; `DataStoreRuntime`
(packages/runtime/datastore) hosts channels (DDS instances); the
channel seam (packages/runtime/datastore-definitions/src/channel.ts:243)
is the plugin boundary DDSes register behind.
"""

from .channel import (
    ChannelAttributes,
    ChannelFactory,
    ChannelRegistry,
    ChannelServices,
    ChannelStorage,
    DeltaConnection,
)
from .shared_object import SharedObject
from .datastore import DataStoreRuntime
from .container_runtime import ContainerRuntime, Envelope, FlushMode

__all__ = [
    "ChannelAttributes",
    "ChannelFactory",
    "ChannelRegistry",
    "ChannelServices",
    "ChannelStorage",
    "ContainerRuntime",
    "DataStoreRuntime",
    "DeltaConnection",
    "Envelope",
    "FlushMode",
    "SharedObject",
]
