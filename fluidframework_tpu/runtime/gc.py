"""Garbage collection across the container's reference graph.

Mirrors the reference GC subsystem
(packages/runtime/container-runtime/src/gc/garbageCollection.ts:91 and
the standalone packages/runtime/garbage-collector): DDS values may hold
*handles* (serialized references) to datastores/channels; GC marks
everything reachable from root datastores via handles, tracks when a
node first became unreferenced (gcUnreferencedStateTracker.ts), and
sweeps nodes that stay unreferenced past a grace window (the
tombstone → sweep-ready progression).

Handle encoding (the FluidSerializer role,
shared-object-base/src/serializer.ts): a JSON-able marker dict
`{"type": "__fluid_handle__", "url": "/<datastore>[/<channel>]"}`.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Set, Tuple

HANDLE_TYPE = "__fluid_handle__"


def make_handle(url: str) -> dict:
    return {"type": HANDLE_TYPE, "url": url}


def is_handle(value: Any) -> bool:
    return (
        isinstance(value, dict)
        and value.get("type") == HANDLE_TYPE
        and isinstance(value.get("url"), str)
    )


def find_handles(value: Any) -> Iterator[str]:
    """All handle urls embedded in a JSON-able value tree."""
    if is_handle(value):
        yield value["url"]
    elif isinstance(value, dict):
        for v in value.values():
            yield from find_handles(v)
    elif isinstance(value, (list, tuple)):
        for v in value:
            yield from find_handles(v)


def run_garbage_collection(
    graph: Dict[str, List[str]], roots: List[str]
) -> Tuple[Set[str], Set[str]]:
    """Mark phase over an adjacency map (the standalone
    runGarbageCollection, packages/runtime/garbage-collector/src/
    garbageCollector.ts). Returns (referenced, unreferenced)."""
    referenced: Set[str] = set()
    stack = [r for r in roots if r in graph]
    while stack:
        node = stack.pop()
        if node in referenced:
            continue
        referenced.add(node)
        for out in graph.get(node, []):
            if out not in referenced and out in graph:
                stack.append(out)
    return referenced, set(graph) - referenced


class GarbageCollector:
    """Container-level GC driver (GarbageCollector,
    gc/garbageCollection.ts:91).

    Nodes are "/<ds>" and "/<ds>/<channel>". A datastore created with
    root=True is a GC root. A channel is referenced iff its datastore
    is referenced or a handle points at it. `sweep_grace` is measured
    in sequence numbers (the reference uses wall-clock sessionExpiry).

    Coordination model (as in the reference): GC runs as part of
    summarization — the single elected summarizer calls collect(), and
    the resulting unreferenced/tombstone state rides the summary
    (SummaryManager wires this). Replicas therefore agree on GC state
    at every summary boundary; tombstones absorb any straggler ops in
    between. Ad-hoc collect() calls on multiple replicas are *not*
    coordinated — use them only single-replica or in tests.
    """

    def __init__(self, runtime, sweep_grace: int = 0):
        self.runtime = runtime
        self.sweep_grace = sweep_grace
        # node -> seq at which it became unreferenced
        self.unreferenced_since: Dict[str, int] = {}
        # Swept node ids: late traffic addressed to these is dropped
        # (the reference's tombstone stage; full sweep coordination is
        # a GC-op protocol — here every replica makes the same
        # seq-space decision, and tombstones absorb stragglers).
        self.tombstoned: Set[str] = set()

    # ------------------------------------------------------------- graph

    def build_graph(self) -> Tuple[Dict[str, List[str]], List[str]]:
        graph: Dict[str, List[str]] = {}
        roots: List[str] = []
        for did, ds in self.runtime.datastores.items():
            ds_node = f"/{did}"
            # GC must see every channel's outbound handles; realize
            # lazily-loaded ones (the reference's GC likewise walks
            # full gc data on the summarizer cadence).
            for cid in list(getattr(ds, "_unrealized", ())):
                ds.get_channel(cid)
            ch_nodes = [f"/{did}/{cid}" for cid in ds.channels]
            graph[ds_node] = list(ch_nodes)  # a live datastore refs its channels
            if getattr(ds, "is_root", True):
                roots.append(ds_node)
            for cid, ch in ds.channels.items():
                refs: List[str] = []
                for blob in ch.get_attach_summary().flatten().values():
                    if isinstance(blob, str):
                        import json as _json

                        try:
                            refs.extend(find_handles(_json.loads(blob)))
                        except (ValueError, TypeError):
                            pass
                # A reachable channel keeps its datastore alive (a
                # handle to a child implies the parent is loadable).
                graph[f"/{did}/{cid}"] = refs + [ds_node]
        # Attachment blobs are leaf nodes kept alive only by handles
        # (blobManager.ts GC integration).
        blobs = getattr(self.runtime, "blobs", None)
        if blobs is not None:
            for sid in blobs.attached:
                graph[f"/_blobs/{sid}"] = []
        return graph, roots

    # --------------------------------------------------------------- run

    def collect(self) -> Tuple[Set[str], Set[str]]:
        """Mark + unreferenced-state tracking. Returns
        (referenced, unreferenced) node sets."""
        graph, roots = self.build_graph()
        referenced, unreferenced = run_garbage_collection(graph, roots)
        now = self.runtime.current_seq
        for node in unreferenced:
            self.unreferenced_since.setdefault(node, now)
        for node in referenced:
            self.unreferenced_since.pop(node, None)  # revived
        return referenced, unreferenced

    def sweep(self) -> List[str]:
        """Delete nodes unreferenced for > sweep_grace sequence numbers
        (the sweep-ready phase). Returns deleted node ids."""
        self.collect()
        now = self.runtime.current_seq
        deleted = []
        swept_ds = set()
        for node, since in sorted(self.unreferenced_since.items()):
            if now - since < self.sweep_grace:
                continue
            parts = node.strip("/").split("/")
            blobs = getattr(self.runtime, "blobs", None)
            if (
                parts[0] == "_blobs"
                and blobs is not None
                and len(parts) == 2
                and parts[1] in blobs.attached
            ):
                blobs.delete(parts[1])
                deleted.append(node)
                continue
            if len(parts) == 1:
                if self.runtime.datastores.pop(parts[0], None) is not None:
                    swept_ds.add(parts[0])
                    deleted.append(node)
            else:
                if parts[0] in swept_ds:
                    deleted.append(node)  # went down with its datastore
                    continue
                ds = self.runtime.datastores.get(parts[0])
                if ds is not None and (
                    ds.channels.pop(parts[1], None) is not None
                    or ds._unrealized.pop(parts[1], None) is not None
                ):
                    deleted.append(node)
        for node in deleted:
            self.unreferenced_since.pop(node, None)
        self.tombstoned.update(deleted)
        return deleted

    # ----------------------------------------------------------- summary

    def state(self) -> dict:
        return {
            "unreferencedSince": dict(self.unreferenced_since),
            "tombstoned": sorted(self.tombstoned),
        }

    def load_state(self, data: dict) -> None:
        self.unreferenced_since = dict(data.get("unreferencedSince", {}))
        self.tombstoned = set(data.get("tombstoned", []))
