"""Attachment blobs: out-of-band binary payloads with GC-tracked
handles (node namespace /_blobs/<id>, the reference blobManagerBasePath).

Reference `BlobManager`
(packages/runtime/container-runtime/src/blobManager.ts:149): large
binary content never rides the op stream — the client uploads the
blob to storage, gets a storage id, announces it with a BlobAttach op
(so every replica learns the id and the summarizer records it), and
hands out a handle (`/blobs/<id>`) that DDS values can embed. GC
treats blob nodes like any other node: unreferenced blobs age and are
swept (gc integration via GarbageCollector.build_graph).

The storage side is the driver's blob surface (`upload_blob` /
`read_blob` — LocalServer backs it with the content-addressed store,
server/castore.py, the gitrest role).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .gc import make_handle

BLOB_ATTACH = "blobAttach"


class BlobManager:
    def __init__(self, runtime, driver, doc_id_fn):
        self.runtime = runtime
        self.driver = driver
        self._doc_id_fn = doc_id_fn  # container's doc id (set at attach)
        # storage id -> True once its BlobAttach op processed (or
        # locally created and pending).
        self.attached: Dict[str, bool] = {}

    # ------------------------------------------------------------ create

    def create_blob(self, data: bytes) -> dict:
        """Upload + announce + return a handle (createBlob,
        blobManager.ts:149). The upload happens out-of-band (storage
        round trip); only the tiny id ever enters the op stream."""
        doc_id = self._doc_id_fn()
        if doc_id is None:
            raise RuntimeError("attach the container before creating blobs")
        storage_id = self.driver.upload_blob(doc_id, data)
        self.attached[storage_id] = True
        self.runtime._submit_op(
            _blob_envelope({"type": BLOB_ATTACH, "id": storage_id}), None
        )
        return make_handle(f"/_blobs/{storage_id}")

    # ------------------------------------------------------------- fetch

    def get_blob(self, handle_or_id) -> bytes:
        sid = handle_or_id
        if isinstance(handle_or_id, dict):
            sid = handle_or_id["url"].rsplit("/", 1)[-1]
        elif isinstance(sid, str) and sid.startswith("/blobs/"):
            sid = sid.rsplit("/", 1)[-1]
        return self.driver.read_blob(self._doc_id_fn(), sid)

    # ----------------------------------------------------------- inbound

    def process_attach(self, contents: dict) -> None:
        self.attached[contents["id"]] = True

    def delete(self, storage_id: str) -> None:
        """GC sweep callback: forget the blob (storage-level deletion
        is the service's business, as in the reference)."""
        self.attached.pop(storage_id, None)

    # ----------------------------------------------------------- summary

    def state(self) -> dict:
        return {"ids": sorted(self.attached)}

    def load_state(self, data: dict) -> None:
        self.attached = {i: True for i in data.get("ids", [])}


def _blob_envelope(contents: dict):
    from .container_runtime import Envelope

    return Envelope(".blobs", None, contents)
