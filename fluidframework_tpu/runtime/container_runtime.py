"""ContainerRuntime: orchestrates datastores over the op stream.

Mirrors the reference `ContainerRuntime`
(packages/runtime/container-runtime/src/containerRuntime.ts:543):

- inbound: `process` (:1813) unwraps op envelopes and routes to the
  addressed datastore/channel, with batch-atomicity buffering
  (ScheduleManagerCore, scheduleManager.ts:99);
- outbound: an `Outbox` (opLifecycle/outbox.ts:40) accumulates local
  ops and flushes them as marked batches (`batch: true/false`
  metadata), in Immediate or TurnBased flush mode;
- `PendingStateManager` (pendingStateManager.ts:75) tracks
  unacknowledged local ops, matches them against the sequenced echo,
  and replays them on reconnect (resubmit through each DDS so
  merge-trees can rebase, client.ts:917);
- `order_sequentially` (:1996) rolls back locally applied ops when the
  callback throws.
"""

from __future__ import annotations

import enum
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..protocol.messages import DocumentMessage, MessageType, NackMessage, SequencedMessage
from ..protocol.quorum import ProtocolOpHandler
from ..utils.events import EventEmitter
from . import op_lifecycle
from .channel import ChannelRegistry
from .datastore import DataStoreRuntime
from .summary import SummaryTree, SummaryTreeBuilder

# The summary wire-format version this runtime writes; `load` reads
# every version from 1 up to it (tests/test_snapshot_compat.py pins
# fixtures produced by earlier rounds).
SUMMARY_FORMAT_VERSION = 2



@dataclass
class Envelope:
    """Op envelope addressing datastore → channel (the nested address
    wrapping of reference submitDataStoreOp, containerRuntime.ts:2779)."""

    datastore: str
    channel: str
    contents: Any


class FlushMode(enum.Enum):
    # reference FlushMode (runtime-definitions): Immediate sends each op
    # in its own batch; TurnBased accumulates until flush().
    IMMEDIATE = "immediate"
    TURN_BASED = "turnBased"


@dataclass
class _PendingMessage:
    """One unacked local op (reference IPendingMessage,
    pendingStateManager.ts)."""

    client_seq: int
    envelope: Envelope
    local_metadata: Any
    batch_meta: Optional[dict] = None
    # Perspective at op creation (the reference stamps refSeq when the
    # message is created, not when the batch flushes).
    ref_seq: int = 0
    # Identity the op was submitted under: after a reconnect the echo
    # arrives carrying the OLD client id, and must still match
    # (pendingStateManager matches on the recorded identity).
    client_id: Optional[int] = None


class ContainerRuntime(EventEmitter):
    """The per-container op orchestrator.

    `connection` is anything with `.submit(DocumentMessage)`,
    `.client_id`, and assignable `.listener` / `.nack_listener`
    (server.local_service._Connection satisfies this; drivers provide
    the same surface).
    """

    def __init__(
        self,
        registry: ChannelRegistry,
        flush_mode: FlushMode = FlushMode.TURN_BASED,
        compression_threshold: Optional[int] = 614400,
        max_op_bytes: int = 700 * 1024,
    ):
        super().__init__()
        self.registry = registry
        self.flush_mode = flush_mode
        # Op lifecycle knobs (IContainerRuntimeOptions compression /
        # chunking): batches over `compression_threshold` wire bytes
        # compress (opCompressor.ts:20; None disables); any single
        # message over `max_op_bytes` splits into chunk ops
        # (opSplitter.ts:22) — kept under the service's 768KB nack cap.
        self.compression_threshold = compression_threshold
        self.max_op_bytes = max_op_bytes
        self._reassembler = op_lifecycle.ChunkReassembler()
        self._unpacked: List[Any] = []
        self.datastores: Dict[str, DataStoreRuntime] = {}
        self.connection = None
        self.client_id: Optional[int] = None
        self.current_seq = 0
        self.min_seq = 0
        self._client_seq = 0
        self._outbox: List[_PendingMessage] = []
        self._pending = deque()  # acked-awaited _PendingMessage FIFO
        self._inbound_batch: List[SequencedMessage] = []
        self._in_batch = False
        self._rollback_log: Optional[List[_PendingMessage]] = None
        self._ever_connected = False
        # Apply-side op-lifecycle stage histograms, bound lazily on the
        # first traced message (utils.metrics registry).
        self._stage_hists: Optional[Dict[str, Any]] = None
        # Protocol state: quorum membership + MSN-committed proposals
        # (the loader's initializeProtocolState role, container.ts:1697).
        self.protocol = ProtocolOpHandler()
        # GC driver (attach_gc); its state rides the summary.
        self.gc = None
        # BlobManager (attach_blob_manager); its state rides the summary.
        self.blobs = None
        # Per-channel last-change sequence numbers (the summarizerNode
        # dirty-tracking input): updated on every routed channel op.
        self.channel_change_seq: Dict[tuple, int] = {}

    def attach_gc(self, sweep_grace: int = 0):
        """Enable garbage collection for this container (the reference
        enables GC via IContainerRuntimeOptions.gcOptions)."""
        from .gc import GarbageCollector

        if self.gc is None:
            self.gc = GarbageCollector(self, sweep_grace=sweep_grace)
        else:
            self.gc.sweep_grace = sweep_grace
        return self.gc

    def attach_blob_manager(self, driver, doc_id_fn):
        """Enable attachment blobs (reference blobManager.ts:149;
        storage rides the driver's blob surface). Re-binding an
        existing manager (e.g. after summary load created it with no
        driver) preserves the attached-blob registry."""
        from .blob_manager import BlobManager

        if self.blobs is None:
            self.blobs = BlobManager(self, driver, doc_id_fn)
        else:
            self.blobs.driver = driver
            self.blobs._doc_id_fn = doc_id_fn
        return self.blobs

    _emit = EventEmitter.emit

    @property
    def is_dirty(self) -> bool:
        """True while local changes are unacked (reference
        ContainerRuntime.isDirty)."""
        return bool(self._pending) or bool(self._outbox)

    # --------------------------------------------------------- datastores

    def create_datastore(self, datastore_id: str, root: bool = True) -> DataStoreRuntime:
        """`root=False` datastores survive only while a handle to them
        (or to one of their channels) is reachable from a root — the
        reference's createDataStore vs createRootDataStore split."""
        if datastore_id in self.datastores:
            raise KeyError(f"datastore {datastore_id!r} exists")
        ds = DataStoreRuntime(
            datastore_id,
            self.registry,
            submit_fn=lambda cid, content, md: self._submit_op(
                Envelope(datastore_id, cid, content), md
            ),
        )
        ds.container = self
        ds.is_root = root
        self.datastores[datastore_id] = ds
        return ds

    def get_datastore(self, datastore_id: str) -> DataStoreRuntime:
        return self.datastores[datastore_id]

    # --------------------------------------------------------- connection

    def connect(self, connection) -> None:
        """Go live on an ordering-service connection: catch up on the
        op gap since our last known seq, attach all datastores'
        channels, and replay pending ops if reconnecting."""
        self.connection = connection
        self._ever_connected = True
        self.client_id = connection.client_id
        # Fresh connection = fresh server-side clientSeq expectation
        # (the sequencer's join resets the per-client counter).
        self._client_seq = 0
        if hasattr(connection, "nack_listener"):
            connection.nack_listener = self._on_nack
        # Transport loss (server/driver-initiated included) must
        # transition the runtime to disconnected — the reference
        # DeltaManager surfaces the transport "disconnect" event to the
        # container (connectionManager.ts:170); without it the runtime
        # would keep a dead connection and report connected=True.
        if hasattr(connection, "disconnect_listener"):
            connection.disconnect_listener = (
                lambda conn=connection: self._on_connection_lost(conn)
            )
        for ds in self.datastores.values():
            ds.attach_all()
        # Delta catch-up BEFORE replaying pending: ops that *did*
        # sequence under the previous connection arrive here carrying
        # the old identity and ack their pending entries, so they are
        # not resubmitted (double-apply). (Container.load
        # attachOpHandler + DeltaManager catch-up, SURVEY.md §3.4.)
        if hasattr(connection, "catch_up"):
            # Catch-up drains through the DeltaScheduler: a long
            # offline gap can mean tens of thousands of ops, and the
            # host thread must get breathing room between time slices
            # (deltaScheduler.ts:25 cooperative yielding).
            from .delta_scheduler import drain_sliced

            drain_sliced(
                connection.catch_up(self.current_seq), self.process,
                yield_hook=getattr(self, "yield_hook", None),
            )
        # Attach the live listener only after catch-up: ops sequenced
        # in between were buffered by the connection and drain, in
        # order, on assignment.
        connection.listener = self.process
        # Replay what's still unacked — both flushed-but-unacked
        # (_pending) and never-flushed (_outbox, whose recorded
        # perspectives are stale) — through each channel's resubmit
        # path (PendingStateManager.replayPendingStates →
        # DDS reSubmitCore; merge-trees rebase, client.ts:917).
        replay = list(self._pending) + list(self._outbox)
        self._pending.clear()
        self._outbox.clear()
        for pm in replay:
            if pm.envelope.datastore is None:
                # Synthetic chunk piece: the final chunk's pending
                # entry owns the original op and re-chunks on flush.
                continue
            if pm.envelope.channel is None:
                self._submit_op(pm.envelope, None)  # attach op: as-is
                continue
            ds = self.datastores[pm.envelope.datastore]
            ds.resubmit(pm.envelope.channel, pm.envelope.contents, pm.local_metadata)
        self.flush()
        self._emit("connected", self.client_id)

    def _on_nack(self, nack: NackMessage) -> None:
        """A nack is connection-fatal (the reference client's response
        to a deli nack, lambda.ts:967, is reconnect + replay): drop off
        the connection, keep every unacked op (including the nacked
        one) in the pending FIFO, and let the host reconnect — at which
        point connect() replays them through each DDS's resubmit path
        with fresh perspectives."""
        self.disconnect()
        self._emit("nack", nack)

    def disconnect(self) -> None:
        """Leave the current connection; unacked ops stay pending for
        replay on the next connect()."""
        conn, self.connection = self.connection, None
        if conn is None:
            return
        if hasattr(conn, "disconnect"):
            try:
                conn.disconnect()
            except Exception:
                pass
        self._emit("disconnected")

    def _on_connection_lost(self, conn) -> None:
        """Transport-initiated disconnect (fault injection, server
        eviction, socket loss). Idempotent with locally initiated
        `disconnect()`: whichever runs first clears `connection`, so
        the event fires exactly once."""
        if self.connection is not conn:
            return  # already detached from this connection
        self.connection = None
        self._emit("disconnected")

    # ----------------------------------------------------------- outbound

    def submit_attach_op(self, datastore_id: str, channel) -> None:
        """Announce a dynamically created channel to the session
        (reference attach ops, dataStoreRuntime bindChannel →
        attachGraph): carries the channel's type + attach summary so
        replicas that booted from an older summary can realize it."""
        self._submit_op(
            Envelope(
                datastore_id,
                None,  # runtime-level op, not routed to a channel
                {
                    "type": "attach",
                    "channel": channel.id,
                    "channelType": channel.attributes.type,
                    "summary": channel.get_attach_summary().to_json(),
                },
            ),
            None,
        )

    def _submit_op(self, envelope: Envelope, local_metadata: Any) -> None:
        if self.connection is None and not self._ever_connected:
            # Detached container: ops were already applied locally;
            # state is captured by the attach summary. (A *disconnected*
            # container keeps queueing — the ops flush on reconnect.)
            return
        pm = _PendingMessage(0, envelope, local_metadata, ref_seq=self.current_seq)
        if self._rollback_log is not None:
            self._rollback_log.append(pm)
        self._outbox.append(pm)
        if self.flush_mode is FlushMode.IMMEDIATE:
            self.flush()

    def flush(self) -> None:
        """Send the accumulated batch (Outbox.flush, outbox.ts:40):
        first op carries {"batch": true}, last {"batch": false};
        singletons carry no batch metadata."""
        if self.connection is None:
            return  # disconnected: outbox drains on reconnect
        batch, self._outbox = self._outbox, []
        if not batch:
            return
        conn = self.connection

        def wire_contents(pm: _PendingMessage) -> Any:
            if pm.envelope.channel is None:  # runtime-level (attach) op
                inner = pm.envelope.contents
            else:
                inner = {
                    "address": pm.envelope.channel,
                    "contents": pm.envelope.contents,
                }
            return {"address": pm.envelope.datastore, "contents": inner}

        # Size each message's wire contents ONCE; sizes drive only
        # compression and chunking, so when a conservative bound
        # clears both thresholds the batch skips serialization
        # entirely (the interactive hot path: tiny ops, huge caps).
        items = [(pm, wire_contents(pm)) for pm in batch]
        limit = self.max_op_bytes
        if self.compression_threshold is not None:
            limit = min(limit, self.compression_threshold)
        bound = 0
        for _, c in items:
            s = op_lifecycle.approx_wire_size(c, limit - bound)
            if s < 0:
                bound = -1
                break
            bound += s
            if bound > limit:
                break
        if 0 <= bound <= limit:
            expanded: List[tuple] = list(items)
            dumped = items = None  # all small: no compress, no chunk
        else:
            dumped = [op_lifecycle._dumps(c) for _, c in items]
            # Compression (opCompressor.ts:20): pack the batch's
            # contents into the head message when the total wire size
            # crosses the threshold; the rest become placeholders so
            # each op keeps its own sequence number.
            if self.compression_threshold is not None:
                total = sum(len(d) for d in dumped)
                if total > self.compression_threshold:
                    packed = op_lifecycle.compress_batch_serialized(dumped)
                    items = [(pm, c) for (pm, _), c in zip(items, packed)]
                    dumped = [op_lifecycle._dumps(c) for _, c in items]
            expanded = []
        # Chunking (opSplitter.ts:22): any single message still over
        # the op-size cap splits into chunk ops. Chunk pieces are
        # synthetic pending entries (datastore None); the FINAL chunk
        # keeps the original pending message so its sequenced echo
        # routes (and, on reconnect, resubmits) the original op.
        for (pm, c), d in zip(items or [], dumped or []):
            chunks = op_lifecycle.split_serialized(d, self.max_op_bytes)
            if chunks is None:
                expanded.append((pm, c))
                continue
            for piece in chunks[:-1]:
                expanded.append(
                    (
                        _PendingMessage(
                            0,
                            Envelope(None, None, {"chunkPiece": True}),
                            None,
                            ref_seq=pm.ref_seq,
                        ),
                        piece,
                    )
                )
            expanded.append((pm, chunks[-1]))
        # Stage the ENTIRE batch as in-flight before submitting any of
        # it: a synchronous nack or transport loss during a submit
        # triggers the reconnect replay, which must see the whole
        # batch in _pending — otherwise the unsent remainder would
        # later go out raw on a new connection, bypassing the DDS
        # resubmit/rebase path and splitting batch atomicity.
        n = len(expanded)
        wire: List[DocumentMessage] = []
        # Op-lifecycle trace origin: the client-driver submit timestamp
        # rides the metadata (key "tr_sub"). Readers that ignore the
        # key see unchanged wire semantics (batch markers still work by
        # key lookup); the deli folds it into the client→stamp latency
        # histogram and the sequenced echo's `traces`.
        sub_ts = time.time()
        for i, (pm, c) in enumerate(expanded):
            meta = {"tr_sub": sub_ts}
            if n > 1:
                if i == 0:
                    meta["batch"] = True
                elif i == n - 1:
                    meta["batch"] = False
            self._client_seq += 1
            pm.client_seq = self._client_seq
            pm.client_id = self.client_id
            pm.batch_meta = meta
            self._pending.append(pm)
            wire.append(
                DocumentMessage(
                    client_seq=pm.client_seq,
                    ref_seq=pm.ref_seq,
                    type=MessageType.OP,
                    contents=c,
                    metadata=meta,
                )
            )
        # Boxcarring (pendingBoxcar.ts): one ingress record for the
        # whole batch when the transport supports it.
        if hasattr(conn, "submit_batch") and len(wire) > 1:
            conn.submit_batch(wire)
            return
        for msg in wire:
            if self.connection is not conn:
                # Connection died (or was replaced by a reconnect
                # ladder) mid-batch: stop — every message of this
                # batch was staged pending, so the reconnect replay
                # owns them all now.
                return
            conn.submit(msg)

    def order_sequentially(self, callback: Callable[[], Any]) -> Any:
        """Run `callback`; if it throws, roll back the ops it produced
        in reverse order (containerRuntime.ts:1996)."""
        if self._rollback_log is not None:
            return callback()  # nested: outermost owns the log
        self._rollback_log = []
        try:
            return callback()
        except BaseException as user_exc:
            log, self._rollback_log = self._rollback_log, None
            # Drop the ops from the outbox first — even if a DDS cannot
            # roll back, a "rolled back" op must never reach the wire.
            log_set = {id(pm) for pm in log}
            self._outbox = [m for m in self._outbox if id(m) not in log_set]
            for pm in reversed(log):
                ds = self.datastores[pm.envelope.datastore]
                try:
                    ds.rollback(pm.envelope.channel, pm.envelope.contents,
                                pm.local_metadata)
                except BaseException as rb_exc:
                    # Local state may now diverge from what peers will
                    # compute: unrecoverable (the reference closes the
                    # container, containerRuntime.ts:1996).
                    self._emit("closed", rb_exc)
                    raise RuntimeError(
                        "rollback failed; container corrupt"
                    ) from user_exc
            raise
        finally:
            self._rollback_log = None

    # ------------------------------------------------------------ inbound

    def process(self, msg: SequencedMessage) -> None:
        """Inbound sequenced message (containerRuntime.ts:1813 process),
        with batch buffering: a batch-start message holds delivery until
        its batch-end arrives, then the whole batch applies back-to-back
        (ScheduleManagerCore batch atomicity, scheduleManager.ts:99)."""
        meta = msg.metadata if isinstance(msg.metadata, dict) else None
        if self._in_batch:
            self._inbound_batch.append(msg)
            if meta is not None and meta.get("batch") is False:
                batch, self._inbound_batch = self._inbound_batch, []
                self._in_batch = False
                for m in batch:
                    self._process_one(m)
            return
        if meta is not None and meta.get("batch") is True:
            self._in_batch = True
            self._inbound_batch = [msg]
            return
        self._process_one(msg)

    def _observe_trace(self, msg: SequencedMessage) -> None:
        """Fold the op-lifecycle trace the ordering pipeline stamped
        (`SequencedMessage.traces`: [(stage, ts), ...]) into the
        apply-side stage histograms. Observational only — the message
        is never mutated, and messages without traces (mock harness,
        journal-decoded replay) cost one falsy check."""
        if self._stage_hists is None:
            from ..utils.metrics import get_registry

            reg = get_registry()
            self._stage_hists = {
                s: reg.histogram("op_stage_ms", stage=s)
                for s in ("broadcast_to_apply", "submit_to_apply")
            }
        tr: Dict[str, float] = {}
        for stage, ts in msg.traces:
            tr.setdefault(stage, ts)
        now = time.time()
        b = tr.get("broadcast")
        if b is not None:
            self._stage_hists["broadcast_to_apply"].observe(
                (now - b) * 1000.0
            )
        s = tr.get("submit")
        if s is not None:
            e2e = (now - s) * 1000.0
            self._stage_hists["submit_to_apply"].observe(e2e)
            # Slow-op flight recorder: an apply whose end-to-end
            # latency crosses the rolling p99 (or fixed threshold)
            # keeps its full span — the exact op behind a p99 spike.
            # Two-phase so the steady state never builds a span dict.
            from ..utils.metrics import get_flight_recorder

            fr = get_flight_recorder()
            if fr.note(e2e):
                fr.add(e2e, {
                    "client": msg.client_id,
                    "clientSeq": msg.client_seq,
                    "seq": msg.sequence_number,
                    "stages": {**tr, "apply": now},
                })

    def _process_one(self, msg: SequencedMessage) -> None:
        if msg.traces:
            self._observe_trace(msg)
        self.current_seq = msg.sequence_number
        if msg.minimum_sequence_number > self.min_seq:
            self.min_seq = msg.minimum_sequence_number
        # Every message advances protocol state: join/leave/propose
        # mutate the quorum, and any MSN advance can commit proposals
        # (the reference routes all messages through ProtocolOpHandler).
        # Plain data ops — the hot path — only move seq/MSN
        # (ProtocolOpHandler.process_data_op owns that invariant).
        if msg.type == MessageType.OP:
            self.protocol.process_data_op(
                msg.sequence_number, msg.minimum_sequence_number
            )
        else:
            self.protocol.process_message(msg)
        if msg.type != MessageType.OP or not isinstance(msg.contents, dict):
            if msg.type in (MessageType.CLIENT_JOIN, MessageType.CLIENT_LEAVE):
                # A departed client's partial chunk stream can never
                # complete; a rejoining client starts a fresh one.
                # Either way the stale buffer must go, or it would leak
                # (leave) or corrupt the new stream (rejoin).
                c = msg.contents
                cid = c.get("clientId") if isinstance(c, dict) else c
                if cid is not None:
                    self._reassembler.reset(cid)
            self._emit("op", msg, False)
            return
        # Local iff it matches the head of the pending FIFO by the
        # identity it was SUBMITTED under (not the current connection's:
        # an op sequenced just before a disconnect echoes with the old
        # client id during catch-up — PendingStateManager matches on
        # the recorded identity, pendingStateManager.ts:75).
        head = self._pending[0] if self._pending else None
        local = (
            head is not None
            and head.client_id == msg.client_id
            and head.client_seq == msg.client_seq
        )
        local_metadata = None
        if local:
            pm = self._pending.popleft()
            local_metadata = pm.local_metadata
        elif self.client_id is not None and msg.client_id == self.client_id:
            raise AssertionError(
                f"own op seq={msg.sequence_number} clientSeq={msg.client_seq} "
                "does not match pending head"
            )
        outer = msg.contents
        # Inbound lifecycle transforms, in RemoteMessageProcessor
        # order: reassemble chunked ops, then unpack compressed
        # batches (placeholders consume the unpacked payloads).
        if op_lifecycle.is_chunk(outer):
            complete, orig = self._reassembler.feed(msg.client_id, outer)
            if not complete:
                self._emit("op", msg, local)
                return
            outer = orig
        if op_lifecycle.is_packed_head(outer):
            self._unpacked = op_lifecycle.decompress_batch(outer)
            outer = self._unpacked.pop(0)
        elif op_lifecycle.is_placeholder(outer):
            outer = self._unpacked.pop(0)
        inner = outer["contents"]
        if isinstance(inner, dict) and inner.get("type") == "attach":
            self._process_attach(outer["address"], inner, local)
            self._emit("op", msg, local)
            return
        if isinstance(inner, dict) and inner.get("type") == "blobAttach":
            # Blob announcement (BlobAttach, blobManager.ts): record
            # the storage id on EVERY replica — the registry must
            # exist even on replicas that never touch blob APIs, or
            # their summaries would forget the blobs.
            if self.blobs is None:
                self.attach_blob_manager(None, lambda: None)
            if not local:
                self.blobs.process_attach(inner)
            self._emit("op", msg, local)
            return
        ds = self.datastores.get(outer["address"])
        if ds is None or not ds.has_channel(inner["address"]):
            node = f"/{outer['address']}" if ds is None else (
                f"/{outer['address']}/{inner['address']}"
            )
            if self.gc is not None and node in self.gc.tombstoned:
                # Straggler op to a swept node: absorbed (tombstone
                # semantics, gc/garbageCollection.md).
                self._emit("gcTombstoneOp", node, msg)
                return
            raise KeyError(f"op addressed to unknown node {node}")
        ds.process(inner["address"], _reshape(msg, inner["contents"]), local, local_metadata)
        self.channel_change_seq[(outer["address"], inner["address"])] = (
            msg.sequence_number
        )
        self._emit("op", msg, local)
        if not self.is_dirty:
            self._emit("saved")

    def submit_system_message(self, type_: MessageType, contents: Any) -> None:
        """Submit a non-op protocol message (summarize, propose, noop)
        on this client's sequence-number stream. These don't enter the
        pending-op FIFO — their sequenced echo carries no datastore
        routing."""
        if self.connection is None:
            raise RuntimeError("not connected")
        self._client_seq += 1
        self.connection.submit(
            DocumentMessage(
                client_seq=self._client_seq,
                ref_seq=self.current_seq,
                type=type_,
                contents=contents,
            )
        )

    def propose(self, key: str, value: Any) -> None:
        """Propose a quorum value (Quorum.propose, quorum.ts:142); it
        commits when the MSN passes the proposal (all clients saw it)."""
        self.submit_system_message(MessageType.PROPOSE, {"key": key, "value": value})

    # ---------------------------------------------------------- summaries

    def _process_attach(self, datastore_id: str, attach: dict, local: bool) -> None:
        """Realize a remotely created channel from its attach op
        (RemoteChannelContext creation, remoteChannelContext.ts:39)."""
        if local:
            return  # we created it
        ds = self.datastores.get(datastore_id)
        if ds is None or ds.has_channel(attach["channel"]):
            return
        from .channel import ChannelAttributes, ChannelServices, ChannelStorage

        factory = self.registry.get(attach["channelType"])
        summary = SummaryTree.from_json(attach["summary"])
        services = ChannelServices(
            ds._connection_for(attach["channel"]),
            ChannelStorage(summary.flatten()),
        )
        ch = factory.load(
            ds, attach["channel"], services,
            ChannelAttributes(type=attach["channelType"]),
        )
        ds.channels[attach["channel"]] = ch
        if ds.client_id is not None:
            ch.on_connected()

    def summarize(self, cache=None) -> SummaryTree:
        """Container summary: one subtree per datastore under
        ".channels", plus runtime metadata (the shape of reference
        ContainerRuntime.summarize / summaryFormat.md). With `cache`
        (a SummarizerNodeCache held by the summarizer), unchanged
        channels reuse their previously serialized subtrees — the
        reference's incremental summarizerNode behavior.

        Refuses while local changes are unacked: pending state (e.g. a
        merge-tree segment at UNASSIGNED_SEQ) is not summarizable — the
        reference's summarizer likewise only runs on a clean replica."""
        if self.connection is not None and self.is_dirty:
            raise RuntimeError(
                "cannot summarize with pending local changes; "
                "process the op stream to quiescence first"
            )
        builder = SummaryTreeBuilder()
        channels = SummaryTreeBuilder()
        for did, ds in self.datastores.items():
            channels.add_tree(did, ds.summarize(cache=cache))
        builder.add_tree(".channels", channels.summary)
        builder.add_json_blob(
            ".metadata",
            {
                # Summary wire-format version (the back-compat
                # contract, reference summaryFormat.md /
                # snapshotV1.ts:30): bumped ONLY with a loader that
                # still reads every older version; pinned fixtures in
                # tests/fixtures are booted by test_snapshot_compat.
                "formatVersion": SUMMARY_FORMAT_VERSION,
                "sequenceNumber": self.current_seq,
                "minimumSequenceNumber": self.min_seq,
                "datastores": {
                    did: {"root": ds.is_root} for did, ds in self.datastores.items()
                },
            },
        )
        # Protocol state (quorum + proposals) rides the summary, as the
        # reference's .protocol tree does (scribeHelper.ts): clients
        # booting from the summary see the same membership/proposals.
        builder.add_json_blob(".protocol", self.protocol.snapshot())
        if self.gc is not None:
            builder.add_json_blob(".gc", self.gc.state())
        if self.blobs is not None:
            builder.add_json_blob(".blobs", self.blobs.state())
        return builder.summary

    def load(self, summary: SummaryTree) -> None:
        """Boot from a summary (Container.load → instantiateRuntime →
        lazy datastore realization, SURVEY.md §3.4 — eager here)."""
        import json as _json

        meta = _json.loads(summary.get_blob(".metadata"))
        ver = meta.get("formatVersion", 1)
        if not 1 <= ver <= SUMMARY_FORMAT_VERSION:
            raise ValueError(
                f"unsupported summary format version {ver} "
                f"(this loader reads 1..{SUMMARY_FORMAT_VERSION})"
            )
        self.current_seq = meta["sequenceNumber"]
        self.min_seq = meta["minimumSequenceNumber"]
        roots = meta.get("datastores", {})
        channels = summary.get_tree(".channels")
        for did, node in channels.entries.items():
            assert isinstance(node, SummaryTree)
            ds = self.create_datastore(
                did, root=roots.get(did, {}).get("root", True)
            )
            ds.load(node)
        if ".protocol" in summary.entries:
            self.protocol = ProtocolOpHandler.from_snapshot(
                _json.loads(summary.get_blob(".protocol"))
            )
        if ".gc" in summary.entries:
            self.attach_gc()
            self.gc.load_state(_json.loads(summary.get_blob(".gc")))
        if ".blobs" in summary.entries:
            # Always realize the registry (a later attach_blob_manager
            # re-binds the driver); dropping it would forget every
            # attached blob on boot.
            self.attach_blob_manager(None, lambda: None)
            self.blobs.load_state(_json.loads(summary.get_blob(".blobs")))


def _reshape(msg: SequencedMessage, inner_contents: Any) -> SequencedMessage:
    """The channel-level view of a sequenced message: same stamps,
    contents narrowed to the channel op (what ChannelDeltaConnection
    hands to SharedObjectCore's delta handler)."""
    return SequencedMessage(
        sequence_number=msg.sequence_number,
        minimum_sequence_number=msg.minimum_sequence_number,
        client_id=msg.client_id,
        client_seq=msg.client_seq,
        ref_seq=msg.ref_seq,
        type=msg.type,
        contents=inner_contents,
        metadata=msg.metadata,
        address=None,
        timestamp=msg.timestamp,
    )
