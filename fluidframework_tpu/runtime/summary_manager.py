"""Client-side summarization: election, heuristics, ack tracking.

Mirrors the reference summarizer subsystem
(packages/runtime/container-runtime/src/summary/):

- `SummarizerElection` — the oldest eligible quorum client summarizes
  (SummarizerClientElection + OrderedClientElection,
  summarizerClientElection.ts); on its departure the next-oldest takes
  over.
- `SummaryCollection` — the op-stream view of summarize/ack/nack
  traffic (summaryCollection.ts:222).
- `SummaryManager` — runs the heuristics (op count since last ack,
  runningSummarizer.ts/summarizerHeuristics.ts) and executes the
  summary: serialize the container, upload to storage, submit the
  summarize op, await the server's ack (scribe, SURVEY.md §3.5).

The reference isolates the summarizer in a hidden second container;
here the elected client summarizes in place — same protocol traffic,
simpler topology (our ContainerRuntime.summarize already refuses
dirty state, which is the property the hidden container guarantees).
"""

from __future__ import annotations

from typing import Any, Optional

from ..protocol.messages import MessageType, SequencedMessage
from ..utils.events import EventEmitter
from .container_runtime import ContainerRuntime


class SummaryCollection(EventEmitter):
    """Observes summarize/summaryAck/summaryNack in the op stream
    (summaryCollection.ts:222)."""

    def __init__(self, runtime: ContainerRuntime):
        super().__init__()
        self.runtime = runtime
        self.last_ack: Optional[dict] = None
        self.last_ack_seq = 0  # seq of the acked summarize op
        runtime.on("op", self._on_op)

    def _on_op(self, msg: SequencedMessage, local: bool) -> None:
        if msg.type == MessageType.SUMMARY_ACK:
            self.last_ack = msg.contents
            self.last_ack_seq = msg.contents["summaryProposal"]["summarySequenceNumber"]
            self.emit("ack", msg.contents)
        elif msg.type == MessageType.SUMMARY_NACK:
            self.emit("nack", msg.contents)


class SummarizerElection:
    """Oldest-client election over the runtime's quorum."""

    def __init__(self, runtime: ContainerRuntime):
        self.runtime = runtime

    @property
    def elected_client_id(self) -> Optional[int]:
        oldest = self.runtime.protocol.quorum.oldest()
        return oldest.client_id if oldest else None

    @property
    def is_elected(self) -> bool:
        return (
            self.runtime.client_id is not None
            and self.elected_client_id == self.runtime.client_id
        )


class SummaryManager:
    """Drives the summarize loop for one container.

    `storage` needs `upload_summary(wire) -> handle`
    (server.lambdas.LocalServer provides it; drivers adapt their
    service's storage API to the same shape).
    """

    def __init__(
        self,
        runtime: ContainerRuntime,
        storage: Any,
        max_ops: int = 100,
    ):
        self.runtime = runtime
        self.storage = storage
        self.max_ops = max_ops
        self.election = SummarizerElection(runtime)
        self.collection = SummaryCollection(runtime)
        self._ops_since_ack = 0
        self._summary_in_flight = False
        # Incremental summaries: unchanged channels reuse serialized
        # subtrees across this manager's summaries (summarizerNode).
        from .summary import SummarizerNodeCache

        self.node_cache = SummarizerNodeCache()
        runtime.on("op", self._count)
        self.collection.on("ack", self._on_ack)
        self.collection.on("nack", self._on_nack)

    def _count(self, msg: SequencedMessage, local: bool) -> None:
        if msg.type == MessageType.OP:
            self._ops_since_ack += 1

    def _on_ack(self, contents: dict) -> None:
        self._ops_since_ack = 0
        self._summary_in_flight = False
        # node_cache survives acks deliberately: entries are keyed by
        # change-seq and stay valid, which is what makes the NEXT
        # summary incremental.

    def _on_nack(self, contents: dict) -> None:
        self._summary_in_flight = False  # retry on next heuristic pass

    @property
    def should_summarize(self) -> bool:
        return (
            self.election.is_elected
            and not self._summary_in_flight
            and self._ops_since_ack >= self.max_ops
            and not self.runtime.is_dirty
        )

    def maybe_summarize(self) -> bool:
        """Run one heuristic pass; returns True if a summary was
        submitted (RunningSummarizer.trySummarize)."""
        if not self.should_summarize:
            return False
        self.summarize_now()
        return True

    def summarize_now(self) -> str:
        """Serialize → upload → submit the summarize op. Returns the
        storage handle (SURVEY.md §3.5 submitSummary). GC runs here —
        the summarizer is the coordination point for GC state (the
        reference runs collectGarbage inside submitSummary)."""
        if self.runtime.gc is not None:
            self.runtime.gc.collect()
        self.node_cache.begin_pass()
        wire = self.runtime.summarize(cache=self.node_cache).to_json()
        handle = self.storage.upload_summary(wire)
        self._summary_in_flight = True
        self.runtime.submit_system_message(
            MessageType.SUMMARIZE,
            {"handle": handle, "head": self.runtime.current_seq},
        )
        return handle
