"""DeltaScheduler + Throttler: cooperative inbound pacing.

- `DeltaScheduler` (reference container-runtime/src/deltaScheduler.ts
  :25): when a large inbound backlog drains (boot catch-up, long
  offline gap), processing is TIME-SLICED — after `slice_ms` of
  continuous processing the scheduler yields control (invoking
  `yield_hook`, the requestIdleCallback/setTimeout turn break in the
  reference) before resuming, so a host UI thread is never starved by
  a 50k-op catch-up.
- `Throttler` (reference container-runtime/src/throttler.ts):
  client-side backpressure formula — delay grows with the number of
  recent attempts inside a sliding window and decays as attempts age
  out. Used for reconnect storms and summarizer retry pacing.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Deque, Iterable, Optional

class DeltaScheduler:
    """Drains a queue-like object (duck-typed: `length` property +
    `process_one()`, e.g. the loader's DeltaQueue) in time slices —
    this module lives in the RUNTIME layer like the reference's
    deltaScheduler.ts, consuming the loader's queue through its
    surface only.

    `drain()` processes queued messages until the queue empties or the
    slice budget is spent; it then calls `yield_hook()` (if any) and
    continues, repeating until empty. Returns the number processed.
    Instrumentation counters mirror the reference's telemetry
    (deltaScheduler.ts tracks processing time across yields).
    """

    def __init__(self, queue, slice_ms: float = 20.0,
                 yield_hook: Optional[Callable[[], None]] = None):
        self.queue = queue
        self.slice_ms = slice_ms
        self.yield_hook = yield_hook
        self.yields = 0
        self.processed = 0
        self.busy_ms = 0.0

    def drain(self) -> int:
        n = 0
        while self.queue.length:
            slice_start = time.perf_counter()
            while self.queue.length:
                if not self.queue.process_one():
                    break
                n += 1
                elapsed = (time.perf_counter() - slice_start) * 1000
                if elapsed >= self.slice_ms:
                    break
            self.busy_ms += (time.perf_counter() - slice_start) * 1000
            if self.queue.length:
                self.yields += 1
                if self.yield_hook is not None:
                    self.yield_hook()
        self.processed += n
        return n


class Throttler:
    """Sliding-window attempt throttle (throttler.ts).

    Each `get_delay()` call records an attempt and returns how long
    the caller should wait before acting: zero while attempts are
    sparse, growing linearly with the number of attempts still inside
    `window_ms`, capped at `max_delay_ms`.
    """

    def __init__(self, max_delay_ms: float = 60_000.0,
                 window_ms: float = 60_000.0,
                 delay_per_attempt_ms: float = 1_000.0,
                 now: Callable[[], float] = time.monotonic):
        self.max_delay_ms = max_delay_ms
        self.window_ms = window_ms
        self.delay_per_attempt_ms = delay_per_attempt_ms
        self._now = now
        self._attempts: Deque[float] = deque()

    def get_delay(self) -> float:
        """Record an attempt; return the wait (ms) before acting."""
        t = self._now() * 1000.0
        cutoff = t - self.window_ms
        while self._attempts and self._attempts[0] < cutoff:
            self._attempts.popleft()
        self._attempts.append(t)
        extra = len(self._attempts) - 1  # first attempt is free
        return min(extra * self.delay_per_attempt_ms, self.max_delay_ms)

    @property
    def attempts_in_window(self) -> int:
        return len(self._attempts)


def drain_sliced(messages: Iterable[Any], handler: Callable[[Any], None],
                 slice_ms: float = 20.0,
                 yield_hook: Optional[Callable[[], None]] = None) -> int:
    """Time-sliced processing of a pre-fetched message list (the
    catch-up path: no queue object needed)."""

    class _ListQueue:
        def __init__(self, items):
            self._items = deque(items)

        @property
        def length(self):
            return len(self._items)

        def process_one(self):
            if not self._items:
                return False
            handler(self._items.popleft())
            return True

    return DeltaScheduler(
        _ListQueue(messages), slice_ms=slice_ms, yield_hook=yield_hook
    ).drain()
