// Content-addressed blob store: the native storage backing.
//
// Plays the role the reference's git storage stack plays natively
// (server/gitrest over nodegit/libgit2, a C++ library): immutable
// blobs addressed by SHA-256, with named refs. Exposed to Python via
// a C ABI consumed with ctypes (fluidframework_tpu/native/__init__.py);
// server/castore.py routes through it when the shared library is
// available and falls back to the pure-Python store otherwise.
//
// SHA-256 is implemented inline from the FIPS 180-4 specification so
// the library has zero dependencies beyond the C++ standard library.

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace {

// ---------------------------------------------------------------- sha256

struct Sha256 {
  uint32_t h[8];
  uint64_t len = 0;
  uint8_t buf[64];
  size_t buf_len = 0;

  Sha256() {
    static const uint32_t init[8] = {
        0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
        0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    std::memcpy(h, init, sizeof(init));
  }

  static uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

  void block(const uint8_t* p) {
    static const uint32_t k[64] = {
        0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
        0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
        0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
        0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
        0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
        0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
        0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
        0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
        0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
        0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
        0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
        0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
        0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
      w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
             (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    for (int i = 16; i < 64; i++) {
      uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; i++) {
      uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + S1 + ch + k[i] + w[i];
      uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void update(const uint8_t* data, size_t n) {
    len += n;
    while (n > 0) {
      size_t take = 64 - buf_len;
      if (take > n) take = n;
      std::memcpy(buf + buf_len, data, take);
      buf_len += take;
      data += take;
      n -= take;
      if (buf_len == 64) {
        block(buf);
        buf_len = 0;
      }
    }
  }

  void hex(char out[65]) {
    uint64_t bits = len * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t zero = 0;
    while (buf_len != 56) update(&zero, 1);
    uint8_t lenb[8];
    for (int i = 0; i < 8; i++) lenb[i] = uint8_t(bits >> (56 - 8 * i));
    len -= 9;  // the padding bytes above bumped len; harmless but tidy
    update(lenb, 8);
    static const char* digits = "0123456789abcdef";
    for (int i = 0; i < 8; i++)
      for (int j = 0; j < 4; j++) {
        uint8_t byte = uint8_t(h[i] >> (24 - 8 * j));
        out[i * 8 + j * 2] = digits[byte >> 4];
        out[i * 8 + j * 2 + 1] = digits[byte & 0xf];
      }
    out[64] = 0;
  }
};

struct Store {
  std::map<std::string, std::vector<uint8_t>> blobs;
  std::map<std::string, std::string> refs;
  std::mutex mu;
  // Durable mode (the gitrest role's persistence): blobs as files
  // under <dir>/objects/<h[0:2]>/<hash>, refs in an append-only
  // fsynced journal <dir>/refs.log (last writer wins on replay).
  std::string dir;  // empty => in-memory only
  int refs_fd = -1;

  ~Store() {
    if (refs_fd >= 0) ::close(refs_fd);
  }

  std::string blob_path(const std::string& key) const {
    return dir + "/objects/" + key.substr(0, 2) + "/" + key;
  }

  bool persist_blob(const std::string& key, const uint8_t* data, size_t n) {
    if (dir.empty()) return true;
    std::string path = blob_path(key);
    struct stat sb;
    if (::stat(path.c_str(), &sb) == 0) return true;  // content-addressed: done
    std::string d1 = dir + "/objects";
    ::mkdir(d1.c_str(), 0777);
    std::string d2 = d1 + "/" + key.substr(0, 2);
    ::mkdir(d2.c_str(), 0777);
    std::string tmp = path + ".tmp." + std::to_string(::getpid());
    int fd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0666);
    if (fd < 0) return false;
    size_t off = 0;
    while (off < n) {
      ssize_t w = ::write(fd, data + off, n - off);
      if (w <= 0) { ::close(fd); ::unlink(tmp.c_str()); return false; }
      off += size_t(w);
    }
    ::fsync(fd);
    ::close(fd);
    if (::rename(tmp.c_str(), path.c_str()) != 0) {
      ::unlink(tmp.c_str());
      return false;
    }
    return true;
  }

  bool load_blob(const std::string& key) {
    if (dir.empty()) return false;
    int fd = ::open(blob_path(key).c_str(), O_RDONLY);
    if (fd < 0) return false;
    std::vector<uint8_t> data;
    uint8_t buf[1 << 16];
    ssize_t r;
    while ((r = ::read(fd, buf, sizeof(buf))) > 0)
      data.insert(data.end(), buf, buf + r);
    ::close(fd);
    blobs.emplace(key, std::move(data));
    return true;
  }

  bool persist_ref(const std::string& name, const std::string& key) {
    if (dir.empty()) return true;
    if (refs_fd < 0) {
      refs_fd = ::open((dir + "/refs.log").c_str(),
                       O_WRONLY | O_CREAT | O_APPEND, 0666);
      if (refs_fd < 0) return false;
    }
    std::string line = name + " " + key + "\n";
    if (::write(refs_fd, line.data(), line.size()) !=
        ssize_t(line.size()))
      return false;
    ::fsync(refs_fd);  // a ref update IS the durability point
    return true;
  }
};

}  // namespace

extern "C" {

void* cas_new() { return new Store(); }

// Durable store rooted at `dir` (created if absent); refs replay from
// the journal, blobs load lazily from the object files.
void* cas_open(const char* dir) {
  auto* st = new Store();
  st->dir = dir;
  ::mkdir(dir, 0777);
  ::mkdir((st->dir + "/objects").c_str(), 0777);
  FILE* f = ::fopen((st->dir + "/refs.log").c_str(), "r");
  if (f) {
    char name[512], key[80];
    while (::fscanf(f, "%511s %79s", name, key) == 2)
      st->refs[name] = key;  // journal replay: last writer wins
    ::fclose(f);
  }
  return st;
}

void cas_free(void* p) { delete static_cast<Store*>(p); }

void cas_put(void* p, const uint8_t* data, size_t n, char* out_key) {
  Sha256 s;
  s.update(data, n);
  char key[65];
  s.hex(key);
  auto* st = static_cast<Store*>(p);
  {
    std::lock_guard<std::mutex> g(st->mu);
    st->blobs.emplace(std::string(key),
                      std::vector<uint8_t>(data, data + n));
    st->persist_blob(key, data, n);
  }
  std::memcpy(out_key, key, 65);
}

long cas_get_len(void* p, const char* key) {
  auto* st = static_cast<Store*>(p);
  std::lock_guard<std::mutex> g(st->mu);
  auto it = st->blobs.find(key);
  if (it == st->blobs.end()) {
    if (!st->load_blob(key)) return -1;
    it = st->blobs.find(key);
  }
  return long(it->second.size());
}

long cas_get(void* p, const char* key, uint8_t* buf, size_t buf_len) {
  auto* st = static_cast<Store*>(p);
  std::lock_guard<std::mutex> g(st->mu);
  auto it = st->blobs.find(key);
  if (it == st->blobs.end()) {
    if (!st->load_blob(key)) return -1;
    it = st->blobs.find(key);
  }
  size_t n = it->second.size();
  if (buf && buf_len >= n) std::memcpy(buf, it->second.data(), n);
  return long(n);
}

int cas_contains(void* p, const char* key) {
  auto* st = static_cast<Store*>(p);
  std::lock_guard<std::mutex> g(st->mu);
  if (st->blobs.count(key)) return 1;
  if (st->dir.empty()) return 0;
  struct stat sb;
  return ::stat(st->blob_path(key).c_str(), &sb) == 0 ? 1 : 0;
}

int cas_set_ref(void* p, const char* name, const char* key) {
  auto* st = static_cast<Store*>(p);
  std::lock_guard<std::mutex> g(st->mu);
  if (!st->blobs.count(key)) {
    struct stat sb;
    if (st->dir.empty() ||
        ::stat(st->blob_path(key).c_str(), &sb) != 0)
      return -1;
  }
  st->refs[name] = key;
  if (!st->persist_ref(name, key)) return -2;
  return 0;
}

long cas_get_ref(void* p, const char* name, char* out_key) {
  auto* st = static_cast<Store*>(p);
  std::lock_guard<std::mutex> g(st->mu);
  auto it = st->refs.find(name);
  if (it == st->refs.end()) return -1;
  std::memcpy(out_key, it->second.c_str(), it->second.size() + 1);
  return long(it->second.size());
}

long cas_ref_count(void* p) {
  auto* st = static_cast<Store*>(p);
  std::lock_guard<std::mutex> g(st->mu);
  return long(st->refs.size());
}

// List ref names into a newline-joined buffer; returns needed size.
long cas_list_refs(void* p, char* buf, size_t buf_len) {
  auto* st = static_cast<Store*>(p);
  std::lock_guard<std::mutex> g(st->mu);
  std::string joined;
  for (auto& kv : st->refs) {
    joined += kv.first;
    joined += '\n';
  }
  if (buf && buf_len >= joined.size() + 1)
    std::memcpy(buf, joined.c_str(), joined.size() + 1);
  return long(joined.size() + 1);
}

}  // extern "C"
