"""Native (C++) components, bound via ctypes.

The reference's only non-TypeScript pieces are native C/C++
dependencies (librdkafka, libgit2/nodegit, zookeeper — SURVEY.md
§2.5); this package plays the libgit2 role: `castore.cpp` is a
content-addressed blob store with named refs, compiled on demand with
the system g++ into `_castore.so` next to the source and loaded with
ctypes (no pybind11 in this image). `load_castore()` returns None
when no compiler is available — callers fall back to the pure-Python
store (server/castore.py) with identical semantics and digests.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "castore.cpp")
_LIB = os.path.join(_DIR, "_castore.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> bool:
    # Link to a process-unique temp path and rename atomically:
    # several processes (e.g. a test run + its server subprocess) may
    # build concurrently, and dlopen must never see a half-written .so.
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, _SRC],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, _LIB)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load_castore() -> Optional[ctypes.CDLL]:
    """The castore shared library, building it on first use; None when
    unavailable (no compiler)."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _load_failed:
            return None
        if not os.path.exists(_LIB) or (
            os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
        ):
            if not _build():
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _load_failed = True
            return None
        lib.cas_new.restype = ctypes.c_void_p
        lib.cas_free.argtypes = [ctypes.c_void_p]
        lib.cas_put.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p
        ]
        lib.cas_get_len.restype = ctypes.c_long
        lib.cas_get_len.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.cas_get.restype = ctypes.c_long
        lib.cas_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t
        ]
        lib.cas_contains.restype = ctypes.c_int
        lib.cas_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.cas_set_ref.restype = ctypes.c_int
        lib.cas_set_ref.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p
        ]
        lib.cas_get_ref.restype = ctypes.c_long
        lib.cas_get_ref.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p
        ]
        lib.cas_list_refs.restype = ctypes.c_long
        lib.cas_list_refs.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t
        ]
        _lib = lib
        return _lib


class NativeContentStore:
    """ctypes wrapper over the C++ store (same surface and digests as
    the pure-Python ContentAddressedStore)."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        self._ptr = ctypes.c_void_p(lib.cas_new())

    def __del__(self):
        ptr, self._ptr = getattr(self, "_ptr", None), None
        if ptr and getattr(self, "_lib", None) is not None:
            self._lib.cas_free(ptr)

    def put(self, content) -> str:
        if isinstance(content, str):
            content = content.encode()
        out = ctypes.create_string_buffer(65)
        self._lib.cas_put(self._ptr, content, len(content), out)
        return out.value.decode()

    def get(self, key: str) -> bytes:
        n = self._lib.cas_get_len(self._ptr, key.encode())
        if n < 0:
            raise KeyError(key)
        buf = ctypes.create_string_buffer(max(n, 1))
        self._lib.cas_get(self._ptr, key.encode(), buf, n)
        return buf.raw[:n]

    def contains(self, key: str) -> bool:
        return bool(self._lib.cas_contains(self._ptr, key.encode()))

    def set_ref(self, name: str, key: str) -> None:
        if self._lib.cas_set_ref(self._ptr, name.encode(), key.encode()) != 0:
            raise KeyError(f"unknown blob {key}")

    def get_ref(self, name: str):
        out = ctypes.create_string_buffer(65)
        n = self._lib.cas_get_ref(self._ptr, name.encode(), out)
        return None if n < 0 else out.value.decode()

    def list_refs(self):
        n = self._lib.cas_list_refs(self._ptr, None, 0)
        buf = ctypes.create_string_buffer(n)
        self._lib.cas_list_refs(self._ptr, buf, n)
        names = buf.value.decode().split("\n")
        return sorted(x for x in names if x)
