"""Native (C++) components, bound via ctypes.

The reference's only non-TypeScript pieces are native C/C++
dependencies (librdkafka, libgit2/nodegit, zookeeper — SURVEY.md
§2.5); this package plays the libgit2 role: `castore.cpp` is a
content-addressed blob store with named refs, compiled on demand with
the system g++ into `_castore.so` next to the source and loaded with
ctypes (no pybind11 in this image). `load_castore()` returns None
when no compiler is available — callers fall back to the pure-Python
store (server/castore.py) with identical semantics and digests.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "castore.cpp")
_LIB = os.path.join(_DIR, "_castore.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build_lib(src: str, lib: str) -> bool:
    # Link to a process-unique temp path and rename atomically:
    # several processes (e.g. a test run + its server subprocess) may
    # build concurrently, and dlopen must never see a half-written .so.
    tmp = f"{lib}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, src],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, lib)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _build() -> bool:
    return _build_lib(_SRC, _LIB)


def load_castore() -> Optional[ctypes.CDLL]:
    """The castore shared library, building it on first use; None when
    unavailable (no compiler)."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _load_failed:
            return None
        if not os.path.exists(_LIB) or (
            os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
        ):
            if not _build():
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _load_failed = True
            return None
        lib.cas_new.restype = ctypes.c_void_p
        lib.cas_open.restype = ctypes.c_void_p
        lib.cas_open.argtypes = [ctypes.c_char_p]
        lib.cas_free.argtypes = [ctypes.c_void_p]
        lib.cas_put.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p
        ]
        lib.cas_get_len.restype = ctypes.c_long
        lib.cas_get_len.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.cas_get.restype = ctypes.c_long
        lib.cas_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t
        ]
        lib.cas_contains.restype = ctypes.c_int
        lib.cas_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.cas_set_ref.restype = ctypes.c_int
        lib.cas_set_ref.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p
        ]
        lib.cas_get_ref.restype = ctypes.c_long
        lib.cas_get_ref.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p
        ]
        lib.cas_list_refs.restype = ctypes.c_long
        lib.cas_list_refs.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t
        ]
        _lib = lib
        return _lib


class NativeContentStore:
    """ctypes wrapper over the C++ store (same surface and digests as
    the pure-Python ContentAddressedStore)."""

    def __init__(self, lib: ctypes.CDLL, directory: Optional[str] = None):
        self._lib = lib
        if directory:
            self._ptr = ctypes.c_void_p(lib.cas_open(directory.encode()))
        else:
            self._ptr = ctypes.c_void_p(lib.cas_new())

    def __del__(self):
        ptr, self._ptr = getattr(self, "_ptr", None), None
        if ptr and getattr(self, "_lib", None) is not None:
            self._lib.cas_free(ptr)

    def put(self, content) -> str:
        if isinstance(content, str):
            content = content.encode()
        out = ctypes.create_string_buffer(65)
        self._lib.cas_put(self._ptr, content, len(content), out)
        return out.value.decode()

    def get(self, key: str) -> bytes:
        n = self._lib.cas_get_len(self._ptr, key.encode())
        if n < 0:
            raise KeyError(key)
        buf = ctypes.create_string_buffer(max(n, 1))
        self._lib.cas_get(self._ptr, key.encode(), buf, n)
        return buf.raw[:n]

    def contains(self, key: str) -> bool:
        return bool(self._lib.cas_contains(self._ptr, key.encode()))

    def set_ref(self, name: str, key: str) -> None:
        if self._lib.cas_set_ref(self._ptr, name.encode(), key.encode()) != 0:
            raise KeyError(f"unknown blob {key}")

    def get_ref(self, name: str):
        out = ctypes.create_string_buffer(65)
        n = self._lib.cas_get_ref(self._ptr, name.encode(), out)
        return None if n < 0 else out.value.decode()

    def list_refs(self):
        n = self._lib.cas_list_refs(self._ptr, None, 0)
        buf = ctypes.create_string_buffer(n)
        self._lib.cas_list_refs(self._ptr, buf, n)
        names = buf.value.decode().split("\n")
        return sorted(x for x in names if x)


# ---------------------------------------------------------------------
# hostmerge: the native interactive merge-tree engine (hostmerge.cpp),
# playing the role of the reference's JIT-compiled merge-tree hot path
# for interactive clients (mergeTree.ts insertingWalk et al).

_HM_SRC = os.path.join(_DIR, "hostmerge.cpp")
_HM_LIB = os.path.join(_DIR, "_hostmerge.so")
_hm_lib: Optional[ctypes.CDLL] = None
_hm_failed = False


def load_hostmerge() -> Optional[ctypes.CDLL]:
    """The hostmerge shared library, building on first use; None when
    unavailable (no compiler)."""
    global _hm_lib, _hm_failed
    with _lock:
        if _hm_lib is not None:
            return _hm_lib
        if _hm_failed:
            return None
        try:
            stale = not os.path.exists(_HM_LIB) or (
                os.path.getmtime(_HM_LIB) < os.path.getmtime(_HM_SRC)
            )
        except OSError:
            # Source missing but a prebuilt .so exists: use it.
            stale = not os.path.exists(_HM_LIB)
        if stale:
            if not _build_lib(_HM_SRC, _HM_LIB):
                _hm_failed = True
                return None
        try:
            lib = ctypes.CDLL(_HM_LIB)
        except OSError:
            _hm_failed = True
            return None
        i32, i64, p = ctypes.c_int32, ctypes.c_int64, ctypes.c_void_p
        ip = ctypes.POINTER(ctypes.c_int32)
        lib.hm_new.restype = p
        lib.hm_new.argtypes = [i32]
        lib.hm_free.argtypes = [p]
        lib.hm_set_identity.argtypes = [p, i32, i32]
        lib.hm_load.argtypes = [p, ip, i64]
        lib.hm_pack_settled.argtypes = [p]
        lib.hm_apply_batch.restype = i32
        lib.hm_apply_batch.argtypes = [p, i64] + [ip] * 12 + [i32]
        lib.hm_enable_attr.argtypes = [p]
        lib.hm_attr_spans.restype = i64
        lib.hm_attr_spans.argtypes = [p, ip, i64]
        for name in ("hm_current_seq", "hm_min_seq", "hm_local_client",
                     "hm_collaborating", "hm_pending_last_id"):
            getattr(lib, name).restype = i32
            getattr(lib, name).argtypes = [p]
        for name in ("hm_set_current_seq", "hm_set_min_seq",
                     "hm_update_min_seq", "hm_ack"):
            getattr(lib, name).argtypes = [p, i32]
        lib.hm_ack.restype = i32
        lib.hm_segment_count.restype = i64
        lib.hm_segment_count.argtypes = [p]
        lib.hm_pending_count.restype = i64
        lib.hm_pending_count.argtypes = [p]
        lib.hm_content_total.restype = i64
        lib.hm_content_total.argtypes = [p]
        lib.hm_verify.restype = i32
        lib.hm_verify.argtypes = [p]
        lib.hm_insert.restype = i32
        lib.hm_insert.argtypes = [p, i64, ip, i64, i32, i32, i32, ip, ip, i32]
        lib.hm_remove.restype = i32
        lib.hm_remove.argtypes = [p, i64, i64, i32, i32, i32]
        lib.hm_annotate.restype = i32
        lib.hm_annotate.argtypes = [p, i64, i64, ip, ip, i32, i32, i32, i32]
        lib.hm_visible_length.restype = i64
        lib.hm_visible_length.argtypes = [p, i32, i32]
        lib.hm_get_items.restype = i64
        lib.hm_get_items.argtypes = [p, ip, i64]
        lib.hm_item_at.restype = i64
        lib.hm_item_at.argtypes = [p, i64, i32, i32]
        lib.hm_position_of_item.restype = i64
        lib.hm_position_of_item.argtypes = [p, i32, i32, i32]
        lib.hm_spans.restype = i64
        lib.hm_spans.argtypes = [p, ip, i64]
        lib.hm_group_props.restype = i64
        lib.hm_group_props.argtypes = [p, i32, ip, i64]
        lib.hm_regenerate.restype = i64
        lib.hm_regenerate.argtypes = [p, ip, i32, ip, i64]
        _hm_lib = lib
        return _hm_lib
