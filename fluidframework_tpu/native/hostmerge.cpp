// Native host merge-tree engine: the interactive-client hot path.
//
// A faithful C++ port of the scalar oracle's segment-list semantics
// (fluidframework_tpu/core/mergetree.py MergeTreeEngine — itself the
// re-expression of reference packages/dds/merge-tree/src/mergeTree.ts
// insertingWalk/markRangeRemoved/annotateRange and client.ts:98).
// The reference runs this path in optimized JIT-compiled TypeScript;
// the Python oracle is deliberately simple and ~100x too slow to
// serve interactive clients (BENCH_DETAIL configs 1/3). This engine
// keeps the oracle's exact algorithm and data model — a document-
// ordered segment list with perspective visibility — in C++, bound
// via ctypes (core/native_engine.py), and is differentially farm-
// tested against the oracle (tests/test_native_engine.py).
//
// Content items are int32 (codepoints for text engines, handles for
// permutation vectors); property keys/values arrive pre-interned as
// int32 pairs (value PROP_DELETE encodes the reference's null-delete).
//
// Memory model: every Segment/Group is owned by engine-lifetime
// registries; the live document is a vector of raw pointers. Acked or
// zamboni-collected objects may still be referenced by pending-group
// metadata (exactly like Python object references) and stay valid
// until hm_free.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <vector>

namespace {

constexpr int32_t UNASSIGNED_SEQ = -1;
constexpr int32_t UNIVERSAL_SEQ = 0;
constexpr int32_t NON_COLLAB_CLIENT = -2;
constexpr int32_t INT32_MAX_ = 2147483647;
constexpr int32_t EFF_SEQ_NEW_LOCAL = INT32_MAX_;
constexpr int32_t EFF_SEQ_EXISTING_LOCAL = INT32_MAX_ - 1;
constexpr int32_t REMOVED_NONE = INT32_MIN;  // removed_seq: not removed
constexpr int32_t PROP_DELETE = -2;          // interned "None" value
constexpr int32_t LOCAL_NONE = -1;           // local_seq: none

// Op kinds (protocol.mergetree_ops MergeTreeDeltaType numbering).
constexpr int KIND_INSERT = 0;
constexpr int KIND_REMOVE = 1;
constexpr int KIND_ANNOTATE = 2;

enum Vis { SKIP = 0, ZERO = 1, VISIBLE = 2 };

struct Group;

struct Seg {
  std::vector<int32_t> content;
  int32_t seq = UNASSIGNED_SEQ;
  int32_t client_id = NON_COLLAB_CLIENT;
  int32_t local_seq = LOCAL_NONE;
  int32_t removed_seq = REMOVED_NONE;
  int32_t local_removed_seq = LOCAL_NONE;
  std::vector<int32_t> removed_clients;
  std::map<int32_t, int32_t> props;          // key -> value
  std::map<int32_t, int32_t> pending_props;  // key -> pending count
  std::vector<Group*> groups;
  // Per-position insert-attribution runs (offset, key): the
  // attributionCollection.ts role. One run per fresh segment (key =
  // insert seq; UNASSIGNED until ack); runs concatenate when
  // pack_settled merges segments, so attribution survives coalescing
  // exactly the way the reference's collection survives append().
  // Empty when tracking is off.
  std::vector<std::pair<int32_t, int32_t>> attr;
};

struct Group {
  int32_t id;
  int kind;
  int32_t local_seq = LOCAL_NONE;
  std::vector<std::pair<int32_t, int32_t>> props;  // annotate acks
  std::vector<Seg*> segs;
};

struct Engine {
  std::vector<std::unique_ptr<Seg>> seg_owner;
  std::vector<std::unique_ptr<Group>> grp_owner;
  std::vector<Seg*> segments;  // document order
  std::deque<Group*> pending;  // local-op FIFO (ack order)
  int32_t local_client_id = NON_COLLAB_CLIENT;
  bool collaborating = false;
  int32_t current_seq = 0;
  int32_t min_seq = 0;
  int32_t local_seq = 0;
  int32_t next_group_id = 1;

  Seg* new_seg() {
    seg_owner.push_back(std::make_unique<Seg>());
    return seg_owner.back().get();
  }
  Group* new_group(int kind) {
    grp_owner.push_back(std::make_unique<Group>());
    Group* g = grp_owner.back().get();
    g->id = next_group_id++;
    g->kind = kind;
    return g;
  }

  // ---- visibility (mergetree.py _vis / mergeTree.ts:916 nodeLength)
  Vis vis(const Seg* s, int32_t ref_seq, int32_t client, int64_t* len) const {
    bool removed = s->removed_seq != REMOVED_NONE;
    *len = 0;
    if (client == local_client_id && collaborating) {
      if (removed) {
        int64_t norm = (s->removed_seq == UNASSIGNED_SEQ)
                           ? INT64_MAX
                           : (int64_t)s->removed_seq;
        if (norm > min_seq) return ZERO;
        return SKIP;
      }
      *len = (int64_t)s->content.size();
      return VISIBLE;
    }
    if (removed && s->removed_seq != UNASSIGNED_SEQ &&
        s->removed_seq <= ref_seq)
      return SKIP;
    if (s->client_id == client ||
        (s->seq != UNASSIGNED_SEQ && s->seq <= ref_seq)) {
      if (removed) {
        for (int32_t c : s->removed_clients)
          if (c == client) return ZERO;
      }
      *len = (int64_t)s->content.size();
      return VISIBLE;
    }
    if (removed && s->removed_seq != UNASSIGNED_SEQ) return SKIP;
    return ZERO;
  }

  static int32_t eff_seq(int32_t seq) {
    return seq == UNASSIGNED_SEQ ? EFF_SEQ_EXISTING_LOCAL : seq;
  }

  // ---- split (Segment.split: tail inherits all merge metadata)
  Seg* split(Seg* s, int64_t offset) {
    Seg* tail = new_seg();
    tail->content.assign(s->content.begin() + offset, s->content.end());
    s->content.resize(offset);
    tail->seq = s->seq;
    tail->client_id = s->client_id;
    tail->local_seq = s->local_seq;
    tail->removed_seq = s->removed_seq;
    tail->local_removed_seq = s->local_removed_seq;
    tail->removed_clients = s->removed_clients;
    tail->props = s->props;
    tail->pending_props = s->pending_props;
    tail->groups = s->groups;
    for (Group* g : tail->groups) g->segs.push_back(tail);
    if (!s->attr.empty()) {
      // Slice attribution runs at the split point (the
      // attributionCollection.ts splitAt role). Run 0 starts at
      // offset 0 < offset, so i >= 1 on exit.
      size_t i = 0;
      while (i < s->attr.size() && s->attr[i].first < offset) i++;
      bool boundary_run = !(i < s->attr.size() &&
                            s->attr[i].first == offset);
      if (boundary_run)
        // Run i-1 straddles the boundary: tail starts with its key.
        tail->attr.push_back({0, s->attr[i - 1].second});
      for (size_t k = i; k < s->attr.size(); k++)
        tail->attr.push_back(
            {(int32_t)(s->attr[k].first - offset), s->attr[k].second});
      s->attr.resize(i);
    }
    return tail;
  }

  // ---- insert (mergetree.py insert / insertingWalk + breakTie)
  // Returns 0, or -1 for position-beyond-length.
  int insert(int64_t pos, const int32_t* items, int64_t n, int32_t ref_seq,
             int32_t client, int32_t seq, const int32_t* pkeys,
             const int32_t* pvals, int32_t nk) {
    int32_t eff_new = (seq == UNASSIGNED_SEQ) ? EFF_SEQ_NEW_LOCAL : seq;
    int32_t lseq = LOCAL_NONE;
    if (seq == UNASSIGNED_SEQ) lseq = ++local_seq;
    Seg* ns = new_seg();
    if (track_attr) ns->attr.push_back({0, seq});
    ns->content.assign(items, items + n);
    ns->seq = seq;
    ns->client_id = client;
    ns->local_seq = lseq;
    for (int32_t k = 0; k < nk; k++)
      if (pvals[k] != PROP_DELETE) ns->props[pkeys[k]] = pvals[k];

    int64_t remaining = pos;
    size_t insert_at = segments.size();
    bool landed = false;
    for (size_t i = 0; i < segments.size(); i++) {
      Seg* s = segments[i];
      int64_t len;
      Vis cat = vis(s, ref_seq, client, &len);
      if (cat == SKIP) continue;
      if (remaining < len) {
        if (remaining == 0) {
          insert_at = i;
        } else {
          Seg* tail = split(s, remaining);
          segments.insert(segments.begin() + i + 1, tail);
          insert_at = i + 1;
        }
        landed = true;
        break;
      }
      if (remaining == 0 && len == 0) {
        if (eff_new > eff_seq(s->seq)) {
          insert_at = i;
          landed = true;
          break;
        }
        continue;
      }
      remaining -= len;
    }
    if (!landed) {
      if (remaining > 0) return -1;
      insert_at = segments.size();
    }
    segments.insert(segments.begin() + insert_at, ns);
    if (seq == UNASSIGNED_SEQ) {
      Group* g = new_group(KIND_INSERT);
      g->local_seq = lseq;
      g->segs.push_back(ns);
      ns->groups.push_back(g);
      pending.push_back(g);
    }
    return 0;
  }

  // ---- boundary split (ensureIntervalBoundary)
  void ensure_boundary(int64_t pos, int32_t ref_seq, int32_t client) {
    int64_t remaining = pos;
    for (size_t i = 0; i < segments.size(); i++) {
      Seg* s = segments[i];
      int64_t len;
      Vis cat = vis(s, ref_seq, client, &len);
      if (cat == SKIP) continue;
      if (remaining < len) {
        if (remaining > 0) {
          Seg* tail = split(s, remaining);
          segments.insert(segments.begin() + i + 1, tail);
        }
        return;
      }
      remaining -= len;
    }
  }

  // ---- remove (mergetree.py remove_range / markRangeRemoved)
  int remove_range(int64_t start, int64_t end, int32_t ref_seq,
                   int32_t client, int32_t seq) {
    if (!(end > start && start >= 0)) return -1;
    ensure_boundary(start, ref_seq, client);
    ensure_boundary(end, ref_seq, client);
    int32_t lseq = LOCAL_NONE;
    if (seq == UNASSIGNED_SEQ) lseq = ++local_seq;
    std::vector<Seg*> newly_ours;
    int64_t pos = 0;
    for (Seg* s : segments) {
      if (pos >= end) break;
      int64_t len;
      Vis cat = vis(s, ref_seq, client, &len);
      if (cat == SKIP || len == 0) continue;
      if (pos >= start) {
        if (s->removed_seq != REMOVED_NONE) {
          if (s->removed_seq == UNASSIGNED_SEQ) {
            // Our pending local remove lost the race.
            s->removed_clients.insert(s->removed_clients.begin(), client);
            s->removed_seq = seq;
            note_tomb(seq);
          } else {
            s->removed_clients.push_back(client);
          }
        } else {
          s->removed_seq = seq;
          s->removed_clients.assign(1, client);
          s->local_removed_seq = lseq;
          if (seq == UNASSIGNED_SEQ) newly_ours.push_back(s);
          else note_tomb(seq);
        }
      }
      pos += len;
    }
    if (seq == UNASSIGNED_SEQ) {
      Group* g = new_group(KIND_REMOVE);
      g->local_seq = lseq;
      for (Seg* s : newly_ours) {
        g->segs.push_back(s);
        s->groups.push_back(g);
      }
      pending.push_back(g);
    }
    return 0;
  }

  // ---- annotate (mergetree.py annotate_range / annotateRange;
  // pending-shadow rule from segmentPropertiesManager.ts)
  int annotate_range(int64_t start, int64_t end, const int32_t* pkeys,
                     const int32_t* pvals, int32_t nk, int32_t ref_seq,
                     int32_t client, int32_t seq) {
    if (!(end > start && start >= 0)) return -1;
    ensure_boundary(start, ref_seq, client);
    ensure_boundary(end, ref_seq, client);
    bool is_local = seq == UNASSIGNED_SEQ;
    if (is_local) ++local_seq;
    std::vector<Seg*> touched;
    int64_t pos = 0;
    for (Seg* s : segments) {
      if (pos >= end) break;
      int64_t len;
      Vis cat = vis(s, ref_seq, client, &len);
      if (cat == SKIP || len == 0) continue;
      if (pos >= start) {
        for (int32_t k = 0; k < nk; k++) {
          int32_t key = pkeys[k], val = pvals[k];
          if (is_local) {
            s->pending_props[key] += 1;
            if (val == PROP_DELETE)
              s->props.erase(key);
            else
              s->props[key] = val;
          } else {
            auto it = s->pending_props.find(key);
            if (it != s->pending_props.end() && it->second > 0)
              continue;  // shadowed by pending local write
            if (val == PROP_DELETE)
              s->props.erase(key);
            else
              s->props[key] = val;
          }
        }
        touched.push_back(s);
      }
      pos += len;
    }
    if (is_local) {
      Group* g = new_group(KIND_ANNOTATE);
      g->local_seq = local_seq;
      for (int32_t k = 0; k < nk; k++) g->props.push_back({pkeys[k], pvals[k]});
      for (Seg* s : touched) {
        g->segs.push_back(s);
        s->groups.push_back(g);
      }
      pending.push_back(g);
    }
    return 0;
  }

  // ---- ack (mergetree.py ack / ackPendingSegment)
  int ack(int32_t seq) {
    if (pending.empty()) return -1;
    Group* g = pending.front();
    pending.pop_front();
    for (Seg* s : g->segs)
      s->groups.erase(std::remove(s->groups.begin(), s->groups.end(), g),
                      s->groups.end());
    if (g->kind == KIND_INSERT) {
      for (Seg* s : g->segs) {
        s->seq = seq;
        s->local_seq = LOCAL_NONE;
        for (auto& run : s->attr)
          if (run.second == UNASSIGNED_SEQ) run.second = seq;
      }
    } else if (g->kind == KIND_REMOVE) {
      for (Seg* s : g->segs) {
        if (s->removed_seq == UNASSIGNED_SEQ) {
          s->removed_seq = seq;
          note_tomb(seq);
        }
        // else: an overlapping remote remove owns removed_seq.
        s->local_removed_seq = LOCAL_NONE;
      }
    } else {
      for (Seg* s : g->segs) {
        for (auto& kv : g->props) {
          auto it = s->pending_props.find(kv.first);
          if (it != s->pending_props.end() && it->second > 0) {
            if (it->second == 1)
              s->pending_props.erase(it);
            else
              it->second -= 1;
          }
        }
      }
    }
    return 0;
  }

  // Insert-attribution tracking (attributionPolicy.ts role); opt-in
  // because every segment then carries a run vector.
  bool track_attr = false;
  void enable_attr_tracking() {
    if (track_attr) return;
    track_attr = true;
    // Backfill existing segments: preloaded content attributes to
    // key 0 (the "detached/load" attribution), sequenced segments to
    // their insert seq, pending locals to UNASSIGNED (acks fill it).
    for (Seg* s : segments)
      if (s->attr.empty())
        s->attr.push_back(
            {0, s->seq == UNASSIGNED_SEQ ? UNASSIGNED_SEQ
                 : (s->client_id == NON_COLLAB_CLIENT ? 0 : s->seq)});
  }

  // Smallest acked removed_seq still in the list (INT32_MAX_ when no
  // collectible tombstone exists) — lets update_min_seq run O(1) per
  // message until the MSN actually passes a tombstone.
  int32_t min_tomb = INT32_MAX_;
  void note_tomb(int32_t s) {
    if (s < min_tomb) min_tomb = s;
  }

  // ---- windows (mergetree.py update_min_seq; zamboni.ts:19)
  void update_min_seq(int32_t new_min) {
    min_seq = new_min;
    if (min_tomb <= new_min) {
      std::vector<Seg*> kept;
      kept.reserve(segments.size());
      min_tomb = INT32_MAX_;
      for (Seg* s : segments) {
        bool acked_tomb = s->removed_seq != REMOVED_NONE &&
                          s->removed_seq != UNASSIGNED_SEQ;
        if (acked_tomb && s->removed_seq <= new_min) continue;
        if (acked_tomb) note_tomb(s->removed_seq);
        kept.push_back(s);
      }
      segments.swap(kept);
    }
    maybe_autopack();
  }

  // Merge adjacent fully-settled same-props segments (the
  // zamboni.ts:19 packParent role). Settled segments (acked at or
  // below min_seq, not removed, no live pending-group references —
  // `groups` holds exactly the UNacked groups, ack() removes itself
  // from every member) are indistinguishable to every valid future
  // perspective (any refSeq >= MSN sees them), and nothing can later
  // address them through a group, so merging preserves all
  // visibility/position/ack semantics for interactive engines too.
  // Runs are capped so a later insert that lands inside settled
  // content splits an O(cap) segment, not an O(document) one (the
  // reference likewise packs under a segment-size budget).
  static constexpr size_t PACK_RUN_CAP = 4096;
  void pack_settled() {
    std::vector<Seg*> kept;
    kept.reserve(segments.size());
    Seg* run = nullptr;
    for (Seg* s : segments) {
      bool settled = s->seq != UNASSIGNED_SEQ && s->seq <= min_seq &&
                     s->removed_seq == REMOVED_NONE &&
                     s->pending_props.empty() && s->groups.empty();
      if (settled && run != nullptr && run->props == s->props &&
          run->content.size() + s->content.size() <= PACK_RUN_CAP) {
        int32_t base = (int32_t)run->content.size();
        run->content.insert(run->content.end(), s->content.begin(),
                            s->content.end());
        for (auto& r : s->attr) {
          int32_t off = base + r.first;
          if (!run->attr.empty() && run->attr.back().second == r.second)
            continue;  // coalesce equal adjacent keys
          run->attr.push_back({off, r.second});
        }
        continue;
      }
      kept.push_back(s);
      run = settled ? s : nullptr;
    }
    segments.swap(kept);
  }

  // Growth-triggered packing: amortized O(1) per op, keeps the
  // per-op document walks O(collab window + doc/PACK_RUN_CAP) the way
  // the reference's zamboni + B-tree bound them.
  size_t pack_watermark = 64;
  void maybe_autopack() {
    if (segments.size() >= pack_watermark * 2) {
      pack_settled();
      pack_watermark = std::max<size_t>(64, segments.size());
    }
  }

  // ---- queries
  int64_t visible_length(int32_t ref_seq, int32_t client) const {
    int64_t total = 0, len;
    for (const Seg* s : segments) {
      vis(s, ref_seq, client, &len);
      total += len;
    }
    return total;
  }

  int64_t item_at(int64_t pos, int32_t ref_seq, int32_t client) const {
    int64_t remaining = pos, len;
    for (const Seg* s : segments) {
      Vis cat = vis(s, ref_seq, client, &len);
      if (cat == SKIP || len == 0) continue;
      if (remaining < len) return s->content[remaining];
      remaining -= len;
    }
    return -1;
  }

  int64_t position_of_item(int32_t item, int32_t ref_seq,
                           int32_t client) const {
    int64_t pos = 0, len;
    for (const Seg* s : segments) {
      Vis cat = vis(s, ref_seq, client, &len);
      if (cat == SKIP || len == 0) continue;
      for (size_t j = 0; j < s->content.size(); j++)
        if (s->content[j] == item) return pos + (int64_t)j;
      pos += len;
    }
    return -1;
  }

  // ---- reconnect rebase (mergetree.py regenerate_pending /
  // client.ts:917 regeneratePendingOp). See the Python docstring for
  // the group-splitting contract; the wire encoding is
  // [kind, grp_id, a, b, n_items, items...]* (insert: a=pos; range
  // ops: a=start, b=end).
  int32_t group_fifo_index(const Group* g) const {
    for (size_t i = 0; i < pending.size(); i++)
      if (pending[i] == g) return (int32_t)i;
    return -1;
  }

  int32_t group_index_of_kind(const Seg* s, int kind) const {
    for (Group* g : s->groups)
      if (g->kind == kind) return group_fifo_index(g);
    return -1;
  }

  int64_t reg_vis_len(const Seg* s, int32_t idx) const {
    if (s->seq == UNASSIGNED_SEQ) {
      int32_t gi = group_index_of_kind(s, KIND_INSERT);
      if (gi < 0 || gi >= idx) return 0;
    }
    if (s->removed_seq != REMOVED_NONE) {
      if (s->removed_seq != UNASSIGNED_SEQ) return 0;
      int32_t gi = group_index_of_kind(s, KIND_REMOVE);
      if (gi >= 0 && gi < idx) return 0;
    }
    return (int64_t)s->content.size();
  }

  int64_t base_pos(const Seg* target, int32_t idx) const {
    int64_t total = 0;
    for (const Seg* s : segments) {
      if (s == target) return total;
      total += reg_vis_len(s, idx);
    }
    return -1;
  }

  bool regenerate_one(Group* g, std::vector<int32_t>& out) {
    int32_t idx = group_fifo_index(g);
    if (idx < 0) return true;  // sequenced during catch-up
    std::map<const Seg*, size_t> seg_pos;
    for (size_t i = 0; i < segments.size(); i++) seg_pos[segments[i]] = i;
    std::vector<Seg*> segs;
    for (Seg* s : g->segs)
      if (seg_pos.count(s)) segs.push_back(s);
    std::sort(segs.begin(), segs.end(), [&](Seg* a, Seg* b) {
      return seg_pos[a] < seg_pos[b];
    });
    for (Seg* s : segs) s->client_id = local_client_id;

    if (g->kind == KIND_INSERT) {
      if (segs.empty()) {
        pending.erase(
            std::remove(pending.begin(), pending.end(), g), pending.end());
        return true;
      }
      int64_t pos = base_pos(segs[0], idx);
      out.push_back(KIND_INSERT);
      out.push_back(g->id);
      out.push_back((int32_t)pos);
      out.push_back(0);
      size_t nslot = out.size();
      out.push_back(0);
      int32_t n = 0;
      for (Seg* s : segs)
        for (int32_t it : s->content) {
          out.push_back(it);
          n++;
        }
      out[nslot] = n;
      return true;
    }

    // Range ops: drop members whose removal has sequenced.
    std::vector<Seg*> live;
    for (Seg* s : segs)
      if (!(s->removed_seq != REMOVED_NONE &&
            s->removed_seq != UNASSIGNED_SEQ))
        live.push_back(s);
    if (live.empty()) {
      pending.erase(
          std::remove(pending.begin(), pending.end(), g), pending.end());
      return true;
    }
    // Split: one per-segment group replacing the original at idx.
    pending.erase(
        std::remove(pending.begin(), pending.end(), g), pending.end());
    std::vector<Group*> new_groups;
    for (Seg* s : live) {
      Group* ng = new_group(g->kind);
      ng->local_seq = g->local_seq;
      ng->props = g->props;
      ng->segs.push_back(s);
      s->groups.erase(std::remove(s->groups.begin(), s->groups.end(), g),
                      s->groups.end());
      s->groups.push_back(ng);
      new_groups.push_back(ng);
    }
    pending.insert(pending.begin() + idx, new_groups.begin(),
                   new_groups.end());

    int64_t removed_before = 0;
    for (size_t i = 0; i < live.size(); i++) {
      Seg* s = live[i];
      int64_t start = base_pos(s, idx) - removed_before;
      int64_t end = start + (int64_t)s->content.size();
      out.push_back(g->kind);
      out.push_back(new_groups[i]->id);
      out.push_back((int32_t)start);
      out.push_back((int32_t)end);
      out.push_back(0);
      if (g->kind == KIND_REMOVE) removed_before += (int64_t)s->content.size();
    }
    return true;
  }
};

Engine* E(void* h) { return static_cast<Engine*>(h); }

}  // namespace

extern "C" {

void* hm_new(int32_t client_id) {
  Engine* e = new Engine();
  e->local_client_id = client_id;
  e->collaborating = client_id != NON_COLLAB_CLIENT;
  return e;
}

void hm_free(void* h) { delete E(h); }

void hm_set_identity(void* h, int32_t cid, int32_t collaborating) {
  E(h)->local_client_id = cid;
  E(h)->collaborating = collaborating != 0;
}

void hm_load(void* h, const int32_t* items, int64_t n) {
  if (n <= 0) return;
  Engine* e = E(h);
  Seg* s = e->new_seg();
  s->content.assign(items, items + n);
  s->seq = UNIVERSAL_SEQ;
  s->client_id = NON_COLLAB_CLIENT;
  if (e->track_attr) s->attr.push_back({0, 0});
  e->segments.push_back(s);
}

void hm_enable_attr(void* h) { E(h)->enable_attr_tracking(); }

// Per-position insert-attribution runs over the visible document:
// flat stream of (run_len, key) pairs (adjacent equal keys NOT merged
// across segments — callers normalize). Two-call sizing like hm_spans.
int64_t hm_attr_spans(void* h, int32_t* out, int64_t cap) {
  Engine* e = E(h);
  int64_t n = 0;
  auto put = [&](int32_t v) {
    if (out && n < cap) out[n] = v;
    n++;
  };
  for (const Seg* s : e->segments) {
    if (s->removed_seq != REMOVED_NONE) continue;
    int64_t len = (int64_t)s->content.size();
    if (len == 0) continue;
    if (s->attr.empty()) {
      put((int32_t)len);
      put(s->seq);
      continue;
    }
    for (size_t i = 0; i < s->attr.size(); i++) {
      int64_t end = (i + 1 < s->attr.size()) ? s->attr[i + 1].first : len;
      put((int32_t)(end - s->attr[i].first));
      put(s->attr[i].second);
    }
  }
  return n;
}

int32_t hm_current_seq(void* h) { return E(h)->current_seq; }
void hm_set_current_seq(void* h, int32_t v) { E(h)->current_seq = v; }
int32_t hm_min_seq(void* h) { return E(h)->min_seq; }
void hm_set_min_seq(void* h, int32_t v) { E(h)->min_seq = v; }
int32_t hm_local_client(void* h) { return E(h)->local_client_id; }
int32_t hm_collaborating(void* h) { return E(h)->collaborating ? 1 : 0; }
int64_t hm_segment_count(void* h) { return (int64_t)E(h)->segments.size(); }

int32_t hm_insert(void* h, int64_t pos, const int32_t* items, int64_t n,
                  int32_t ref_seq, int32_t client, int32_t seq,
                  const int32_t* pkeys, const int32_t* pvals, int32_t nk) {
  return E(h)->insert(pos, items, n, ref_seq, client, seq, pkeys, pvals, nk);
}

int32_t hm_remove(void* h, int64_t start, int64_t end, int32_t ref_seq,
                  int32_t client, int32_t seq) {
  return E(h)->remove_range(start, end, ref_seq, client, seq);
}

int32_t hm_annotate(void* h, int64_t start, int64_t end, const int32_t* pkeys,
                    const int32_t* pvals, int32_t nk, int32_t ref_seq,
                    int32_t client, int32_t seq) {
  return E(h)->annotate_range(start, end, pkeys, pvals, nk, ref_seq, client,
                              seq);
}

int32_t hm_ack(void* h, int32_t seq) { return E(h)->ack(seq); }

void hm_pack_settled(void* h) { E(h)->pack_settled(); }

// Batched sequenced-message application: the client.ts:858 applyMsg
// loop crossed ONCE per batch instead of once per message (the
// interactive path's bottleneck was per-op Python/ctypes frames, not
// the merge walks). Row kinds: 0 insert, 1 remove, 2 annotate,
// 3 ack (own op), 4 window-only (join/noop). Deferring the MSN to one
// update_min_seq(final_msn) at batch end is semantics-preserving:
// zamboni timing never changes visible state, and min_seq only enters
// vis() on the LOCAL perspective, which no remote apply or ack reads.
// Returns 0, or -(i+1) for the first failing row i.
int32_t hm_apply_batch(void* h, int64_t n, const int32_t* kind,
                       const int32_t* pos1, const int32_t* pos2,
                       const int32_t* ref_seq, const int32_t* client,
                       const int32_t* seq,
                       const int32_t* arena, const int32_t* aoff,
                       const int32_t* alen,
                       const int32_t* pk, const int32_t* pv,
                       const int32_t* poff, int32_t final_msn) {
  Engine* e = E(h);
  for (int64_t i = 0; i < n; ++i) {
    int rc = 0;
    switch (kind[i]) {
      case 0:
        rc = e->insert(pos1[i], arena + aoff[i], alen[i], ref_seq[i],
                       client[i], seq[i], pk + poff[i],
                       pv + poff[i], poff[i + 1] - poff[i]);
        break;
      case 1:
        rc = e->remove_range(pos1[i], pos2[i], ref_seq[i], client[i],
                             seq[i]);
        break;
      case 2:
        rc = e->annotate_range(pos1[i], pos2[i], pk + poff[i],
                               pv + poff[i], poff[i + 1] - poff[i],
                               ref_seq[i], client[i], seq[i]);
        break;
      case 3:
        rc = e->ack(seq[i]);
        break;
      case 4:
        break;
      default:
        rc = -1;
    }
    if (rc != 0) return (int32_t)(-(i + 1));
    e->current_seq = seq[i];
  }
  if (final_msn > e->min_seq) e->update_min_seq(final_msn);
  else e->maybe_autopack();
  return 0;
}

void hm_update_min_seq(void* h, int32_t min_seq) {
  E(h)->update_min_seq(min_seq);
}

int64_t hm_visible_length(void* h, int32_t ref_seq, int32_t client) {
  return E(h)->visible_length(ref_seq, client);
}

// Visible content at the LOCAL materialized view (removed_seq unset),
// matching the oracle's get_text/get_items.
int64_t hm_get_items(void* h, int32_t* out, int64_t cap) {
  Engine* e = E(h);
  int64_t n = 0;
  for (const Seg* s : e->segments) {
    if (s->removed_seq != REMOVED_NONE) continue;
    for (int32_t it : s->content) {
      if (out && n < cap) out[n] = it;
      n++;
    }
  }
  return n;
}

int64_t hm_item_at(void* h, int64_t pos, int32_t ref_seq, int32_t client) {
  return E(h)->item_at(pos, ref_seq, client);
}

int64_t hm_position_of_item(void* h, int32_t item, int32_t ref_seq,
                            int32_t client) {
  return E(h)->position_of_item(item, ref_seq, client);
}

// Annotated spans of the local materialized view, flat-encoded per
// visible segment: [n_items, items..., n_props, key, val, ...]*.
int64_t hm_spans(void* h, int32_t* out, int64_t cap) {
  Engine* e = E(h);
  int64_t n = 0;
  auto put = [&](int32_t v) {
    if (out && n < cap) out[n] = v;
    n++;
  };
  for (const Seg* s : e->segments) {
    if (s->removed_seq != REMOVED_NONE) continue;
    put((int32_t)s->content.size());
    for (int32_t it : s->content) put(it);
    put((int32_t)s->props.size());
    for (auto& kv : s->props) {
      put(kv.first);
      put(kv.second);
    }
  }
  return n;
}

int64_t hm_pending_count(void* h) { return (int64_t)E(h)->pending.size(); }

// Structural invariant verification (the mergetree.py
// verify_invariants role; reference partialLengths.ts:336 verifier).
// Returns 0 when sound, else a small positive violation code.
int32_t hm_verify(void* h) {
  Engine* e = E(h);
  if (e->min_seq > e->current_seq) return 1;
  for (const Seg* s : e->segments) {
    if (s->content.empty()) return 2;
    if (s->removed_seq == REMOVED_NONE) {
      if (!s->removed_clients.empty()) return 3;
    } else if (s->removed_seq == UNASSIGNED_SEQ) {
      if (s->local_removed_seq == LOCAL_NONE && s->groups.empty()) return 4;
    } else {
      if (s->removed_clients.empty()) return 5;
      if (!(s->removed_seq >= s->seq || s->seq == UNASSIGNED_SEQ)) return 6;
    }
    if (s->seq == UNASSIGNED_SEQ && s->client_id != e->local_client_id)
      return 7;
    for (const Group* g : s->groups) {
      bool found = false;
      for (const Group* p : e->pending)
        if (p == g) found = true;
      if (!found) return 8;
    }
  }
  // Visible length at the local head must equal materialized length.
  int64_t mat = 0;
  for (const Seg* s : e->segments)
    if (s->removed_seq == REMOVED_NONE) mat += (int64_t)s->content.size();
  if (e->visible_length(e->current_seq, e->local_client_id) != mat) return 9;
  return 0;
}

// Upper bound on hm_regenerate's output size (regeneration mutates
// state, so callers must size the buffer BEFORE the single call).
int64_t hm_content_total(void* h) {
  int64_t total = 0;
  for (const Seg* s : E(h)->segments) total += (int64_t)s->content.size();
  return total;
}

int32_t hm_pending_last_id(void* h) {
  Engine* e = E(h);
  return e->pending.empty() ? -1 : e->pending.back()->id;
}

int64_t hm_group_props(void* h, int32_t grp_id, int32_t* out, int64_t cap) {
  Engine* e = E(h);
  for (auto& g : e->grp_owner)
    if (g->id == grp_id) {
      int64_t n = 0;
      for (auto& kv : g->props) {
        if (out && n + 1 < cap) {
          out[n] = kv.first;
          out[n + 1] = kv.second;
        }
        n += 2;
      }
      return n;
    }
  return -1;
}

// Regenerate the pending ops backed by `grp_ids` for resubmission
// after reconnect. Returns the number of int32s written (flat op
// stream, see Engine::regenerate_one), or -1 on unknown group id.
int64_t hm_regenerate(void* h, const int32_t* grp_ids, int32_t n_grps,
                      int32_t* out, int64_t cap) {
  Engine* e = E(h);
  std::vector<int32_t> buf;
  for (int32_t i = 0; i < n_grps; i++) {
    Group* g = nullptr;
    for (auto& og : e->grp_owner)
      if (og->id == grp_ids[i]) {
        g = og.get();
        break;
      }
    if (!g) return -1;
    e->regenerate_one(g, buf);
  }
  for (size_t i = 0; i < buf.size(); i++)
    if (out && (int64_t)i < cap) out[i] = buf[i];
  return (int64_t)buf.size();
}

}  // extern "C"
