"""Offline tooling: op-stream analysis + cross-engine replay
validation (the fetch-tool / replay-tool roles,
packages/tools/fetch-tool/src/fluidAnalyzeMessages.ts and
packages/tools/replay-tool/src/replayMessages.ts)."""

from .analyzer import analyze_messages
from .replay_validator import validate_replay

__all__ = ["analyze_messages", "validate_replay"]
