"""Cross-engine replay validation (the replay-tool role,
packages/tools/replay-tool/src/replayMessages.ts): replay one recorded
op stream through MULTIPLE engines, capturing staged state digests,
and assert they are bit-identical at every stage — the reference uses
this to cross-validate snapshots between runtime versions; here it
cross-validates the independent merge engines (scalar oracle, numpy
overlay, scan kernel, pallas overlay interpret)."""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence

from ..protocol.messages import SequencedMessage
from ..testing.digest import state_digest


def _stage_points(n: int, stages: int) -> List[int]:
    if stages <= 1 or n <= 1:
        return [n]
    step = max(1, n // stages)
    pts = list(range(step, n, step)) + [n]
    return sorted(set(pts))


def validate_replay(
    messages: Sequence[SequencedMessage],
    initial: str = "",
    engines: Optional[List[str]] = None,
    stages: int = 4,
) -> Dict[str, Any]:
    """Replay `messages` through each engine with staged digests.

    Engines: "oracle" (core/mergetree.py), "overlay" (numpy
    overlay, ops/overlay_ref.py), "kernel" (scan kernel via
    KernelReplica), "overlay-device" (pallas overlay, interpret mode).
    Returns {"stages": [...], "digests": {engine: [...]}, "ok": bool,
    "mismatches": [...]}; raises nothing — callers inspect "ok".
    """
    engines = engines or ["oracle", "overlay", "kernel"]
    msgs = list(messages)
    pts = _stage_points(len(msgs), stages)
    digests: Dict[str, List[str]] = {}

    for name in engines:
        digests[name] = _replay_staged(name, msgs, initial, pts)

    base = engines[0]
    mismatches = []
    for i, pt in enumerate(pts):
        vals = {name: digests[name][i] for name in engines}
        if len(set(vals.values())) != 1:
            mismatches.append({"stage": pt, "digests": vals})
    return {
        "stages": pts,
        "digests": digests,
        "ok": not mismatches,
        "mismatches": mismatches,
        "baseline": base,
    }


def _replay_staged(engine: str, msgs, initial: str,
                   pts: List[int]) -> List[str]:
    out: List[str] = []
    if engine == "oracle":
        from ..core.mergetree import replay_passive

        marks = set(pts)

        def hook(i, eng):
            if i + 1 in marks:
                out.append(state_digest(eng.annotated_spans()))

        replay_passive(msgs, initial, on_message=hook)
        return out
    if engine == "overlay":
        from ..ops.overlay_ref import OverlayMessageReplica

        return _staged_apply(
            OverlayMessageReplica(initial=initial, fold_interval=64),
            msgs, pts,
        )
    if engine == "kernel":
        from ..core.kernel_replica import KernelReplica

        return _staged_apply(
            KernelReplica(initial=initial, chunk_size=64, capacity=4096),
            msgs, pts,
        )
    if engine == "overlay-device":
        from ..core.overlay_replay import OverlayKernelMessageReplica

        return _staged_apply(
            OverlayKernelMessageReplica(
                initial=initial, chunk_size=64, window=2048,
                interpret=True,
            ),
            msgs, pts,
        )
    raise ValueError(f"unknown engine {engine!r}")


def _staged_apply(replica, msgs, pts: List[int]) -> List[str]:
    out: List[str] = []
    lo = 0
    for pt in pts:
        replica.apply_messages(msgs[lo:pt])
        lo = pt
        out.append(state_digest(replica.annotated_spans()))
    replica.check_errors()
    return out
