"""Op-stream analyzer (the fluidAnalyzeMessages role,
packages/tools/fetch-tool/src/fluidAnalyzeMessages.ts): offline
statistics over a sequenced message stream — message-type histogram,
per-client activity, op sizes, session duration/rates, MSN lag, and
channel-level op routing counts."""

from __future__ import annotations

import json
from collections import Counter
from typing import Any, Dict, Iterable, List

from ..protocol.messages import MessageType, SequencedMessage


def _op_size(msg: SequencedMessage) -> int:
    from ..runtime.op_lifecycle import wire_size

    return wire_size(msg.contents)


def _channel_of(contents: Any) -> str:
    """Best-effort channel address of a runtime op envelope."""
    if isinstance(contents, dict):
        inner = contents.get("contents")
        addr = contents.get("address")
        if isinstance(inner, dict) and "address" in inner:
            return f"{addr}/{inner['address']}"
        if addr is not None:
            return str(addr)
    return "<raw>"


def analyze_messages(stream: Iterable[SequencedMessage]) -> Dict[str, Any]:
    """Aggregate statistics over a sequenced stream."""
    type_counts: Counter = Counter()
    client_counts: Counter = Counter()
    channel_counts: Counter = Counter()
    sizes: List[int] = []
    msn_lags: List[int] = []
    first_ts = last_ts = None
    n = 0
    max_seq = 0
    for msg in stream:
        n += 1
        max_seq = max(max_seq, msg.sequence_number)
        type_counts[msg.type.name] += 1
        client_counts[msg.client_id] += 1
        msn_lags.append(msg.sequence_number - msg.minimum_sequence_number)
        if msg.type == MessageType.OP:
            sizes.append(_op_size(msg))
            channel_counts[_channel_of(msg.contents)] += 1
        if msg.timestamp:
            if first_ts is None:
                first_ts = msg.timestamp
            last_ts = msg.timestamp
    duration = (last_ts - first_ts) if first_ts and last_ts else 0.0
    sizes.sort()

    def pct(vals: List[int], q: float) -> int:
        if not vals:
            return 0
        return vals[min(len(vals) - 1, int(q * len(vals)))]

    return {
        "messages": n,
        "maxSeq": max_seq,
        "types": dict(type_counts),
        "clients": {
            "count": len(client_counts),
            "top": client_counts.most_common(5),
        },
        "channels": dict(channel_counts.most_common(10)),
        "opSizeBytes": {
            "count": len(sizes),
            "total": sum(sizes),
            "p50": pct(sizes, 0.5),
            "p90": pct(sizes, 0.9),
            "max": sizes[-1] if sizes else 0,
        },
        "msnLag": {
            "mean": round(sum(msn_lags) / n, 1) if n else 0,
            "max": max(msn_lags, default=0),
        },
        "durationSeconds": round(duration, 3),
        "opsPerSecond": round(n / duration, 1) if duration > 0 else None,
    }


def main() -> None:  # pragma: no cover - CLI shim
    import sys

    from ..drivers.file_driver import message_from_json

    path = sys.argv[1]
    with open(path) as f:
        msgs = [message_from_json(m) for m in json.load(f)]
    print(json.dumps(analyze_messages(msgs), indent=1, default=str))


if __name__ == "__main__":  # pragma: no cover
    main()
