"""Framework / public API layer (the reference's packages/framework/*,
azure/packages/* — what app developers actually touch).

- `fluid_static`: ContainerSchema + FluidContainer + TpuClient — the
  service-agnostic simple API (framework/fluid-static, azure-client).
- `data_object`: class-based app objects rooted on a SharedDirectory
  (framework/aqueduct).
- `undo_redo`: operation-grouped undo/redo stacks over DDS revertibles
  (framework/undo-redo).
- `attributor`: who-wrote-what, seq → {client, timestamp}
  (framework/attributor).
- `agent_scheduler`: distributed singleton task election
  (framework/agent-scheduler).
"""

from .fluid_static import ContainerSchema, FluidContainer, TpuClient
from .data_object import DataObject, DataObjectFactory
from .undo_redo import UndoRedoStackManager
from .attributor import Attributor
from .agent_scheduler import AgentScheduler

__all__ = [
    "AgentScheduler",
    "Attributor",
    "ContainerSchema",
    "DataObject",
    "DataObjectFactory",
    "FluidContainer",
    "TpuClient",
    "UndoRedoStackManager",
]
