"""AgentScheduler: distributed-singleton task election.

Mirrors `@fluidframework/agent-scheduler`
(framework/agent-scheduler/src/scheduler.ts): clients `pick` tasks
with a worker callback; exactly one connected client runs each task at
a time, and tasks fail over when their holder leaves. Built on the
TaskManager DDS's volunteer queues (the reference builds on
ConsensusRegisterCollection — same server-ack election, newer DDS).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from ..dds.consensus import TaskManager

LEADER_TASK = "__leader__"


class AgentScheduler:
    def __init__(self, task_manager: TaskManager):
        self.tasks = task_manager
        self._workers: Dict[str, Callable[[], None]] = {}
        self._running: set = set()
        task_manager.on("queueChanged", self._evaluate)
        task_manager.on("assigned", lambda tid, cid: self._evaluate(tid))

    # ------------------------------------------------------------- picks

    def pick(self, task_id: str, worker: Callable[[], None]) -> None:
        """Volunteer to run `task_id`; `worker()` fires when (and each
        time) this client becomes the assignee."""
        self._workers[task_id] = worker
        self.tasks.volunteer_for_task(task_id)

    def release(self, task_id: str) -> None:
        self._workers.pop(task_id, None)
        self._running.discard(task_id)
        self.tasks.abandon(task_id)

    def picked(self, task_id: str) -> bool:
        return self.tasks.assigned(task_id)

    def _evaluate(self, task_id: str) -> None:
        worker = self._workers.get(task_id)
        if worker is None:
            return
        if self.tasks.assigned(task_id):
            if task_id not in self._running:
                self._running.add(task_id)
                worker()
        else:
            self._running.discard(task_id)

    # ---------------------------------------------------------- leadership

    def volunteer_for_leadership(self, on_leader: Callable[[], None]) -> None:
        """The oldest-volunteer leadership pattern the reference's
        LeaderElection builds on agent-scheduler."""
        self.pick(LEADER_TASK, on_leader)

    @property
    def is_leader(self) -> bool:
        return self.picked(LEADER_TASK)
