"""Attribution: who wrote what, when.

Mirrors `@fluid-experimental/attributor`
(framework/attributor/src/attributor.ts:42 + mixinAttributor): maps
sequence numbers to {client, timestamp} by observing the op stream,
with an interned, run-length-packed serialization (the role of the
reference's LZ4 + string-interning summary encoding,
src/lz4Encoder.ts / src/stringInterner.ts — here delta+interning,
which composes with the summary store's own compression).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

from ..protocol.messages import MessageType, SequencedMessage


class Attributor:
    def __init__(self):
        self.entries: Dict[int, dict] = {}  # seq -> {"client", "timestamp"}

    def record(self, seq: int, client: Any, timestamp: float) -> None:
        self.entries[seq] = {"client": client, "timestamp": timestamp}

    def get(self, seq: int) -> Optional[dict]:
        return self.entries.get(seq)

    def __len__(self) -> int:
        return len(self.entries)

    # ------------------------------------------------------ serialization

    def serialize(self) -> str:
        """Interned clients + delta-coded seqs/timestamps."""
        seqs = sorted(self.entries)
        clients: list = []
        index: Dict[Any, int] = {}
        c_ids, d_seqs, d_ts = [], [], []
        prev_seq, prev_ts = 0, 0
        for s in seqs:
            e = self.entries[s]
            c = e["client"]
            if c not in index:
                index[c] = len(clients)
                clients.append(c)
            c_ids.append(index[c])
            d_seqs.append(s - prev_seq)
            prev_seq = s
            ts = int(e["timestamp"] * 1000)
            d_ts.append(ts - prev_ts)
            prev_ts = ts
        return json.dumps(
            {"clients": clients, "seqs": d_seqs, "ts": d_ts, "cids": c_ids}
        )

    @classmethod
    def deserialize(cls, data: str) -> "Attributor":
        obj = json.loads(data)
        out = cls()
        seq, ts = 0, 0
        for ds, dt, ci in zip(obj["seqs"], obj["ts"], obj["cids"]):
            seq += ds
            ts += dt
            out.entries[seq] = {
                "client": obj["clients"][ci], "timestamp": ts / 1000
            }
        return out

    def serialize_packed(self) -> str:
        """Compressed summary form: interning + delta coding, then
        DEFLATE over the whole table (the reference's LZ4 encoder
        role, attributor/src/lz4Encoder.ts — zlib is this
        environment's codec), base64-armored for summary blobs."""
        import base64
        import zlib

        return base64.b64encode(
            zlib.compress(self.serialize().encode(), 6)
        ).decode()

    @classmethod
    def deserialize_packed(cls, data: str) -> "Attributor":
        import base64
        import zlib

        return cls.deserialize(
            zlib.decompress(base64.b64decode(data)).decode()
        )

    # ------------------------------------------------- segment bridge

    def entry_at(self, channel, pos: int) -> Optional[dict]:
        """{client, timestamp} for the character at visible position
        `pos` of an attribution-tracking sequence channel: the
        per-segment key (insert seq) resolves through this op-stream
        table (the attributionCollection.ts -> attributor.ts:42
        pipeline)."""
        return self.get(channel.attribution_at(pos))


def mixin_attributor(runtime) -> Attributor:
    """Attach an attributor to a container runtime's op stream
    (mixinAttributor role). Returns it; also sets `runtime.attributor`."""
    attributor = Attributor()

    def on_op(msg: SequencedMessage, local: bool) -> None:
        if msg.type == MessageType.OP:
            attributor.record(msg.sequence_number, msg.client_id, msg.timestamp)

    runtime.on("op", on_op)
    runtime.attributor = attributor
    return attributor
