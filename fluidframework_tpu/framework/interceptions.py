"""DDS interception wrappers (the @fluid-experimental/dds-interceptions
role, packages/framework/dds-interceptions): wrap a DDS so every
LOCAL edit is transformed — canonically, auto-attaching properties
(attribution tags) to sequence inserts/annotates and map sets —
without the calling code knowing.

The wrappers delegate everything else to the underlying channel, so
they drop into existing call sites (the reference's
createSharedStringWithInterception /
createSharedMapWithInterception factories)."""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional


class SharedStringWithInterception:
    """SharedString wrapper injecting properties into local edits
    (sequence/sharedStringWithInterception.ts)."""

    def __init__(self, shared_string,
                 props_interceptor: Callable[[Optional[dict]], dict]):
        self._s = shared_string
        self._intercept = props_interceptor

    def insert_text(self, pos: int, text: str,
                    props: Optional[dict] = None) -> None:
        self._s.insert_text(pos, text, props=self._intercept(props))

    def annotate_range(self, start: int, end: int, props: dict) -> None:
        self._s.annotate_range(start, end, self._intercept(props))

    def remove_range(self, start: int, end: int) -> None:
        self._s.remove_range(start, end)

    def __getattr__(self, name: str) -> Any:
        return getattr(self._s, name)


class SharedMapWithInterception:
    """SharedMap wrapper transforming values on local set
    (map/sharedMapWithInterception.ts)."""

    def __init__(self, shared_map,
                 set_interceptor: Callable[[str, Any], Any]):
        self._m = shared_map
        self._intercept = set_interceptor

    def set(self, key: str, value: Any) -> None:
        self._m.set(key, self._intercept(key, value))

    def __getattr__(self, name: str) -> Any:
        return getattr(self._m, name)


def create_attribution_interceptor(client_id_fn: Callable[[], Any],
                                   key: str = "author"):
    """Props interceptor stamping the local identity on every edit —
    the canonical interception use (attribution props)."""

    def interceptor(props: Optional[dict]) -> dict:
        out = dict(props or {})
        out.setdefault(key, client_id_fn())
        return out

    return interceptor
