"""Class-based app objects (the aqueduct role).

Mirrors `DataObject`/`PureDataObject` + `DataObjectFactory`
(framework/aqueduct/src/data-objects/dataObject.ts:22,
dataObjectFactory.ts): an app class rooted on a SharedDirectory with
initialize hooks — `initializing_first_time` on fresh create,
`initializing_from_existing` on load, `has_initialized` on both.
"""

from __future__ import annotations

from typing import Any, Optional, Type

from ..dds.map import DirectoryFactory, SharedDirectory
from ..runtime.datastore import DataStoreRuntime

ROOT_ID = "root"


class DataObject:
    """Base app object; `self.root` is its SharedDirectory."""

    def __init__(self, runtime: DataStoreRuntime):
        self.runtime = runtime
        self.root: Optional[SharedDirectory] = None

    # ---------------------------------------------------------- lifecycle

    def initializing_first_time(self, props: Any = None) -> None:  # pragma: no cover
        pass

    def initializing_from_existing(self) -> None:  # pragma: no cover
        pass

    def has_initialized(self) -> None:  # pragma: no cover
        pass


class DataObjectFactory:
    """Creates/loads a DataObject subclass over a datastore
    (aqueduct DataObjectFactory)."""

    def __init__(self, object_class: Type[DataObject],
                 extra_channels: Optional[list] = None):
        """`extra_channels`: [(channel_id, type_name)] created alongside
        the root directory on first create."""
        self.object_class = object_class
        self.extra_channels = extra_channels or []

    def create(self, runtime: DataStoreRuntime, props: Any = None) -> DataObject:
        obj = self.object_class(runtime)
        obj.root = runtime.create_channel(ROOT_ID, DirectoryFactory.type_name)
        for cid, tname in self.extra_channels:
            runtime.create_channel(cid, tname)
        obj.initializing_first_time(props)
        obj.has_initialized()
        return obj

    def load(self, runtime: DataStoreRuntime) -> DataObject:
        obj = self.object_class(runtime)
        obj.root = runtime.get_channel(ROOT_ID)
        obj.initializing_from_existing()
        obj.has_initialized()
        return obj
