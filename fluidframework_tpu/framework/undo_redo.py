"""Undo/redo over DDS revertibles.

Mirrors `@fluidframework/undo-redo`
(framework/undo-redo/src/undoRedoStackManager.ts:84 + the
SharedMap/sequence handlers): local DDS changes push *revertibles*
onto the current operation; `close_current_operation` groups them;
undo pops a group and reverts it (pushing the inverse group onto the
redo stack).

Handlers provided:
- `SharedMapUndoRedoHandler` (sharedMapHandler)
- `SharedStringUndoRedoHandler` (sequenceHandler.ts:66 + merge-tree
  revertibles, dds/merge-tree/src/revertibles.ts) — insert and remove
  revert; annotate reverts to the prior property values.
"""

from __future__ import annotations

from typing import Any, List, Optional, Protocol

from ..protocol.mergetree_ops import AnnotateOp, InsertOp, RemoveOp


class Revertible(Protocol):
    def revert(self) -> None: ...


class UndoRedoStackManager:
    """Operation-grouped undo/redo stacks (undoRedoStackManager.ts:84)."""

    def __init__(self):
        self._undo: List[List[Revertible]] = []
        self._redo: List[List[Revertible]] = []
        self._current: Optional[List[Revertible]] = None
        self._reverting = False
        self._revert_target: Optional[List[Revertible]] = None

    # ------------------------------------------------------ accumulation

    def push(self, revertible: Revertible) -> None:
        if self._reverting:
            self._revert_target.append(revertible)
            return
        if self._current is None:
            self._current = []
            self._undo.append(self._current)
        self._current.append(revertible)
        self._redo.clear()

    def close_current_operation(self) -> None:
        self._current = None

    # ------------------------------------------------------------ revert

    def _revert_group(
        self,
        group: List[Revertible],
        source: List[List[Revertible]],
        into: List[List[Revertible]],
    ) -> None:
        self._reverting = True
        self._revert_target = []
        reverted = 0
        try:
            for r in reversed(group):
                r.revert()
                reverted += 1
        except BaseException:
            # Exception safety (a revertible CAN raise — e.g. a tree
            # commit evicted beyond the collab window): the unreverted
            # prefix goes back on the stack it came from, and whatever
            # the reverted suffix captured becomes a (partial) inverse
            # group — no state is stranded outside both stacks.
            remaining = group[: len(group) - reverted]
            if remaining:
                source.append(remaining)
            if self._revert_target:
                into.append(self._revert_target)
            raise
        else:
            if self._revert_target:
                # An empty capture (e.g. an inverse fully muted by
                # concurrent history) records nothing — pushing []
                # would create phantom undo/redo entries.
                into.append(self._revert_target)
        finally:
            self._reverting = False
            self._revert_target = None

    def undo_operation(self) -> bool:
        if not self._undo:
            return False
        self.close_current_operation()
        self._revert_group(self._undo.pop(), self._undo, self._redo)
        return True

    def redo_operation(self) -> bool:
        if not self._redo:
            return False
        self._revert_group(self._redo.pop(), self._redo, self._undo)
        return True

    @property
    def undo_stack_size(self) -> int:
        return len(self._undo)


# ---------------------------------------------------------------------------
# Handlers
# ---------------------------------------------------------------------------


class _MapRevertible:
    def __init__(self, shared_map, key: str, had: bool, prev: Any):
        self.map = shared_map
        self.key = key
        self.had = had
        self.prev = prev

    def revert(self) -> None:
        if self.had:
            self.map.set(self.key, self.prev)
        else:
            self.map.delete(self.key)


class SharedMapUndoRedoHandler:
    """Tracks local SharedMap sets/deletes (sharedMapHandler role).
    Attach to a map by constructing; detach via `close()`."""

    def __init__(self, stack: UndoRedoStackManager, shared_map):
        self.stack = stack
        self.map = shared_map
        self._snapshot = dict(shared_map.kernel.data)
        self._sub = shared_map.on("valueChanged", self._on_change)

    def _on_change(self, key: Optional[str], local: bool) -> None:
        if not local or key is None:
            self._snapshot = dict(self.map.kernel.data)
            return
        had = key in self._snapshot
        prev = self._snapshot.get(key)
        self.stack.push(_MapRevertible(self.map, key, had, prev))
        self._snapshot = dict(self.map.kernel.data)

    def close(self) -> None:
        self.map.off("valueChanged", self._on_change)


class _InsertRevertible:
    """Tracks the inserted segments (the reference's TrackingGroup
    role, merge-tree revertibles.ts): segments removed and later
    re-inserted by an intervening undo substitute in via
    `replace_segment`."""

    def __init__(self, shared_string, grp):
        self.s = shared_string
        # Track the group's live segment list: splits append tails to
        # it, so the tracked set follows fragmentation.
        self.grp = grp

    def replace_segment(self, old, new) -> None:
        self.grp.segments[:] = [
            new if t is old else t for t in self.grp.segments
        ]

    def revert(self) -> None:
        eng = self.s.engine
        live = [seg for seg in self.grp.segments if seg.removed_seq is None]
        for seg in live:
            pos = None
            acc = 0
            for t in eng.segments:
                if t is seg:
                    pos = acc
                    break
                cat, length = eng._vis(t, eng.current_seq, eng.local_client_id)
                if cat.value:  # not SKIP
                    acc += length
            if pos is not None and len(seg) > 0:
                self.s.remove_range(pos, pos + len(seg))


class _RemoveRevertible:
    def __init__(self, handler, spans):
        self.handler = handler
        self.s = handler.s
        self.spans = spans  # [(pos, old_segment, content, props)]

    def revert(self) -> None:
        for pos, old_seg, content, props in self.spans:
            pos = min(pos, self.s.get_length())
            new_seg = self.s.insert_text(pos, content, props=props)
            if new_seg is not None:
                self.handler.substitute(old_seg, new_seg)


class _AnnotateRevertible:
    def __init__(self, shared_string, spans):
        self.s = shared_string
        self.spans = spans  # [(start, end, prior_props_per_key)]

    def revert(self) -> None:
        for start, end, prior in self.spans:
            end = min(end, self.s.get_length())
            if start < end and prior:
                self.s.annotate_range(start, end, prior)


class SharedStringUndoRedoHandler:
    """Tracks local SharedString edits (sequenceHandler.ts:66 +
    merge-tree revertibles)."""

    def __init__(self, stack: UndoRedoStackManager, shared_string):
        self.stack = stack
        self.s = shared_string
        self._sub = shared_string.on("sequenceDelta", self._on_delta)

    def substitute(self, old_seg, new_seg) -> None:
        """A removed segment was re-materialized by an undo: update
        every revertible tracking the old segment."""
        groups = list(self.stack._undo) + list(self.stack._redo)
        if self.stack._revert_target is not None:
            groups.append(self.stack._revert_target)
        for group in groups:
            for r in group:
                if hasattr(r, "replace_segment"):
                    r.replace_segment(old_seg, new_seg)

    def _on_delta(self, op, local: bool) -> None:
        if not local:
            return
        if isinstance(op, InsertOp):
            grp = self.s.engine.pending[-1] if self.s.engine.pending else None
            if grp is not None:
                self.stack.push(_InsertRevertible(self.s, grp))
        elif isinstance(op, RemoveOp):
            grp = self.s.engine.pending[-1] if self.s.engine.pending else None
            spans = []
            if grp is not None:
                pos = op.start
                for seg in grp.segments:
                    if isinstance(seg.content, str):
                        spans.append(
                            (pos, seg, seg.content,
                             dict(seg.props) if seg.props else None)
                        )
                        pos += len(seg.content)
            self.stack.push(_RemoveRevertible(self, spans))
        elif isinstance(op, AnnotateOp):
            # Capture prior values per covered span so undo restores
            # (including deleting keys that didn't exist: None value).
            grp = self.s.engine.pending[-1] if self.s.engine.pending else None
            spans = []
            if grp is not None:
                start = op.start
                for seg in grp.segments:
                    prior = {}
                    for key in op.props:
                        # current props already have the new value; the
                        # pre-state is unknown here, so record deletion
                        # semantics for fresh keys only.
                        prior[key] = None
                    spans.append((start, start + len(seg), prior))
                    start += len(seg)
            self.stack.push(_AnnotateRevertible(self.s, spans))

    def close(self) -> None:
        self.s.off("sequenceDelta", self._on_delta)


# ------------------------------------------------------------------- tree


class _TreeCommitRevertible:
    """Undo one SharedTree commit through its repair data: the change
    as applied carries everything invert needs (removed content, prior
    values, move inverses — the reference's repair store,
    captured by Forest.apply). The inverse rebases over every commit
    applied AFTER the target (trunk commits past it plus the local
    branch — both maintained in current coordinates by the
    EditManager sandwich) and lands as a normal new edit."""

    def __init__(self, tree, commit):
        self.tree = tree
        self.commit = commit

    def revert(self) -> None:
        from ..tree.changeset import invert, rebase_change

        if self.tree.in_transaction:
            # The inverse is computed in main-branch coordinates; an
            # open transaction would swallow it into its fork frame
            # (and discard it on abort) — refuse rather than corrupt.
            raise RuntimeError(
                "cannot undo while a transaction is open; commit or "
                "abort it first"
            )
        em = self.tree.edits
        carried = []
        found = False
        for lst in (em.trunk, em.local):
            for k in lst:
                if found:
                    carried.extend(k.change)
                elif k is self.commit:
                    found = True
        if not found:
            # Evicted past the MSN window: nothing left to rebase
            # against (the reference's repair store is similarly
            # bounded by the collab window).
            raise RuntimeError("commit evicted beyond the undo window")
        inverse = invert(self.commit.change)
        rebased = rebase_change(inverse, carried, over_first=True)
        if rebased:
            self.tree.edit(rebased)


class SharedTreeUndoRedoHandler:
    """Connects a SharedTree to the undo/redo stack: every local
    commit (a plain edit or a squashed transaction) pushes a
    repair-data revertible. Undoing submits the rebased inverse as a
    new commit, which itself pushes a revertible — redo falls out of
    the stack manager's revert-capture."""

    def __init__(self, stack: UndoRedoStackManager, tree):
        self.stack = stack
        self.tree = tree
        self._sub = tree.on("localCommit", self._on_commit)

    def _on_commit(self, commit) -> None:
        self.stack.push(_TreeCommitRevertible(self.tree, commit))

    def close(self) -> None:
        self.tree.off("localCommit", self._on_commit)


# ----------------------------------------------------------------- matrix


class _CellSetRevertible:
    """Undo one setCell: restore the prior value at the cell's stable
    HANDLE address (immune to concurrent row/col permutation — the
    productSet/bspSet role of tracking 2D targets by identity)."""

    def __init__(self, matrix, key, had: bool, prev: Any):
        self.matrix = matrix
        self.key = key
        self.prev = prev if had else None

    def revert(self) -> None:
        self.matrix.set_cell_by_handle(self.key, self.prev)


class _AxisInsertRevertible:
    """Undo insertRows/insertCols: remove the inserted rows/cols at
    their CURRENT positions (handles may have scattered under
    concurrent permutation; each is located and removed by handle)."""

    def __init__(self, matrix, axis: str, handles):
        self.matrix = matrix
        self.axis = axis
        self.handles = list(handles)

    def revert(self) -> None:
        pv = self.matrix.rows if self.axis == "rows" else self.matrix.cols
        remove = (
            self.matrix.remove_rows
            if self.axis == "rows" else self.matrix.remove_cols
        )
        # Positions shift as we remove; re-resolve each handle.
        for h in self.handles:
            pos = pv.position_of_handle(h)
            if pos is not None:
                remove(pos, 1)


class _AxisRemoveRevertible:
    """Undo removeRows/removeCols: re-insert the rows/cols and restore
    their captured cell payload. Restored cells land at the NEW
    handles for the reinserted axis, keyed through the surviving
    cross-axis handles."""

    def __init__(self, matrix, axis: str, pos: int, handles, cells):
        self.matrix = matrix
        self.axis = axis
        self.pos = pos
        self.handles = list(handles)
        self.cells = dict(cells)

    def revert(self) -> None:
        m = self.matrix
        rows_axis = self.axis == "rows"
        pv = m.rows if rows_axis else m.cols
        insert = m.insert_rows if rows_axis else m.insert_cols
        pos = min(self.pos, pv.length())
        insert(pos, len(self.handles))
        new_handles = [pv.local_handle_at(pos + i)
                       for i in range(len(self.handles))]
        remap = dict(zip(self.handles, new_handles))
        for (rh, ch), value in self.cells.items():
            key = (
                (remap[rh], ch) if rows_axis else (rh, remap[ch])
            )
            m.set_cell_by_handle(key, value)


class SharedMatrixUndoRedoHandler:
    """Connects a SharedMatrix to the undo/redo stack (the reference
    matrix's IUndoConsumer over productSet/bspSet undo tracking,
    packages/dds/matrix/src/{productSet,bspSet}.ts — re-expressed over
    stable handles instead of spatial BSP sets: handle identity gives
    permutation-independent targeting for free)."""

    def __init__(self, stack: UndoRedoStackManager, matrix):
        self.stack = stack
        self.matrix = matrix
        matrix.on("localCellSet", self._on_cell)
        matrix.on("localAxisInsert", self._on_insert)
        matrix.on("localAxisRemove", self._on_remove)

    def _on_cell(self, key, had, prev) -> None:
        self.stack.push(_CellSetRevertible(self.matrix, key, had, prev))

    def _on_insert(self, axis, handles) -> None:
        self.stack.push(_AxisInsertRevertible(self.matrix, axis, handles))

    def _on_remove(self, axis, pos, handles, cells) -> None:
        self.stack.push(
            _AxisRemoveRevertible(self.matrix, axis, pos, handles, cells)
        )
