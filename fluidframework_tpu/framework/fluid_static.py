"""The service-agnostic simple API: schema-declared containers.

Mirrors `@fluidframework/fluid-static` + the service clients
(`AzureClient`/`TinyliciousClient`): a `ContainerSchema` declares the
initial objects (framework/fluid-static/src/types.ts:85), a
`FluidContainer` exposes them (src/fluidContainer.ts:201), and
`TpuClient` creates/loads containers against an ordering service
(azure/packages/azure-client/src/AzureClient.ts:51,77,144 — here the
service is anything with the LocalServer surface: connect /
upload_summary / download_summary).

The default channel registry includes every built-in DDS family, so
dynamic create of any type works out of the box (the reference's
`dynamicObjectTypes`).
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ..dds import (
    CellFactory,
    ConsensusQueueFactory,
    CounterFactory,
    DirectoryFactory,
    InkFactory,
    MapFactory,
    MatrixFactory,
    PactMapFactory,
    RegisterCollectionFactory,
    StringFactory,
    SummaryBlockFactory,
    TaskManagerFactory,
)
from ..runtime import ChannelRegistry, ContainerRuntime
from ..runtime.summary import SummaryTree
from ..utils.events import EventEmitter

DEFAULT_DATASTORE = "default"


def default_registry() -> ChannelRegistry:
    return ChannelRegistry(
        [
            MapFactory(),
            DirectoryFactory(),
            CellFactory(),
            CounterFactory(),
            StringFactory(),
            MatrixFactory(),
            ConsensusQueueFactory(),
            RegisterCollectionFactory(),
            TaskManagerFactory(),
            PactMapFactory(),
            InkFactory(),
            SummaryBlockFactory(),
        ]
    )


@dataclass
class ContainerSchema:
    """{name: DDS type} for the objects every container of this schema
    starts with (reference ContainerSchema.initialObjects, types.ts:85).
    Values may be factory classes, factory instances, or type-name
    strings."""

    initial_objects: Dict[str, Any] = field(default_factory=dict)

    def type_name(self, value: Any) -> str:
        if isinstance(value, str):
            return value
        if isinstance(value, type):
            return value.type_name
        return value.type_name


class FluidContainer(EventEmitter):
    """App-facing container wrapper (fluidContainer.ts:201)."""

    def __init__(self, runtime: ContainerRuntime, schema: ContainerSchema,
                 client: "TpuClient", doc_id: Optional[str] = None):
        super().__init__()
        self.runtime = runtime
        self.schema = schema
        self._client = client
        self.doc_id = doc_id
        runtime.on("connected", lambda cid: self.emit("connected", cid))
        runtime.on("disconnected", lambda: self.emit("disconnected"))
        runtime.on("saved", lambda: self.emit("saved"))

    @property
    def initial_objects(self) -> Dict[str, Any]:
        ds = self.runtime.get_datastore(DEFAULT_DATASTORE)
        return {name: ds.get_channel(name) for name in self.schema.initial_objects}

    def create(self, type_name_or_factory: Any, channel_id: Optional[str] = None):
        """Dynamically create another DDS (FluidContainer.create)."""
        tname = self.schema.type_name(type_name_or_factory)
        ds = self.runtime.get_datastore(DEFAULT_DATASTORE)
        cid = channel_id or f"dyn-{uuid.uuid4().hex[:8]}"
        ch = ds.create_channel(cid, tname)
        if self.runtime.connection is not None:
            # Announce first so the attach op sequences ahead of the
            # channel's own ops, then go live.
            self.runtime.submit_attach_op(DEFAULT_DATASTORE, ch)
            ds.attach_channel(ch)
            ch.on_connected()
        return ch

    @property
    def attach_state(self) -> str:
        return "Attached" if self.doc_id is not None else "Detached"

    @property
    def is_dirty(self) -> bool:
        return self.runtime.is_dirty

    def attach(self, doc_id: Optional[str] = None) -> str:
        """Promote a detached container to a live document
        (container.ts:1056 attach): persist the attach summary, then
        connect."""
        if self.doc_id is not None:
            raise RuntimeError("already attached")
        return self._client._attach(self, doc_id)

    def connect(self) -> None:
        self._client._connect(self)

    def disconnect(self) -> None:
        self.runtime.disconnect()

    def flush(self) -> None:
        self.runtime.flush()

    def dispose(self) -> None:
        self.runtime.disconnect()
        self.emit("disposed")


class InsecureTokenProvider:
    """Signs per-document tokens locally with the tenant key — the
    tinylicious-client `InsecureTokenProvider` role (the key lives in
    the client, so dev/test only; a production provider fetches tokens
    from a secure service instead, the `ITokenProvider` contract of
    AzureClient.ts:51)."""

    def __init__(self, tenant_id: str, key: str,
                 user: Optional[dict] = None,
                 scopes: Optional[list] = None,
                 lifetime_s: float = 3600.0):
        from ..server.riddler import SCOPE_READ, SCOPE_WRITE

        self.tenant_id = tenant_id
        self.key = key
        self.user = user or {"id": "insecure-user"}
        self.scopes = list(scopes or [SCOPE_READ, SCOPE_WRITE])
        self.lifetime_s = lifetime_s
        self._cache: dict = {}  # doc_id -> (expiry, token)

    def credentials_for(self, doc_id: str):
        import time as _time

        from ..server.riddler import sign_token

        # Cache per document until near expiry: signing (JSON + HMAC +
        # base64) stays off the per-submit hot path while the rotation
        # seam keeps long-lived connections alive past expiry.
        now = _time.time()
        hit = self._cache.get(doc_id)
        if hit is not None and now < hit[0]:
            return self.tenant_id, hit[1]
        token = sign_token(
            self.key, self.tenant_id, doc_id, self.scopes, self.user,
            lifetime_s=self.lifetime_s, now=now,
        )
        self._cache[doc_id] = (now + self.lifetime_s * 0.8, token)
        return self.tenant_id, token


class TpuClient:
    """Service client (AzureClient.ts:51 shape) over any server with
    the LocalServer surface, or over the TCP `SocketDriver` surface.

    `token_provider`: an object with ``credentials_for(doc_id) ->
    (tenant_id, token)`` (e.g. `InsecureTokenProvider`). When given,
    it threads through to the driver so every request carries fresh
    per-document credentials — the AzureClient token-provider seam."""

    def __init__(self, server, registry: Optional[ChannelRegistry] = None,
                 token_provider=None):
        self.server = server
        self.registry = registry or default_registry()
        if token_provider is not None:
            if not hasattr(server, "token_provider"):
                raise TypeError(
                    "this server surface has no credential seam; "
                    "connect a SocketDriver to use a token provider"
                )
            if server.token_provider is not token_provider:
                # Never silently change a shared driver's credentials
                # (another provider OR static tenant credentials —
                # other users of the driver would start acting under
                # this client's identity). Re-attaching the SAME
                # provider is an idempotent no-op.
                has = getattr(server, "has_credentials", None)
                if has() if has is not None else (
                    server.token_provider is not None
                ):
                    raise ValueError(
                        "driver already carries credentials; "
                        "construct a dedicated SocketDriver (or pass "
                        "token_provider to it directly)"
                    )
                server.token_provider = token_provider

    # ------------------------------------------------------------ create

    def create_container(self, schema: ContainerSchema) -> FluidContainer:
        """A detached container with the schema's initial objects
        (AzureClient.createContainer :77)."""
        rt = ContainerRuntime(self.registry)
        ds = rt.create_datastore(DEFAULT_DATASTORE)
        for name, t in schema.initial_objects.items():
            ds.create_channel(name, schema.type_name(t))
        return FluidContainer(rt, schema, self)

    def _attach(self, container: FluidContainer, doc_id: Optional[str]) -> str:
        doc_id = doc_id or uuid.uuid4().hex[:12]
        wire = container.runtime.summarize().to_json()
        if hasattr(self.server, "create_document"):
            # Driver surface (SocketDriver over TCP): the server-side
            # historian/storage owns the summary handle.
            self.server.create_document(doc_id, wire)
        else:
            handle = self.server.upload_summary(wire)
            self.server.storage.set_ref(doc_id, handle)
        container.doc_id = doc_id
        self._connect(container)
        return doc_id

    def _connect(self, container: FluidContainer) -> None:
        assert container.doc_id is not None, "attach before connecting"
        container.runtime.connect(self.server.connect(container.doc_id))

    # -------------------------------------------------------------- load

    def get_container(self, doc_id: str, schema: ContainerSchema) -> FluidContainer:
        """Load the latest summary and catch up (AzureClient
        .getContainer :144)."""
        rt = ContainerRuntime(self.registry)
        if hasattr(self.server, "load_document"):
            wire = self.server.load_document(doc_id)
        else:
            wire = self.server.download_summary(doc_id)
        if wire is None:
            raise KeyError(f"unknown document {doc_id!r}")
        rt.load(SummaryTree.from_json(wire))
        container = FluidContainer(rt, schema, self, doc_id=doc_id)
        self._connect(container)
        return container
