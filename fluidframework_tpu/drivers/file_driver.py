"""FileDriver: documents persisted as files.

Reference drivers/file-driver (fileDocumentService): summaries and op
streams stored in a directory —

    <root>/<doc_id>/summary.json
    <root>/<doc_id>/ops.jsonl      (one SequencedMessage per line)

Reading yields a read-only replay document (connect goes through an
internal ReplayDriver); `record()` captures a live document from any
other driver into files. Sequence ops are wire-encoded with
op_to_json, so recorded streams are plain JSON.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, List, Optional

from ..protocol.messages import MessageType, SequencedMessage
from ..protocol.mergetree_ops import op_from_json, op_to_json
from .replay_driver import ReplayDriver


def message_to_json(msg: SequencedMessage) -> dict:
    contents = msg.contents
    if isinstance(contents, dict):
        contents = _encode_contents(contents)
    return {
        "sequenceNumber": msg.sequence_number,
        "minimumSequenceNumber": msg.minimum_sequence_number,
        "clientId": msg.client_id,
        "clientSequenceNumber": msg.client_seq,
        "referenceSequenceNumber": msg.ref_seq,
        "type": msg.type.value,
        "contents": contents,
        "metadata": msg.metadata,
        "timestamp": msg.timestamp,
    }


def _encode_contents(contents: Any) -> Any:
    if isinstance(contents, dict):
        out = {}
        for k, v in contents.items():
            if k == "op" and dataclasses.is_dataclass(v):
                out[k] = op_to_json(v)
            elif isinstance(v, dict):
                out[k] = _encode_contents(v)
            else:
                out[k] = v
        return out
    return contents


def message_from_json(data: dict) -> SequencedMessage:
    return SequencedMessage(
        sequence_number=data["sequenceNumber"],
        minimum_sequence_number=data["minimumSequenceNumber"],
        client_id=data["clientId"],
        client_seq=data["clientSequenceNumber"],
        ref_seq=data["referenceSequenceNumber"],
        type=MessageType(data["type"]),
        contents=data["contents"],
        metadata=data["metadata"],
        timestamp=data.get("timestamp", 0.0),
    )


class FileDriver:
    def __init__(self, root: str):
        self.root = root
        self._replay: Optional[ReplayDriver] = None

    def _doc_dir(self, doc_id: str) -> str:
        return os.path.join(self.root, doc_id)

    # ----------------------------------------------------------- writing

    def record(self, doc_id: str, summary_wire: Optional[str],
               messages: List[SequencedMessage]) -> None:
        """Capture a document (snapshot + ops) to files — the fetch-tool
        / recorded-document workflow."""
        d = self._doc_dir(doc_id)
        os.makedirs(d, exist_ok=True)
        if summary_wire is not None:
            with open(os.path.join(d, "summary.json"), "w") as f:
                f.write(summary_wire)
        with open(os.path.join(d, "ops.jsonl"), "w") as f:
            for m in messages:
                f.write(json.dumps(message_to_json(m)) + "\n")
        self._replay = None  # invalidate cache

    # ----------------------------------------------------- driver surface

    def _ensure_replay(self) -> ReplayDriver:
        if self._replay is None:
            streams, summaries = {}, {}
            if os.path.isdir(self.root):
                for doc_id in os.listdir(self.root):
                    d = self._doc_dir(doc_id)
                    ops_path = os.path.join(d, "ops.jsonl")
                    if os.path.exists(ops_path):
                        with open(ops_path) as f:
                            streams[doc_id] = [
                                message_from_json(json.loads(line))
                                for line in f if line.strip()
                            ]
                    s_path = os.path.join(d, "summary.json")
                    if os.path.exists(s_path):
                        with open(s_path) as f:
                            summaries[doc_id] = f.read()
            self._replay = ReplayDriver(streams, summaries)
        return self._replay

    def create_document(self, doc_id: str, summary_wire: str) -> None:
        self.record(doc_id, summary_wire, [])

    def load_document(self, doc_id: str) -> Optional[str]:
        return self._ensure_replay().load_document(doc_id)

    def connect(self, doc_id: str, client_id: Optional[int] = None):
        return self._ensure_replay().connect(doc_id, client_id)

    def ops_from(self, doc_id: str, from_seq: int,
                 to_seq: Optional[int] = None) -> List[SequencedMessage]:
        return self._ensure_replay().ops_from(doc_id, from_seq, to_seq=to_seq)

    # --------------------------------------------------------- controller

    def replay_all(self, doc_id: str) -> int:
        return self._ensure_replay().replay_all(doc_id)

    def step(self, doc_id: str, count: int = 1) -> int:
        return self._ensure_replay().step(doc_id, count)
