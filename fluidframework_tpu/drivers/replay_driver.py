"""ReplayDriver: read-only playback of a recorded op stream.

Reference drivers/replay-driver (ReplayController,
replayDocumentDeltaConnection.ts): a container connects to a recorded
document and receives the stream up to a controllable watermark —
`replay_to(seq)` / `replay_all()` / `step(n)` — never submitting.
This is the transport behind benchmark config 2 (1024-client replay)
and the replay-tool workflows.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..protocol.messages import SequencedMessage
from ..utils.events import BufferedListener


class _ReplayConnection(BufferedListener):
    """Read-only connection: delivery is driven by the controller."""

    def __init__(self, driver: "ReplayDriver", doc_id: str):
        super().__init__()
        self.driver = driver
        self.doc_id = doc_id
        self.client_id = -999  # never matches any recorded op's author
        self.nack_listener = None
        self.connected = True
        self.join_seq = 0  # deliver everything from the start

    def submit(self, msg) -> None:
        raise RuntimeError("replay documents are read-only")

    def catch_up(self, from_seq: int) -> List[SequencedMessage]:
        return []  # the controller pushes; no pull-gap exists

    def disconnect(self) -> None:
        self.connected = False


class ReplayDriver:
    def __init__(self, streams: Dict[str, List[SequencedMessage]],
                 summaries: Optional[Dict[str, str]] = None):
        """`streams`: doc id → full recorded sequenced stream;
        `summaries`: optional doc id → summary wire to boot from (ops
        below the summary's seq are skipped on delivery)."""
        self.streams = streams
        self.summaries = summaries or {}
        self._connections: Dict[str, List[_ReplayConnection]] = {}
        self._watermark: Dict[str, int] = {}

    # ----------------------------------------------------- driver surface

    def create_document(self, doc_id: str, summary_wire: str) -> None:
        raise RuntimeError("replay documents are read-only")

    def load_document(self, doc_id: str) -> Optional[str]:
        return self.summaries.get(doc_id)

    def connect(self, doc_id: str, client_id: Optional[int] = None):
        conn = _ReplayConnection(self, doc_id)
        self._connections.setdefault(doc_id, []).append(conn)
        return conn

    def ops_from(self, doc_id: str, from_seq: int,
                 to_seq: Optional[int] = None) -> List[SequencedMessage]:
        mark = self._watermark.get(doc_id, 0)
        if to_seq is not None:
            mark = min(mark, to_seq)
        return [
            m for m in self.streams.get(doc_id, [])
            if from_seq < m.sequence_number <= mark
        ]

    # --------------------------------------------------------- controller

    def replay_to(self, doc_id: str, seq: int) -> int:
        """Deliver recorded ops with sequence number <= seq; returns
        the number delivered (ReplayController.replay)."""
        mark = self._watermark.get(doc_id, 0)
        batch = [
            m for m in self.streams.get(doc_id, [])
            if mark < m.sequence_number <= seq
        ]
        for msg in batch:
            for conn in self._connections.get(doc_id, []):
                if conn.connected:
                    conn._dispatch(msg)
        if batch:
            self._watermark[doc_id] = batch[-1].sequence_number
        return len(batch)

    def replay_all(self, doc_id: str) -> int:
        stream = self.streams.get(doc_id, [])
        if not stream:
            return 0
        return self.replay_to(doc_id, stream[-1].sequence_number)

    def step(self, doc_id: str, count: int = 1) -> int:
        mark = self._watermark.get(doc_id, 0)
        remaining = [
            m for m in self.streams.get(doc_id, []) if m.sequence_number > mark
        ]
        if not remaining:
            return 0
        return self.replay_to(doc_id, remaining[: count][-1].sequence_number)
