"""Fault-injection driver wrapper.

Reference packages/test/test-service-load/src/faultInjectionDriver.ts
(:27 factory, :149 delta connection): wraps any driver and injects
failures — dropped connections, submit errors — to exercise the
reconnect/rebase/recovery machinery under test control.
"""

from __future__ import annotations

from typing import Any, List, Optional


class _FaultConnection:
    def __init__(self, inner, driver: "FaultInjectionDriver"):
        self._inner = inner
        self._driver = driver

    # passthrough surface
    @property
    def client_id(self):
        return self._inner.client_id

    @property
    def connected(self):
        return self._inner.connected

    @property
    def listener(self):
        return self._inner.listener

    @listener.setter
    def listener(self, fn):
        self._inner.listener = fn

    @property
    def nack_listener(self):
        return self._inner.nack_listener

    @nack_listener.setter
    def nack_listener(self, fn):
        self._inner.nack_listener = fn

    @property
    def disconnect_listener(self):
        return self._inner.disconnect_listener

    @disconnect_listener.setter
    def disconnect_listener(self, fn):
        self._inner.disconnect_listener = fn

    def catch_up(self, from_seq: int):
        return self._inner.catch_up(from_seq)

    def submit(self, msg) -> None:
        if self._driver.submits_fail:
            raise ConnectionError("injected submit failure")
        if self._driver.drop_submits:
            return  # silently lost (network partition)
        self._inner.submit(msg)

    def submit_batch(self, msgs) -> None:
        if self._driver.submits_fail:
            raise ConnectionError("injected submit failure")
        if self._driver.drop_submits:
            return  # silently lost (network partition)
        self._inner.submit_batch(msgs)

    def disconnect(self) -> None:
        self._inner.disconnect()

    # fault controls (injectDisconnect / injectError)
    def inject_disconnect(self) -> None:
        self._inner.disconnect()


class FaultInjectionDriver:
    def __init__(self, inner):
        self.inner = inner
        self.connections: List[_FaultConnection] = []
        self.submits_fail = False
        self.drop_submits = False
        # Next N connect() calls raise ConnectionError (exercises the
        # reconnect backoff ladder, connectionManager.ts:170).
        self.connects_fail_remaining = 0

    # ----------------------------------------------------- driver surface

    def create_document(self, doc_id: str, summary_wire: str) -> None:
        self.inner.create_document(doc_id, summary_wire)

    def load_document(self, doc_id: str) -> Optional[str]:
        return self.inner.load_document(doc_id)

    def connect(self, doc_id: str, client_id: Optional[int] = None):
        if self.connects_fail_remaining > 0:
            self.connects_fail_remaining -= 1
            raise ConnectionError("injected connect failure")
        conn = _FaultConnection(self.inner.connect(doc_id, client_id), self)
        self.connections.append(conn)
        return conn

    def ops_from(self, doc_id: str, from_seq: int,
                 to_seq: Optional[int] = None):
        if to_seq is not None:
            try:
                return self.inner.ops_from(doc_id, from_seq, to_seq=to_seq)
            except TypeError:
                pass  # wrapped driver predates ranged reads
        return self.inner.ops_from(doc_id, from_seq)

    def upload_blob(self, doc_id: str, data: bytes) -> str:
        if self.submits_fail:
            raise ConnectionError("injected blob upload failure")
        return self.inner.upload_blob(doc_id, data)

    def read_blob(self, doc_id: str, blob_id: str) -> bytes:
        return self.inner.read_blob(doc_id, blob_id)

    # -------------------------------------------------- credential seam

    @property
    def token_provider(self) -> Any:
        """Delegated to the wrapped driver in BOTH directions (the
        CachedDriver lesson, ADVICE.md round 5): an assignment landing
        on the wrapper would leave the inner driver unauthenticated.
        Raises AttributeError when the inner driver has no credential
        seam so `hasattr` checks stay truthful."""
        return self.inner.token_provider

    @token_provider.setter
    def token_provider(self, value: Any) -> None:
        if not hasattr(self.inner, "token_provider"):
            raise AttributeError(
                "wrapped driver has no token_provider seam"
            )
        self.inner.token_provider = value

    def __getattr__(self, name: str) -> Any:
        # Forward anything else (has_credentials, driver extensions) so
        # fault injection composes as a first-class resilience layer,
        # not just a test prop.
        return getattr(self.inner, name)

    # ------------------------------------------------------ fault controls

    def disconnect_all(self) -> None:
        """Drop every live connection (random client kill)."""
        for conn in list(self.connections):
            if conn.connected:
                conn.inject_disconnect()
