"""Drivers: service adapters behind the document-service surface.

The reference's packages/drivers/* role (SURVEY.md §1 L2 — the
process/network boundary). Every driver exposes:

    create_document(doc_id, summary_wire)
    load_document(doc_id) -> summary_wire | None
    connect(doc_id, client_id=None) -> connection
    ops_from(doc_id, from_seq) -> [SequencedMessage]

- `LocalDriver` — straight onto an in-proc LocalServer
  (drivers/local-driver).
- `ReplayDriver` — read-only playback of a recorded op stream with
  stepping (drivers/replay-driver; benchmark config 2's transport).
- `FileDriver` — snapshot+ops persisted to a directory
  (drivers/file-driver, used by the replay tooling).
- `FaultInjectionDriver` — wraps any driver; drops connections and
  injects submit failures on demand
  (test-service-load/src/faultInjectionDriver.ts:27).
"""

from .local_driver import LocalDriver
from .replay_driver import ReplayDriver
from .file_driver import FileDriver
from .fault_injection import FaultInjectionDriver
from .web_cache import CachedDriver

__all__ = ["CachedDriver", "FaultInjectionDriver", "FileDriver", "LocalDriver", "ReplayDriver"]
