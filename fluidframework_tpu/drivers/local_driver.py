"""LocalDriver: the in-proc service adapter.

Reference drivers/local-driver (LocalDocumentServiceFactory →
LocalDeltaConnectionServer): binds the loader to a LocalServer (full
lambda pipeline) or LocalOrderingService instance.
"""

from __future__ import annotations

from typing import List, Optional

from ..protocol.messages import SequencedMessage


class LocalDriver:
    def __init__(self, server):
        self.server = server

    def create_document(self, doc_id: str, summary_wire: str) -> None:
        handle = self.server.upload_summary(summary_wire)
        self.server.storage.set_ref(doc_id, handle)

    def load_document(self, doc_id: str) -> Optional[str]:
        return self.server.download_summary(doc_id)

    def connect(self, doc_id: str, client_id: Optional[int] = None):
        return self.server.connect(doc_id, client_id)

    def ops_from(self, doc_id: str, from_seq: int,
                 to_seq: Optional[int] = None) -> List[SequencedMessage]:
        ops = self.server.ops_from(doc_id, from_seq)
        if to_seq is not None:
            ops = [m for m in ops if m.sequence_number <= to_seq]
        return ops

    def catchup(self, doc_id: str, from_seq: int = 0) -> dict:
        """Nearest summary + op tail in ONE call (the summary-service
        join shape): ``{"summary": wire|None, "summarySeq": s,
        "ops": [...tail past max(from_seq, s)]}``. `Loader.resolve`
        prefers this over load_document + full ops_from when the
        driver offers it."""
        return self.server.catchup(doc_id, from_seq)

    # Blob surface (reference IDocumentStorageService.createBlob/
    # readBlob — backed server-side by the content-addressed store).
    def upload_blob(self, doc_id: str, data: bytes) -> str:
        return self.server.storage.put(data)

    def read_blob(self, doc_id: str, blob_id: str) -> bytes:
        return self.server.storage.get(blob_id)
