"""Socket driver: the loader's service adapter over a real TCP boundary.

The client half of server/socket_service.py — the role of the
reference's `DocumentDeltaConnection` over socket.io
(drivers/driver-base/src/documentDeltaConnection.ts:42) plus the REST
storage calls of routerlicious-driver. Every driver call runs over
newline-delimited JSON frames; the delta connection holds a
long-lived socket with a reader thread that dispatches pushed "op" /
"nack" events, while storage/control calls use short-lived sockets.

Semantics match the in-proc drivers: buffered early ops (events that
arrive before a listener attaches are queued and drained on listener
assignment), catch_up over the join gap, and disconnect events
surfacing through disconnect_listener.
"""

from __future__ import annotations

import base64
import json
import queue
import socket
import threading
from typing import Any, Callable, List, Optional

from ..drivers.file_driver import message_from_json
from ..protocol.messages import DocumentMessage, NackMessage, SequencedMessage


class _Rpc:
    """One request/response exchange over a fresh socket. Credentials
    are the caller's business (SocketDriver._call merges them per
    document)."""

    def __init__(self, host: str, port: int):
        self.host, self.port = host, port

    def call(self, **req) -> Any:
        from ..server.framing import read_frame, write_frame

        with socket.create_connection((self.host, self.port)) as s:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            f = s.makefile("rwb")
            req.setdefault("id", 1)
            write_frame(f, req)
            resp = read_frame(f)
            if resp is None:
                raise ConnectionError("server closed during RPC")
            if "error" in resp:
                raise RuntimeError(f"server error: {resp['error']}")
            return resp["result"]


class _SocketConnection:
    """A live delta connection (long-lived socket + reader thread)."""

    def __init__(self, host: str, port: int, doc_id: str,
                 client_id: Optional[int], auth_factory=None):
        """`auth_factory`: zero-arg callable returning the CURRENT
        credentials dict (or None) — re-resolved on every request so a
        token provider can rotate tokens under a long-lived connection
        (the server re-authorizes every command)."""
        self._auth_factory = auth_factory
        self._doc_id = doc_id
        self._sock = socket.create_connection((host, port))
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._file = self._sock.makefile("rwb")
        self._req_id = 0
        self._pending_resp: dict = {}
        self._resp_cond = threading.Condition()
        self._listener: Optional[Callable[[SequencedMessage], None]] = None
        self.nack_listener: Optional[Callable[[NackMessage], None]] = None
        self.disconnect_listener: Optional[Callable[[], None]] = None
        self.connected = False
        self._early: List[SequencedMessage] = []
        self._lock = threading.RLock()
        self._wlock = threading.Lock()

        # Events are dispatched from a dedicated thread, NOT the socket
        # reader: a callback (nack -> disconnect, CollabWindowTracker
        # NOOP) may issue an RPC, and only the reader thread can
        # deliver RPC responses — running callbacks on the reader
        # would deadlock the wait loop forever.
        self._events: "queue.Queue" = queue.Queue()
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True
        )
        self._reader.start()
        self._dispatcher.start()
        info = self._call(cmd="connect", docId=doc_id, clientId=client_id)
        self.client_id = info["clientId"]
        self.join_seq = info["joinSeq"]
        # Live-stream continuity guard (the resilience layer for a
        # flaky fan-out edge): the last sequence number delivered to
        # the listener. A pushed op at/below it is a duplicate and is
        # dropped; one that jumps past last+1 reveals a gap, closed
        # with a ranged refetch before delivery (ops_from(from, to) —
        # the reference driver's deltaStorage catch-up read).
        self.last_seq = self.join_seq
        self.gap_refetches = 0
        self.dup_drops = 0
        self.connected = True

    # --------------------------------------------------------- framing

    def _call(self, **req) -> Any:
        auth = self._auth_factory() if self._auth_factory else None
        if auth:
            req.update(auth)
            req.setdefault("docId", self._doc_id)
        with self._resp_cond:
            self._req_id += 1
            rid = self._req_id
        req["id"] = rid
        if threading.current_thread() is self._reader:
            # All callbacks run on the dispatcher thread, so an RPC
            # from the reader is a bug — and it could never complete
            # (the reader can't wait on itself for the response).
            raise RuntimeError(
                "RPC from the socket reader thread would deadlock"
            )
        from ..server.framing import write_frame

        with self._wlock:  # dispatcher-thread callbacks may also submit
            write_frame(self._file, req)
        with self._resp_cond:
            while rid not in self._pending_resp:
                if not self._reader.is_alive():
                    raise ConnectionError("socket reader died")
                self._resp_cond.wait(timeout=10)
            resp = self._pending_resp.pop(rid)
        if "error" in resp:
            raise RuntimeError(f"server error: {resp['error']}")
        return resp["result"]

    def _read_loop(self) -> None:
        import json as _json

        from ..server.framing import KIND_OPS, read_frame_raw

        try:
            while True:
                raw = read_frame_raw(self._file)
                if raw is None:
                    break
                kind, body = raw
                if kind == KIND_OPS:
                    # Batched broadcast: routed WITHOUT parsing (the
                    # dispatcher defers the parse until a consumer is
                    # attached — idle fan-out costs no CPU).
                    self._events.put({"__raw_ops__": body})
                    continue
                frame = _json.loads(body)
                if "event" in frame:
                    self._events.put(frame)
                else:
                    with self._resp_cond:
                        self._pending_resp[frame["id"]] = frame
                        self._resp_cond.notify_all()
        except (OSError, ValueError):
            pass
        finally:
            was = self.connected  # False if disconnect() was local
            self.connected = False
            with self._resp_cond:
                self._resp_cond.notify_all()
            self._events.put({"__eof__": was})  # dispatcher exits

    def _dispatch_loop(self) -> None:
        """Drain pushed events in arrival order, off the reader thread."""
        while True:
            frame = self._events.get()
            if "__eof__" in frame:
                if frame["__eof__"]:
                    # Reader died without a local disconnect(): surface
                    # the transport loss (connectionManager.ts:170).
                    if self.disconnect_listener is not None:
                        self.disconnect_listener()
                return
            try:
                self._on_event(frame)
            except Exception:
                # A failing listener means this replica can no longer
                # trust its state (an op may be half-applied). Surface
                # it the way the old reader did: traceback + transport
                # teardown, so the container reconnects and catches up
                # rather than silently diverging.
                import traceback

                traceback.print_exc()
                try:
                    self.disconnect()
                except Exception:
                    pass
                return

    def _on_event(self, frame: dict) -> None:
        if "__raw_ops__" in frame:
            with self._lock:
                if self._listener is None:
                    # Wire bytes buffer as-is; decoded on attach.
                    self._early.append(frame["__raw_ops__"])
                    return
            import json as _json

            frame = _json.loads(frame["__raw_ops__"])
        if frame["event"] == "ops":
            # Batched broadcast (one frame per broadcaster pump —
            # fan-out cost amortizes across the room's ops).
            for m in frame["msgs"]:
                self._on_event({"event": "op", "msg": m})
            return
        if frame["event"] == "op":
            # The buffer-or-deliver decision is made under the lock
            # (serializing against the setter's early-op drain so ops
            # neither strand in _early nor overtake buffered ones);
            # the captured listener is invoked outside it so decode
            # stays off the critical section. Buffered ops stay in
            # WIRE form — decode defers until a consumer attaches.
            with self._lock:
                listener = self._listener
                if listener is None:
                    self._early.append(frame["msg"])
                    return
            self._deliver(frame["msg"], listener)
        elif frame["event"] == "nack":
            m = frame["msg"]
            if self.nack_listener is not None:
                self.nack_listener(
                    NackMessage(m["clientId"], m["clientSeq"], m["code"],
                                m["reason"])
                )

    # -------------------------------------------- connection surface

    @property
    def listener(self):
        return self._listener

    @listener.setter
    def listener(self, fn) -> None:
        # Draining buffered early ops on listener attach (the
        # driver-base early-op queue, documentDeltaConnection.ts),
        # under the same lock _on_event delivers with — attach-time
        # races can neither strand an op in _early nor reorder.
        with self._lock:
            self._listener = fn
            if fn is not None and self._early:
                early, self._early = self._early, []
                for m in early:
                    if isinstance(m, bytes):  # deferred ops frame
                        import json as _json

                        for w in _json.loads(m)["msgs"]:
                            self._deliver(w, fn)
                    else:
                        self._deliver(m, fn)

    def _deliver(self, wire: dict, fn) -> None:
        """Continuity-guarded delivery: drop duplicates, close gaps
        with a ranged refetch, and if a gap cannot be closed, tear the
        transport down rather than corrupt the replica."""
        seq = wire.get("sequenceNumber")
        if seq is None:
            fn(message_from_json(wire))
            return
        if seq <= self.last_seq:
            self.dup_drops += 1  # duplicated delivery: already applied
            return
        if seq > self.last_seq + 1:
            self.gap_refetches += 1
            try:
                missing = self._call(
                    cmd="ops_from", docId=self._doc_id,
                    fromSeq=self.last_seq, toSeq=seq - 1,
                )
            except Exception:
                missing = []
            for w in missing:
                if w["sequenceNumber"] > self.last_seq:
                    self.last_seq = w["sequenceNumber"]
                    fn(message_from_json(w))
            if self.last_seq < seq - 1:
                # The hole is not servable (mid-restart server):
                # applying this op out of order would silently diverge
                # the replica. Drop the connection; the container's
                # reconnect path catches up from durable storage.
                try:
                    self.disconnect()
                except Exception:
                    pass
                return
        self.last_seq = seq
        fn(message_from_json(wire))

    def submit(self, msg: DocumentMessage) -> None:
        from ..server.socket_service import document_message_to_json

        if not self.connected:
            raise RuntimeError("socket connection closed")
        self._call(cmd="submit", msg=document_message_to_json(msg))

    def submit_batch(self, msgs: List[DocumentMessage]) -> None:
        from ..server.socket_service import document_message_to_json

        if not self.connected:
            raise RuntimeError("socket connection closed")
        self._call(
            cmd="submit_batch",
            msgs=[document_message_to_json(m) for m in msgs],
        )

    def catch_up(self, from_seq: int) -> List[SequencedMessage]:
        return [
            message_from_json(m)
            for m in self._call(cmd="catch_up", fromSeq=from_seq)
        ]

    def disconnect(self) -> None:
        if not self.connected:
            return
        self.connected = False
        try:
            self._call(cmd="disconnect")
        except Exception:
            pass
        try:
            # shutdown unblocks the reader thread (a bare close can
            # leave a concurrent blocking read stuck on Linux).
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        if self.disconnect_listener is not None:
            self.disconnect_listener()


class SocketDriver:
    """Driver surface over TCP (create/load/connect/ops_from/blobs)."""

    def __init__(self, host: str, port: int,
                 tenant_id: Optional[str] = None,
                 token: Optional[str] = None,
                 token_provider=None):
        """`tenant_id`/`token`: static riddler credentials (signed
        per-document token; see server.riddler.sign_token) attached to
        every request when the server runs with a TenantManager.
        `token_provider`: the reference's ITokenProvider seam
        (AzureClient.ts:51 connection config): an object with
        ``credentials_for(doc_id) -> (tenant_id, token)`` resolving
        FRESH per-document credentials for each request — takes
        precedence over the static pair."""
        self.host, self.port = host, port
        self.token_provider = token_provider
        self._auth = (
            {"tenantId": tenant_id, "token": token} if token else None
        )
        self._rpc = _Rpc(host, port)

    def has_credentials(self) -> bool:
        """Does this driver already carry ANY credentials (a provider
        or a static tenant pair)? Public predicate so callers (e.g.
        TpuClient's provider guard) never reach into private state."""
        return self.token_provider is not None or self._auth is not None

    def _auth_for(self, doc_id: Optional[str]) -> Optional[dict]:
        if self.token_provider is not None and doc_id is not None:
            tenant_id, token = self.token_provider.credentials_for(doc_id)
            return {"tenantId": tenant_id, "token": token}
        return self._auth

    def _call(self, doc_id: Optional[str], **req) -> Any:
        auth = self._auth_for(doc_id)
        if auth:
            req.update(auth)
        return self._rpc.call(**req)

    def create_document(self, doc_id: str, summary_wire: str) -> None:
        self._call(doc_id, cmd="create_document", docId=doc_id,
                   summary=summary_wire)

    def load_document(self, doc_id: str) -> Optional[str]:
        return self._call(doc_id, cmd="load_document", docId=doc_id)

    def connect(self, doc_id: str, client_id: Optional[int] = None):
        # The connection re-resolves credentials per request (token
        # rotation under long-lived connections).
        return _SocketConnection(
            self.host, self.port, doc_id, client_id,
            lambda: self._auth_for(doc_id),
        )

    def ops_from(self, doc_id: str, from_seq: int,
                 to_seq: Optional[int] = None) -> List[SequencedMessage]:
        return [
            message_from_json(m)
            for m in self._call(doc_id, cmd="ops_from", docId=doc_id,
                                fromSeq=from_seq, toSeq=to_seq)
        ]

    def catchup(self, doc_id: str, from_seq: int = 0) -> dict:
        """Nearest summary + op tail in ONE round trip (the summary
        service's join shape — `Loader.resolve` prefers it over
        load_document + a full ops_from)."""
        res = self._call(doc_id, cmd="catchup", docId=doc_id,
                         fromSeq=from_seq)
        return {
            "summary": res["summary"],
            "summarySeq": res["summarySeq"],
            "ops": [message_from_json(m) for m in res["ops"]],
        }

    def upload_blob(self, doc_id: str, data: bytes) -> str:
        return self._call(
            doc_id, cmd="upload_blob", docId=doc_id,
            data=base64.b64encode(data).decode(),
        )

    def read_blob(self, doc_id: str, blob_id: str) -> bytes:
        return base64.b64decode(
            self._call(doc_id, cmd="read_blob", docId=doc_id,
                       blobId=blob_id)
        )
