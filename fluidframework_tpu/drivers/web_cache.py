"""Client-side snapshot/blob cache tier: the driver-web-cache role.

The reference's `@fluidframework/driver-web-cache`
(packages/drivers/driver-web-cache/src/FluidCache.ts) persists
snapshots and blobs in IndexedDB so a returning client boots from
local storage instead of a service round trip, with staleness expiry
(`FluidCacheEntry` partitioned by file, age-gated reads) and
best-effort writes that never fail the caller. This is that tier over
a local directory (the IndexedDB stand-in), wrapping ANY driver with
the SocketDriver surface:

- `load_document` caches the summary wire form per document with a
  TTL: fresh hits skip the service entirely (the fast-boot path);
  stale entries re-fetch and refresh. A service failure falls back to
  a stale cached copy when allowed (offline boot).
- `read_blob` caches content-addressed blobs FOREVER (immutable by
  construction — the content address is the identity).
- `ops_from`/`connect`/writes pass through untouched: only boot
  artifacts cache (the reference likewise caches snapshots, never the
  delta stream).

Cache writes are best-effort: an unwritable cache directory degrades
to pass-through, never an error (FluidCache.ts swallows storage
failures the same way).
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import time
from typing import Any, List, Optional


class CachedDriver:
    """Wrap a driver with the local snapshot/blob cache tier."""

    def __init__(self, inner, cache_dir: str,
                 snapshot_ttl_s: float = 3600.0,
                 allow_stale_on_error: bool = True):
        self.inner = inner
        self.dir = cache_dir
        self.snapshot_ttl_s = snapshot_ttl_s
        self.allow_stale_on_error = allow_stale_on_error
        self.hits = 0
        self.misses = 0
        self.stale_hits = 0
        try:
            os.makedirs(cache_dir, exist_ok=True)
            self._usable = True
        except OSError:
            self._usable = False

    # ------------------------------------------------------------ paths

    def _key(self, kind: str, *parts: str) -> str:
        h = hashlib.sha256("\x00".join(parts).encode()).hexdigest()[:32]
        return os.path.join(self.dir, f"{kind}-{h}.json")

    def _read(self, path: str, *keys: str) -> Optional[dict]:
        """Load an entry; malformed/foreign shapes degrade to a miss
        (cache failures never fail the caller)."""
        try:
            with open(path) as f:
                entry = json.load(f)
        except (OSError, ValueError):
            return None
        if not isinstance(entry, dict) or any(k not in entry for k in keys):
            return None
        return entry

    def _write(self, path: str, entry: dict) -> None:
        if not self._usable:
            return
        try:
            tmp = path + f".tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(entry, f)
            os.replace(tmp, path)
        except OSError:
            pass  # best-effort: cache failures never fail the caller

    # ---------------------------------------------------------- summary

    def load_document(self, doc_id: str) -> Optional[str]:
        path = self._key("snap", doc_id)
        entry = self._read(path, "at", "wire")
        now = time.time()
        if entry is not None and now - entry["at"] < self.snapshot_ttl_s:
            self.hits += 1
            return entry["wire"]
        self.misses += 1
        try:
            wire = self.inner.load_document(doc_id)
        except Exception:
            if entry is not None and self.allow_stale_on_error:
                # Offline boot: a stale snapshot beats no snapshot
                # (the client catches up over the delta stream later).
                # Counted once, as a stale hit — hits + misses +
                # stale_hits partitions the lookups.
                self.misses -= 1
                self.stale_hits += 1
                return entry["wire"]
            raise
        if wire is not None:
            self._write(path, {"at": now, "wire": wire})
        return wire

    # ------------------------------------------------------------ blobs

    def read_blob(self, doc_id: str, blob_id: str) -> bytes:
        path = self._key("blob", doc_id, blob_id)
        entry = self._read(path, "data")
        if entry is not None:
            self.hits += 1
            return base64.b64decode(entry["data"])
        self.misses += 1
        data = self.inner.read_blob(doc_id, blob_id)
        # Content-addressed: immutable, cache forever.
        self._write(path, {"data": base64.b64encode(data).decode()})
        return data

    # -------------------------------------------------------- housekeeping

    def clear_expired(self, now: Optional[float] = None) -> int:
        """Drop expired snapshot entries (the FluidCache partitioned-
        clear role); returns the number removed. Blobs are immutable
        and stay."""
        if not self._usable:
            return 0
        now = time.time() if now is None else now
        removed = 0
        for name in os.listdir(self.dir):
            if not name.startswith("snap-"):
                continue
            path = os.path.join(self.dir, name)
            entry = self._read(path, "at")
            if entry is None or now - entry["at"] >= self.snapshot_ttl_s:
                try:
                    os.remove(path)
                    removed += 1
                except OSError:
                    pass
        return removed

    # ------------------------------------------------------ pass-through

    @property
    def token_provider(self) -> Any:
        """The credential seam delegates to the wrapped driver in BOTH
        directions. `__getattr__` already forwarded reads, but an
        ASSIGNMENT used to land on the wrapper instance, leaving the
        inner SocketDriver with token_provider=None — a cached client
        silently went out unauthenticated against a secure server
        (round-5 ADVICE.md low). Raises AttributeError when the inner
        driver has no credential seam, so `hasattr` checks stay
        truthful."""
        return self.inner.token_provider

    @token_provider.setter
    def token_provider(self, value: Any) -> None:
        if not hasattr(self.inner, "token_provider"):
            raise AttributeError(
                "wrapped driver has no token_provider seam"
            )
        self.inner.token_provider = value

    def __getattr__(self, name: str) -> Any:
        return getattr(self.inner, name)
