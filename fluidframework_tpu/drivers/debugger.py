"""Debugger driver: record and step through a document's op stream.

The reference's debugger (packages/drivers/debugger) wraps any driver
and lets a developer replay a session interactively — pause inbound
delivery, step one op at a time, resume live. This wrapper
interposes on the connection a wrapped driver returns: ops flow into
a paused DeltaQueue; `step()` delivers one, `play()` drains and goes
live, and everything delivered is recorded for inspection (the same
pause/step machinery DeltaQueue gives replay tooling)."""

from __future__ import annotations

from typing import Any, List, Optional

from ..loader.delta_queue import DeltaQueue


class DebuggerConnection:
    """Wraps a live connection; inbound ops route through a pausable
    queue under the controller's command."""

    def __init__(self, inner, controller: "DebuggerController"):
        self._inner = inner
        self._controller = controller
        self._queue = DeltaQueue(self._deliver)
        self._queue.pause()
        self._listener = None
        inner.listener = self._on_op
        controller._register(self)

    def _on_op(self, msg) -> None:
        self._controller.recorded.append(msg)
        self._queue.push(msg)
        if self._controller.live:
            self._queue.process_one()

    def _deliver(self, msg) -> None:
        if self._listener is not None:
            self._listener(msg)

    # ---- connection surface (delegate + interpose)

    @property
    def listener(self):
        return self._listener

    @listener.setter
    def listener(self, fn) -> None:
        self._listener = fn

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)

    # ---- stepping

    def step(self) -> bool:
        return self._queue.process_one()

    def drain(self) -> int:
        n = 0
        while self._queue.process_one():
            n += 1
        return n

    @property
    def pending(self) -> int:
        return self._queue.length


class DebuggerController:
    """Controls stepping across a debugged document's connections and
    holds the recorded stream (the debugger UI's model)."""

    def __init__(self, live: bool = False):
        self.live = live
        self.recorded: List[Any] = []
        self._connections: List[DebuggerConnection] = []

    def _register(self, conn: DebuggerConnection) -> None:
        self._connections.append(conn)

    def pause(self) -> None:
        self.live = False

    def play(self) -> None:
        """Deliver everything buffered and go live."""
        self.live = True
        for c in self._connections:
            c.drain()

    def step(self) -> int:
        return sum(1 for c in self._connections if c.step())

    @property
    def pending(self) -> int:
        return sum(c.pending for c in self._connections)


class DebugDriver:
    """Driver wrapper: same factory surface, connections interposed
    (FluidDebugger's createFromService shape)."""

    def __init__(self, inner, controller: Optional[DebuggerController] = None):
        self._inner = inner
        self.controller = controller or DebuggerController()

    def connect(self, doc_id: str, client_id: Optional[int] = None):
        conn = self._inner.connect(doc_id, client_id)
        return DebuggerConnection(conn, self.controller)

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)
