"""Experimental DDS families (the reference's experimental/ tree,
SURVEY.md §1 row X): PropertyDDS — the typed property tree + changeset
family."""

from .property_dds import (
    ChangeSet,
    PropertySet,
    PropertyTemplate,
    SharedPropertyTree,
    SharedPropertyTreeFactory,
)

__all__ = [
    "ChangeSet",
    "PropertySet",
    "PropertyTemplate",
    "SharedPropertyTree",
    "SharedPropertyTreeFactory",
]
