"""PropertyDDS: typed property trees + changesets (minimal family).

The reference's experimental PropertyDDS
(experimental/PropertyDDS/packages): `property-properties` defines
TYPED property sets built from schema templates (typeid + typed
fields); `property-changeset` defines the nested
insert/modify/remove ChangeSet format with `applyChangeSet` and
`squash` (changeset.ts, changeset_operations/); `property-dds`'s
SharedPropertyTree synchronizes a property set by submitting
changesets over the op stream (rebase.ts resolves concurrency —
last-sequenced-writer-wins per leaf path here, the format's modify
semantics).

This is the minimal faithful core of that family: typed templates
with validation, hierarchical property sets, the nested changeset
algebra (apply / squash with the reference's insert∘modify and
remove-cancels-insert laws), and a DDS channel with pending-op
rebottoming and summary round-trip. The full reference family
(property-binder, proxies, query) remains out of scope.
"""

from __future__ import annotations

import copy
import json
from typing import Any, Dict, List, Optional

from ..protocol.messages import SequencedMessage
from ..runtime.channel import ChannelFactory, ChannelStorage
from ..runtime.shared_object import SharedObject
from ..runtime.summary import SummaryTreeBuilder

PRIMITIVES = {"Int32", "Float64", "String", "Bool"}
NODE = "NodeProperty"


class PropertyTemplate:
    """A typed schema (property-properties templates,
    property-changeset/src/templateValidator.ts): typeid + fields,
    each a primitive, NodeProperty, or another registered typeid."""

    def __init__(self, typeid: str, properties: List[dict]):
        self.typeid = typeid
        self.properties = list(properties)
        ids = [p["id"] for p in properties]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate field ids in {typeid}")
        for p in properties:
            if "id" not in p or "typeid" not in p:
                raise ValueError(f"field needs id+typeid: {p}")


class _Registry:
    def __init__(self):
        self._templates: Dict[str, PropertyTemplate] = {}

    def register(self, template: PropertyTemplate) -> None:
        self._templates[template.typeid] = template

    def get(self, typeid: str) -> Optional[PropertyTemplate]:
        return self._templates.get(typeid)


def _default_value(typeid: str, registry: _Registry) -> Any:
    if typeid == "Int32":
        return 0
    if typeid == "Float64":
        return 0.0
    if typeid == "String":
        return ""
    if typeid == "Bool":
        return False
    return PropertySet(typeid, registry)


class PropertySet:
    """A typed hierarchical property tree (BaseProperty/NodeProperty
    roles). Dynamic children may be inserted under any node; typed
    children come from the node's template."""

    def __init__(self, typeid: str, registry: _Registry):
        self.typeid = typeid
        self._registry = registry
        self._children: Dict[str, Any] = {}
        tpl = registry.get(typeid)
        if tpl is not None:
            for field in tpl.properties:
                self._children[field["id"]] = _default_value(
                    field["typeid"], registry
                )

    # -------------------------------------------------------- accessors

    def get(self, path: str) -> Any:
        node: Any = self
        for part in path.split("."):
            if not isinstance(node, PropertySet) or part not in node._children:
                raise KeyError(path)
            node = node._children[part]
        return node

    def set_value(self, path: str, value: Any) -> None:
        *head, leaf = path.split(".")
        node = self.get(".".join(head)) if head else self
        if not isinstance(node, PropertySet) or leaf not in node._children:
            raise KeyError(path)
        cur = node._children[leaf]
        if isinstance(cur, PropertySet):
            raise TypeError(f"{path} is a container")
        node._children[leaf] = _check_type(cur, value, path)

    def insert(self, path: str, typeid: str) -> "PropertySet":
        """Insert a dynamic child property at `path` (NodeProperty
        insert semantics)."""
        *head, name = path.split(".")
        node = self.get(".".join(head)) if head else self
        if name in node._children:
            raise KeyError(f"{path} exists")
        child = _default_value(typeid, self._registry)
        node._children[name] = child
        return child if isinstance(child, PropertySet) else node

    def remove(self, path: str) -> None:
        *head, name = path.split(".")
        node = self.get(".".join(head)) if head else self
        del node._children[name]

    def to_json(self) -> dict:
        out: Dict[str, Any] = {"typeid": self.typeid, "fields": {}}
        for k, v in sorted(self._children.items()):
            out["fields"][k] = (
                v.to_json() if isinstance(v, PropertySet) else
                {"value": v, "typeid": _typeid_of(v)}
            )
        return out

    @classmethod
    def from_json(cls, data: dict, registry: _Registry) -> "PropertySet":
        ps = cls.__new__(cls)
        ps.typeid = data["typeid"]
        ps._registry = registry
        ps._children = {}
        for k, v in data["fields"].items():
            if "fields" in v:
                ps._children[k] = cls.from_json(v, registry)
            else:
                ps._children[k] = v["value"]
        return ps


def _typeid_of(value: Any) -> str:
    if isinstance(value, bool):
        return "Bool"
    if isinstance(value, int):
        return "Int32"
    if isinstance(value, float):
        return "Float64"
    return "String"


def _check_type(current: Any, value: Any, path: str) -> Any:
    want = _typeid_of(current)
    got = _typeid_of(value)
    if want == "Float64" and got == "Int32":
        return float(value)
    if want != got:
        raise TypeError(f"{path}: expected {want}, got {got}")
    return value


class ChangeSet:
    """The nested changeset form (property-changeset/src/changeset.ts):
    per node, `insert` (subtree payloads by name), `modify` (nested
    changesets / leaf values), `remove` (names). `apply` mutates a
    PropertySet; `squash` composes a later changeset into this one
    under the reference's laws (modify-after-insert folds into the
    insert; remove-after-insert cancels; modify-after-modify is
    last-writer-wins per leaf)."""

    def __init__(self, data: Optional[dict] = None):
        self.data = data or {}

    # ----------------------------------------------------------- apply

    def apply(self, ps: PropertySet,
              shadowed: Optional[Dict[str, List[int]]] = None) -> None:
        """`shadowed`: leaf-path -> [pending modifies, pending
        structural ops] (the map-kernel shadowing convention, made
        KIND-AWARE for the nested tree — the rule set below is the
        unique convergent assignment of winners given that pending
        local ops always sequence after currently-arriving remotes):

        - a remote REMOVE always applies (concurrent edits' echoes
          mute as modifies of a removed child on every remote);
        - a remote INSERT skips iff a pending local STRUCTURAL op
          (insert: ours recreates at its echo; remove: ours deletes at
          its sequencing on remotes) holds the path — a pending
          modify CANNOT recreate a node, so it never shadows inserts;
        - a remote MODIFY skips iff any pending local write holds the
          path (a pending insert's payload carries the local value).
        """
        self._apply_node(self.data, ps, shadowed or {}, "")

    @staticmethod
    def _shadow_at(shadowed, path: str, slot: int) -> bool:
        entry = shadowed.get(path)
        return entry is not None and entry[slot] > 0

    def _apply_node(self, cs: dict, node: PropertySet,
                    shadowed: Dict[str, List[int]], prefix: str) -> None:
        def path_of(name: str) -> str:
            return f"{prefix}{name}"

        for name in cs.get("remove", []):
            node._children.pop(name, None)
        for name, payload in cs.get("insert", {}).items():
            if self._shadow_at(shadowed, path_of(name), 1):
                continue
            node._children[name] = (
                PropertySet.from_json(payload, node._registry)
                if isinstance(payload, dict) and "fields" in payload
                else payload["value"]
            )
        for name, sub in cs.get("modify", {}).items():
            child = node._children.get(name)
            if child is None:
                continue  # modify of a concurrently removed child mutes
            p = path_of(name)
            if isinstance(child, PropertySet):
                if "value" in sub:
                    continue  # leaf write vs now-container: shape mutes
                self._apply_node(sub, child, shadowed, p + ".")
            elif "value" not in sub:
                continue  # nested modify vs now-primitive: shape mutes
            elif not (
                self._shadow_at(shadowed, p, 0)
                or self._shadow_at(shadowed, p, 1)
            ):
                node._children[name] = sub["value"]

    def paths(self) -> List[tuple]:
        """(path, slot) for every write: slot 0 = modify, slot 1 =
        structural (insert/remove) — the shadow bookkeeping keys."""
        out: List[tuple] = []

        def walk(cs: dict, prefix: str) -> None:
            for name in cs.get("remove", []):
                out.append((prefix + name, 1))
            for name in cs.get("insert", {}):
                out.append((prefix + name, 1))
            for name, sub in cs.get("modify", {}).items():
                if "value" in sub:
                    out.append((prefix + name, 0))
                else:
                    walk(sub, prefix + name + ".")

        walk(self.data, "")
        return out

    # ---------------------------------------------------------- squash

    def squash(self, later: "ChangeSet") -> "ChangeSet":
        """this ∘ later (changeset_operations squash laws)."""
        return ChangeSet(
            _squash_node(copy.deepcopy(self.data), later.data)
        )


def _squash_node(base: dict, later: dict) -> dict:
    for name in later.get("remove", []):
        if name in base.get("insert", {}):
            del base["insert"][name]  # remove cancels our insert
        else:
            base.setdefault("remove", []).append(name)
        base.get("modify", {}).pop(name, None)
    for name, payload in later.get("insert", {}).items():
        base.setdefault("insert", {})[name] = copy.deepcopy(payload)
    for name, sub in later.get("modify", {}).items():
        ins = base.get("insert", {}).get(name)
        if ins is not None:
            # modify folds into our pending insert's payload.
            _fold_modify_into_insert(ins, sub)
            continue
        cur = base.setdefault("modify", {}).get(name)
        if cur is None or "value" in sub:
            base["modify"][name] = copy.deepcopy(sub)  # leaf LWW
        else:
            base["modify"][name] = _squash_node(cur, sub)
    return base


def _fold_modify_into_insert(ins: dict, sub: dict) -> None:
    if "value" in sub:
        ins["value"] = sub["value"]
        return
    for name in sub.get("remove", []):
        ins.get("fields", {}).pop(name, None)
    for name, payload in sub.get("insert", {}).items():
        ins.setdefault("fields", {})[name] = copy.deepcopy(payload)
    for name, inner in sub.get("modify", {}).items():
        child = ins.get("fields", {}).get(name)
        if child is not None:
            _fold_modify_into_insert(child, inner)


class SharedPropertyTree(SharedObject):
    """The DDS channel (property-dds SharedPropertyTree): local edits
    accumulate into a pending changeset submitted on commit();
    sequenced changesets apply in total order on every replica
    (rebase.ts's effective policy for non-conflicting paths; leaf
    conflicts resolve last-sequenced-wins via modify semantics)."""

    ROOT_TYPEID = NODE

    def initialize_local_core(self) -> None:
        self.registry = _Registry()
        self.root = PropertySet(self.ROOT_TYPEID, self.registry)
        self._pending = ChangeSet()
        self._shadow: Dict[str, List[int]] = {}

    def register_template(self, template: PropertyTemplate) -> None:
        self.registry.register(template)

    # -------------------------------------------------------- local API

    @staticmethod
    def _singleton(kind: str, path: str, payload: Any) -> ChangeSet:
        """One primitive edit as a changeset; pending edits fold via
        `squash`, so the algebra is the single source of truth."""
        *head, name = path.split(".")
        if kind == "set":
            leaf: Dict[str, Any] = {"modify": {name: {"value": payload}}}
        elif kind == "insert":
            leaf = {"insert": {name: payload}}
        else:
            leaf = {"remove": [name]}
        for part in reversed(head):
            leaf = {"modify": {part: leaf}}
        return ChangeSet(leaf)

    def _fold(self, kind: str, path: str, payload: Any = None) -> None:
        self._pending = self._pending.squash(
            self._singleton(kind, path, payload)
        )

    def set_value(self, path: str, value: Any) -> None:
        self.root.set_value(path, value)
        self._fold("set", path, value)

    def insert_property(self, path: str, typeid: str) -> None:
        self.root.insert(path, typeid)
        child = self.root.get(path)
        payload = (
            child.to_json() if isinstance(child, PropertySet)
            else {"value": child, "typeid": typeid}
        )
        self._fold("insert", path, payload)

    def remove_property(self, path: str) -> None:
        self.root.remove(path)
        self._fold("remove", path)

    def commit(self) -> None:
        """Submit the accumulated pending changeset as ONE op (the
        reference's commit granularity). Written paths shadow remote
        writes until this op's own echo sequences (then the sequenced
        order is authoritative)."""
        if not self._pending.data:
            return
        cs, self._pending = self._pending, ChangeSet()
        for p, slot in cs.paths():
            entry = self._shadow.setdefault(p, [0, 0])
            entry[slot] += 1
        self.submit_local_message({"cs": cs.data}, None)

    # ----------------------------------------------------------- apply

    def process_core(self, msg: SequencedMessage, local: bool,
                     local_metadata: Any) -> None:
        cs = ChangeSet(msg.contents["cs"])
        if local:
            # Applied optimistically at edit time; release the shadows.
            for p, slot in cs.paths():
                entry = self._shadow.get(p)
                if entry is not None:
                    entry[slot] = max(0, entry[slot] - 1)
                    if entry == [0, 0]:
                        self._shadow.pop(p, None)
            # The echo is the authoritative sequenced point for THIS
            # op: re-applying it (over the shadows that remain for
            # later still-pending local commits) converges the
            # optimistic state with what every remote just computed —
            # corrective when concurrent earlier-sequenced ops
            # perturbed our optimistic values (e.g. a racing
            # remove+reinsert), idempotent otherwise.
            cs.apply(self.root, self._shadow)
            return
        cs.apply(self.root, self._shadow)

    def apply_stashed_op(self, content: Any) -> Any:
        ChangeSet(content["cs"]).apply(self.root)
        return None

    # --------------------------------------------------------- summary

    def summarize_core(self):
        return (
            SummaryTreeBuilder()
            .add_json_blob("root", self.root.to_json())
            .summary
        )

    def load_core(self, storage: ChannelStorage) -> None:
        self.initialize_local_core()
        self.root = PropertySet.from_json(
            json.loads(storage.read("root")), self.registry
        )


class SharedPropertyTreeFactory(ChannelFactory):
    type_name = "https://graph.microsoft.com/types/PropertyDDS"
    channel_class = SharedPropertyTree
