"""PropertyDDS: typed property trees + changesets (minimal family).

The reference's experimental PropertyDDS
(experimental/PropertyDDS/packages): `property-properties` defines
TYPED property sets built from schema templates (typeid + typed
fields); `property-changeset` defines the nested
insert/modify/remove ChangeSet format with `applyChangeSet` and
`squash` (changeset.ts, changeset_operations/); `property-dds`'s
SharedPropertyTree synchronizes a property set by submitting
changesets over the op stream, resolving concurrency by CHANGESET
REBASE (rebase.ts): incoming changesets rebase over the trunk window
their author had not seen, and the pending local chain rebases over
each incoming.

This is the faithful core of that family: typed templates with
validation, hierarchical property sets, ARRAY properties with
index-adjusting rebase, the nested changeset algebra (apply / squash
/ rebase with the reference's insert∘modify, remove-cancels-insert,
remove-over-modify, and later-writer-wins laws), and a DDS channel
maintaining a remote-tip view plus a rebased local branch, with
summary round-trip. The full reference family (property-binder,
proxies, query) remains out of scope.
"""

from __future__ import annotations

import copy
import json
from typing import Any, Dict, List, Optional

from ..protocol.messages import SequencedMessage
from ..runtime.channel import ChannelFactory, ChannelStorage
from ..runtime.shared_object import SharedObject
from ..runtime.summary import SummaryTreeBuilder

PRIMITIVES = {"Int32", "Float64", "String", "Bool"}
NODE = "NodeProperty"


class PropertyTemplate:
    """A typed schema (property-properties templates,
    property-changeset/src/templateValidator.ts): typeid + fields,
    each a primitive, NodeProperty, or another registered typeid."""

    def __init__(self, typeid: str, properties: List[dict]):
        self.typeid = typeid
        self.properties = list(properties)
        ids = [p["id"] for p in properties]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate field ids in {typeid}")
        for p in properties:
            if "id" not in p or "typeid" not in p:
                raise ValueError(f"field needs id+typeid: {p}")


class _Registry:
    def __init__(self):
        self._templates: Dict[str, PropertyTemplate] = {}

    def register(self, template: PropertyTemplate) -> None:
        self._templates[template.typeid] = template

    def get(self, typeid: str) -> Optional[PropertyTemplate]:
        return self._templates.get(typeid)


def _default_value(typeid: str, registry: _Registry) -> Any:
    if typeid == "Int32":
        return 0
    if typeid == "Float64":
        return 0.0
    if typeid == "String":
        return ""
    if typeid == "Bool":
        return False
    if typeid == "Array" or typeid.startswith("array<"):
        return []
    return PropertySet(typeid, registry)


class PropertySet:
    """A typed hierarchical property tree (BaseProperty/NodeProperty
    roles). Dynamic children may be inserted under any node; typed
    children come from the node's template."""

    def __init__(self, typeid: str, registry: _Registry):
        self.typeid = typeid
        self._registry = registry
        self._children: Dict[str, Any] = {}
        tpl = registry.get(typeid)
        if tpl is not None:
            for field in tpl.properties:
                self._children[field["id"]] = _default_value(
                    field["typeid"], registry
                )

    # -------------------------------------------------------- accessors

    def get(self, path: str) -> Any:
        node: Any = self
        for part in path.split("."):
            if not isinstance(node, PropertySet) or part not in node._children:
                raise KeyError(path)
            node = node._children[part]
        return node

    def set_value(self, path: str, value: Any) -> None:
        *head, leaf = path.split(".")
        node = self.get(".".join(head)) if head else self
        if not isinstance(node, PropertySet) or leaf not in node._children:
            raise KeyError(path)
        cur = node._children[leaf]
        if isinstance(cur, PropertySet):
            raise TypeError(f"{path} is a container")
        node._children[leaf] = _check_type(cur, value, path)

    def insert(self, path: str, typeid: str) -> "PropertySet":
        """Insert a dynamic child property at `path` (NodeProperty
        insert semantics)."""
        *head, name = path.split(".")
        node = self.get(".".join(head)) if head else self
        if name in node._children:
            raise KeyError(f"{path} exists")
        child = _default_value(typeid, self._registry)
        node._children[name] = child
        return child if isinstance(child, PropertySet) else node

    def remove(self, path: str) -> None:
        *head, name = path.split(".")
        node = self.get(".".join(head)) if head else self
        del node._children[name]

    def to_json(self) -> dict:
        out: Dict[str, Any] = {"typeid": self.typeid, "fields": {}}
        for k, v in sorted(self._children.items()):
            out["fields"][k] = (
                v.to_json() if isinstance(v, PropertySet) else
                # Deep-copied: mutable values (arrays) must never
                # alias between a snapshot and the live tree — the
                # tip/view split depends on it.
                {"value": copy.deepcopy(v), "typeid": _typeid_of(v)}
            )
        return out

    def clone(self) -> "PropertySet":
        """Deep copy sharing the registry — half the copying of a
        to_json/from_json round trip (the view-rebuild hot path)."""
        ps = PropertySet.__new__(PropertySet)
        ps.typeid = self.typeid
        ps._registry = self._registry
        ps._children = {
            k: (v.clone() if isinstance(v, PropertySet)
                else copy.deepcopy(v))
            for k, v in self._children.items()
        }
        return ps

    @classmethod
    def from_json(cls, data: dict, registry: _Registry) -> "PropertySet":
        ps = cls.__new__(cls)
        ps.typeid = data["typeid"]
        ps._registry = registry
        ps._children = {}
        for k, v in data["fields"].items():
            if "fields" in v:
                ps._children[k] = cls.from_json(v, registry)
            else:
                ps._children[k] = copy.deepcopy(v["value"])
        return ps


def _typeid_of(value: Any) -> str:
    if isinstance(value, bool):
        return "Bool"
    if isinstance(value, int):
        return "Int32"
    if isinstance(value, float):
        return "Float64"
    if isinstance(value, list):
        return "Array"
    return "String"


def _check_type(current: Any, value: Any, path: str) -> Any:
    want = _typeid_of(current)
    got = _typeid_of(value)
    if want == "Float64" and got == "Int32":
        return float(value)
    if want != got:
        raise TypeError(f"{path}: expected {want}, got {got}")
    return value


class ChangeSet:
    """The nested changeset form (property-changeset/src/changeset.ts):
    per node, `insert` (subtree payloads by name), `modify` (nested
    changesets / leaf values), `remove` (names). `apply` mutates a
    PropertySet; `squash` composes a later changeset into this one
    under the reference's laws (modify-after-insert folds into the
    insert; remove-after-insert cancels; modify-after-modify is
    last-writer-wins per leaf)."""

    def __init__(self, data: Optional[dict] = None):
        self.data = data or {}

    # ----------------------------------------------------------- apply

    def apply(self, ps: PropertySet) -> None:
        """Apply in place. Concurrency is resolved BEFORE apply by
        `rebase_changeset` (the reference's rebase.ts pipeline:
        incoming changesets rebase over the trunk window, pending
        local changesets rebase over each incoming) — apply itself is
        unconditional, with shape-mismatch mutes as the only guards."""
        self._apply_node(self.data, ps)

    def _apply_node(self, cs: dict, node: PropertySet) -> None:
        for name in cs.get("remove", []):
            node._children.pop(name, None)
        for name, payload in cs.get("insert", {}).items():
            node._children[name] = (
                PropertySet.from_json(payload, node._registry)
                if isinstance(payload, dict) and "fields" in payload
                else copy.deepcopy(payload["value"])
            )
        for name, sub in cs.get("modify", {}).items():
            child = node._children.get(name)
            if child is None:
                continue  # modify of a concurrently removed child mutes
            if isinstance(child, PropertySet):
                if "value" in sub or "array" in sub:
                    continue  # leaf write vs now-container: shape mutes
                self._apply_node(sub, child)
            elif "array" in sub:
                if isinstance(child, list):
                    _apply_array_ops(child, sub["array"])
            elif "value" not in sub:
                continue  # nested modify vs now-primitive: shape mutes
            else:
                node._children[name] = copy.deepcopy(sub["value"])

    # ---------------------------------------------------------- squash

    def squash(self, later: "ChangeSet") -> "ChangeSet":
        """this ∘ later (changeset_operations squash laws)."""
        return ChangeSet(
            _squash_node(copy.deepcopy(self.data), later.data)
        )


def _apply_array_ops(arr: list, ops: List[dict]) -> None:
    """Apply array ops in order (property-changeset array semantics:
    indexed insert/remove/set over the array's current state)."""
    for op in ops:
        i = min(max(int(op["idx"]), 0), len(arr))
        if op["type"] == "ins":
            arr[i:i] = copy.deepcopy(op["values"])
        elif op["type"] == "rem":
            del arr[i: i + int(op["count"])]
        elif op["type"] == "set":
            if i < len(arr):
                arr[i] = copy.deepcopy(op["value"])


def _squash_node(base: dict, later: dict) -> dict:
    for name in later.get("remove", []):
        if name in base.get("insert", {}):
            del base["insert"][name]  # remove cancels our insert
        else:
            base.setdefault("remove", []).append(name)
        base.get("modify", {}).pop(name, None)
    for name, payload in later.get("insert", {}).items():
        base.setdefault("insert", {})[name] = copy.deepcopy(payload)
    for name, sub in later.get("modify", {}).items():
        ins = base.get("insert", {}).get(name)
        if ins is not None:
            # modify folds into our pending insert's payload.
            _fold_modify_into_insert(ins, sub)
            continue
        cur = base.setdefault("modify", {}).get(name)
        if "array" in sub:
            # Array ops compose sequentially: concatenation IS the
            # squash (each op is relative to the state its
            # predecessors produced). Array ops AFTER a whole-value
            # write fold into that written value (the insert-fold
            # law's modify analog).
            if cur is not None and "array" in cur:
                cur["array"] = cur["array"] + copy.deepcopy(sub["array"])
            elif (
                cur is not None and "value" in cur
                and isinstance(cur["value"], list)
            ):
                _apply_array_ops(cur["value"], sub["array"])
            else:
                base["modify"][name] = copy.deepcopy(sub)
        elif cur is None or "value" in sub or "array" in cur:
            base["modify"][name] = copy.deepcopy(sub)  # leaf LWW
        else:
            base["modify"][name] = _squash_node(cur, sub)
    return base


# ---------------------------------------------------------------------------
# rebase (changeset_operations/rebase laws)
# ---------------------------------------------------------------------------


def _adjust_array_op(op: dict, base: dict,
                     op_later: bool) -> List[dict]:
    """Transform ONE array op over one base op (shared start state);
    returns 0..2 result pieces. `op_later`: op sequences after base
    (gap ties: the earlier-sequenced insert's content lands first;
    removed content wins over sets/removes)."""
    cur = copy.deepcopy(op)
    bi = int(base["idx"])
    if base["type"] == "ins":
        n = len(base["values"])
        i = int(cur["idx"])
        if cur["type"] == "ins":
            if bi < i or (bi == i and not op_later):
                cur["idx"] = i + n
            return [cur]
        if cur["type"] == "rem":
            c = int(cur["count"])
            if bi <= i:
                cur["idx"] = i + n
                return [cur]
            if bi < i + c:
                # Foreign content inside our removal: keep our span
                # but skip over it (two sequential pieces).
                return [
                    {"type": "rem", "idx": i, "count": bi - i},
                    {"type": "rem", "idx": bi + n - (bi - i),
                     "count": c - (bi - i)},
                ]
            return [cur]
        # set
        if bi <= int(cur["idx"]):
            cur["idx"] = int(cur["idx"]) + n
        return [cur]
    if base["type"] == "rem":
        n = int(base["count"])
        i = int(cur["idx"])
        if cur["type"] == "ins":
            if i >= bi + n:
                cur["idx"] = i - n
            elif i > bi:
                cur["idx"] = bi  # slide to the removal start
            return [cur]
        if cur["type"] == "rem":
            c = int(cur["count"])
            lo = max(i, bi)
            hi = min(i + c, bi + n)
            lost = max(0, hi - lo)
            c -= lost
            if c <= 0:
                return []
            cur["count"] = c
            cur["idx"] = i if i < bi else max(bi, i - n)
            return [cur]
        # set
        if bi <= i < bi + n:
            return []  # target removed: mute
        if i >= bi + n:
            cur["idx"] = i - n
        return [cur]
    # base set: no structural effect; concurrent sets on the same
    # slot resolve later-wins (the earlier drops when rebased over
    # the later).
    if (
        op["type"] == "set" and base["type"] == "set"
        and int(op["idx"]) == bi and not op_later
    ):
        return []
    return [cur]


def _xform_arrays(A: List[dict], B: List[dict],
                  a_later: bool) -> tuple:
    """Inclusion transform of SEQUENTIAL array-op lists sharing one
    start state (the tree changeset's _xform shape): returns
    ``(A', B')`` with A' applying after B and B' after A — pairwise
    recursion keeps every comparison in a shared frame."""
    if not A or not B:
        return list(A), list(B)
    if len(A) == 1 and len(B) == 1:
        a_p = _adjust_array_op(A[0], B[0], a_later)
        b_p = _adjust_array_op(B[0], A[0], not a_later)
        return a_p, b_p
    if len(A) > 1:
        A1, Bp = _xform_arrays(A[:1], B, a_later)
        A2, Bpp = _xform_arrays(A[1:], Bp, a_later)
        return A1 + A2, Bpp
    Ap, B1 = _xform_arrays(A, B[:1], a_later)
    App, B2 = _xform_arrays(Ap, B[1:], a_later)
    return App, B1 + B2


def _rebase_array_ops(ours: List[dict], theirs: List[dict],
                      ours_later: bool) -> List[dict]:
    """Rebase our SEQUENTIAL array ops over theirs (the reference's
    array-changeset rebase) via the inclusion transform."""
    out, _ = _xform_arrays(
        [copy.deepcopy(o) for o in ours],
        [copy.deepcopy(b) for b in theirs],
        ours_later,
    )
    return out


def rebase_changeset(ours: dict, theirs: dict,
                     ours_later: bool = True) -> dict:
    """Rebase `ours` over `theirs` (both relative to one start state;
    the result applies after `theirs`) — the reference's
    changeset_operations rebase laws (property-changeset
    src/changeset_operations + property-dds src/rebase.ts):

    - our modify under THEIR remove drops (removal wins over edits);
    - same-name insert-vs-insert: the later-sequenced insert wins
      (its payload overwrites; the earlier's survives only until the
      later applies);
    - leaf modify-vs-modify: the later-sequenced write wins (the
      earlier drops when rebased over it);
    - nested modifies recurse; array ops adjust indices
      (`_rebase_array_ops`).

    `ours_later`: True when `ours` sequences after `theirs` (the
    normal direction for pending-local-over-incoming-remote); False
    when carrying an earlier changeset over a later one (the dual
    step of the chain transform).
    """
    out: Dict[str, Any] = {}
    their_removed = set(theirs.get("remove", []))
    their_inserts = theirs.get("insert", {})
    their_modify = theirs.get("modify", {})
    for name in ours.get("remove", []):
        if name in their_removed:
            continue  # already gone
        out.setdefault("remove", []).append(name)
    for name, payload in ours.get("insert", {}).items():
        if name in their_inserts and not ours_later:
            continue  # their later insert overwrites ours
        out.setdefault("insert", {})[name] = copy.deepcopy(payload)
    for name, sub in ours.get("modify", {}).items():
        if name in their_removed:
            continue  # removal wins over our edits
        if name in their_inserts:
            if not ours_later:
                continue  # their later insert replaced our target
            out.setdefault("modify", {})[name] = copy.deepcopy(sub)
            continue
        their_sub = their_modify.get(name)
        if their_sub is None:
            out.setdefault("modify", {})[name] = copy.deepcopy(sub)
            continue
        if "array" in sub and "array" in their_sub:
            ops = _rebase_array_ops(
                sub["array"], their_sub["array"], ours_later
            )
            if ops:
                out.setdefault("modify", {})[name] = {"array": ops}
            continue
        if "value" in sub or "value" in their_sub or "array" in sub \
                or "array" in their_sub:
            # Leaf (or shape-conflicting) writes: later wins.
            if ours_later:
                out.setdefault("modify", {})[name] = copy.deepcopy(sub)
            continue
        r = rebase_changeset(sub, their_sub, ours_later)
        if r:
            out.setdefault("modify", {})[name] = r
    return out


def _fold_modify_into_insert(ins: dict, sub: dict) -> None:
    if "array" in sub:
        if isinstance(ins.get("value"), list):
            _apply_array_ops(ins["value"], sub["array"])
        return
    if "value" in sub:
        ins["value"] = sub["value"]
        return
    for name in sub.get("remove", []):
        ins.get("fields", {}).pop(name, None)
    for name, payload in sub.get("insert", {}).items():
        ins.setdefault("fields", {})[name] = copy.deepcopy(payload)
    for name, inner in sub.get("modify", {}).items():
        child = ins.get("fields", {}).get(name)
        if child is not None:
            _fold_modify_into_insert(child, inner)


class SharedPropertyTree(SharedObject):
    """The DDS channel (property-dds SharedPropertyTree): local edits
    accumulate into a pending changeset submitted on commit();
    concurrency resolves by CHANGESET REBASE (rebase.ts), not
    apply-time shadowing:

    - `tip` is the sequenced-only state; `root` is the VIEW (tip plus
      the pending local chain re-applied) — the reference's
      remoteTipView / local-branch split;
    - an incoming remote changeset first rebases over the trunk
      window the sender had not seen (its `ref` field names the
      sequence number it was authored against), applies to the tip,
      then the pending local chain rebases over it (the chain
      transform with the carried remote advancing over each local)
      and the view rebuilds;
    - our own echo applies its (chain-maintained, tip-coordinate)
      form to the tip and pops the chain.
    """

    ROOT_TYPEID = NODE

    def initialize_local_core(self) -> None:
        self.registry = _Registry()
        self.tip = PropertySet(self.ROOT_TYPEID, self.registry)
        self.root = PropertySet(self.ROOT_TYPEID, self.registry)
        self._pending = ChangeSet()
        self._local: List[dict] = []  # committed, unacked (tip coords)
        self._local_orig: List[dict] = []  # same, as-submitted forms
        # Trunk window entries: {seq, session, cs (tip coords),
        # orig (as submitted)} — `orig` feeds the author-chain replay.
        self._trunk: List[dict] = []
        self._trunk_seq = 0

    def register_template(self, template: PropertyTemplate) -> None:
        self.registry.register(template)

    # -------------------------------------------------------- local API

    @staticmethod
    def _singleton(kind: str, path: str, payload: Any) -> ChangeSet:
        """One primitive edit as a changeset; pending edits fold via
        `squash`, so the algebra is the single source of truth."""
        *head, name = path.split(".")
        if kind == "set":
            leaf: Dict[str, Any] = {"modify": {name: {"value": payload}}}
        elif kind == "insert":
            leaf = {"insert": {name: payload}}
        elif kind == "array":
            leaf = {"modify": {name: {"array": [payload]}}}
        else:
            leaf = {"remove": [name]}
        for part in reversed(head):
            leaf = {"modify": {part: leaf}}
        return ChangeSet(leaf)

    def _fold(self, kind: str, path: str, payload: Any = None) -> None:
        self._pending = self._pending.squash(
            self._singleton(kind, path, payload)
        )

    def set_value(self, path: str, value: Any) -> None:
        self.root.set_value(path, value)
        self._fold("set", path, value)

    def insert_property(self, path: str, typeid: str) -> None:
        self.root.insert(path, typeid)
        child = self.root.get(path)
        payload = (
            child.to_json() if isinstance(child, PropertySet)
            else {"value": child, "typeid": typeid}
        )
        self._fold("insert", path, payload)

    def remove_property(self, path: str) -> None:
        self.root.remove(path)
        self._fold("remove", path)

    # Array properties (the reference's ArrayProperty + array
    # changesets): indexed ops whose rebase adjusts indices.

    def _fold_array(self, path: str, op: dict) -> None:
        self._fold("array", path, op)

    def array_insert(self, path: str, idx: int, values: List[Any]) -> None:
        arr = self.root.get(path)
        if not isinstance(arr, list):
            raise TypeError(f"{path} is not an array")
        arr[idx:idx] = list(values)
        self._fold_array(path, {"type": "ins", "idx": idx,
                                "values": list(values)})

    def array_remove(self, path: str, idx: int, count: int = 1) -> None:
        arr = self.root.get(path)
        if not isinstance(arr, list):
            raise TypeError(f"{path} is not an array")
        del arr[idx: idx + count]
        self._fold_array(path, {"type": "rem", "idx": idx,
                                "count": count})

    def array_set(self, path: str, idx: int, value: Any) -> None:
        arr = self.root.get(path)
        if not isinstance(arr, list):
            raise TypeError(f"{path} is not an array")
        arr[idx] = value
        self._fold_array(path, {"type": "set", "idx": idx,
                                "value": value})

    def commit(self) -> None:
        """Submit the accumulated pending changeset as ONE op (the
        reference's commit granularity), stamped with the trunk
        sequence number it was authored against (rebase.ts's
        referenceGuid role)."""
        if not self._pending.data:
            return
        cs, self._pending = self._pending, ChangeSet()
        self._local.append(cs.data)
        self._local_orig.append(copy.deepcopy(cs.data))
        self.submit_local_message(
            {"cs": copy.deepcopy(cs.data), "ref": self._trunk_seq}, None
        )

    # ----------------------------------------------------------- apply

    def _rebuild_view(self) -> None:
        """view = tip + the pending chain (incl. uncommitted edits)."""
        self.root = self.tip.clone()
        for cs in self._local:
            ChangeSet(cs).apply(self.root)
        if self._pending.data:
            self._pending.apply(self.root)

    def process_core(self, msg: SequencedMessage, local: bool,
                     local_metadata: Any) -> None:
        if local:
            # Our echo: the chain's head is already maintained in tip
            # coordinates by the per-remote rebases below.
            assert self._local, "ack with empty local chain"
            cs = self._local.pop(0)
            orig = self._local_orig.pop(0)
            ChangeSet(copy.deepcopy(cs)).apply(self.tip)
            self._trunk.append({
                "seq": msg.sequence_number,
                "session": msg.client_id,
                "cs": cs,
                "orig": orig,
            })
            self._trunk_seq = msg.sequence_number
        else:
            # Rebase the incoming into tip coordinates by REPLAYING
            # THE AUTHOR'S CHAIN through the trunk since its `ref`:
            # the incoming was authored on trunk@ref plus the
            # author's own then-unacked commits (ORIGINAL forms, kept
            # in the trunk entries). Walking the trunk in sequence
            # order: an own entry pops the chain head (it sequenced),
            # a foreign entry chain-transforms (each chain element
            # rebases over the carried foreign; the carried foreign
            # advances over the element) — a flat fold over foreign
            # entries alone diverges when a foreign interleaves
            # between two of the author's in-flight commits (its
            # trunk form does not reflect the first one).
            incoming_orig = copy.deepcopy(msg.contents["cs"])
            ref = msg.contents.get("ref", 0)
            chain = [
                copy.deepcopy(e["orig"]) for e in self._trunk
                if e["seq"] > ref and e["session"] == msg.client_id
            ]
            chain.append(copy.deepcopy(incoming_orig))
            for e in self._trunk:
                if e["seq"] <= ref:
                    continue
                if e["session"] == msg.client_id:
                    chain.pop(0)  # own commit sequenced: left the chain
                else:
                    carried = e["cs"]
                    new_chain = []
                    for l_cs in chain:
                        new_chain.append(rebase_changeset(
                            l_cs, carried, ours_later=True
                        ))
                        carried = rebase_changeset(
                            carried, l_cs, ours_later=False
                        )
                    chain = new_chain
            incoming = chain[-1]
            ChangeSet(copy.deepcopy(incoming)).apply(self.tip)
            self._trunk.append({
                "seq": msg.sequence_number,
                "session": msg.client_id,
                "cs": incoming,
                "orig": incoming_orig,
            })
            self._trunk_seq = msg.sequence_number
            # Chain transform: each pending local rebases over the
            # incoming; the carried incoming advances over the local's
            # ORIGINAL form (the dual direction).
            carried = incoming
            new_local: List[dict] = []
            for l_cs in self._local:
                new_local.append(
                    rebase_changeset(l_cs, carried, ours_later=True)
                )
                carried = rebase_changeset(
                    carried, l_cs, ours_later=False
                )
            self._local = new_local
            if self._pending.data:
                self._pending = ChangeSet(rebase_changeset(
                    self._pending.data, carried, ours_later=True
                ))
            self._rebuild_view()
            self.emit("changesetApplied", False)
        # Trunk eviction below the MSN (no future ref can precede it).
        msn = msg.minimum_sequence_number
        self._trunk = [t for t in self._trunk if t["seq"] > msn]

    def apply_stashed_op(self, content: Any) -> Any:
        cs = ChangeSet(copy.deepcopy(content["cs"]))
        cs.apply(self.root)
        self._local.append(copy.deepcopy(content["cs"]))
        self._local_orig.append(copy.deepcopy(content["cs"]))
        self.submit_local_message(
            {"cs": copy.deepcopy(content["cs"]), "ref": self._trunk_seq},
            None,
        )
        return None

    # --------------------------------------------------------- summary

    def summarize_core(self):
        return (
            SummaryTreeBuilder()
            .add_json_blob("root", self.tip.to_json())
            .add_json_blob(
                "trunk",
                {"seq": self._trunk_seq, "window": list(self._trunk)},
            )
            .summary
        )

    def load_core(self, storage: ChannelStorage) -> None:
        self.initialize_local_core()
        self.tip = PropertySet.from_json(
            json.loads(storage.read("root")), self.registry
        )
        if storage.contains("trunk"):
            t = json.loads(storage.read("trunk"))
            self._trunk_seq = t["seq"]
            self._trunk = list(t["window"])
        self._rebuild_view()


class SharedPropertyTreeFactory(ChannelFactory):
    type_name = "https://graph.microsoft.com/types/PropertyDDS"
    channel_class = SharedPropertyTree
