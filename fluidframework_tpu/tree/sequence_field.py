"""Sequence-field mark calculus: compose / invert / rebase over marks.

The reference's core list-merge machinery
(packages/dds/tree/src/feature-libraries/sequence-field/{rebase,
compose,invert}.ts): a changeset for one sequence field is a stream of
MARKS walked against the field's input state. This module implements
the calculus over the mark vocabulary:

- {"skip": n}                        advance over n untouched nodes
- {"insert": [c...], "tie": k}       new content (consumes no input);
                                     `tie` orders same-position inserts
- {"delete": n, "content": [...]}    detach n nodes (content captured
                                     at apply time, fueling revive)
- {"revive": [c...]}                 reattach deleted content (the
                                     invert of delete)
- {"moveOut": n, "id": m}            detach n nodes for a move
- {"moveIn": "id": m}                reattach the nodes of pair m

Moves are first-class (moveOut/moveIn pairs), delete is detach with
capture, and edits rebased over a delete of their target range are
MUTED (dropped) exactly as the reference mutes marks under detached
ranges.

The laws (core/rebase/verifyChangeRebaser.ts contract) are enforced by
tests/test_sequence_field.py's fuzz suite:
  apply(apply(s,A),B) == apply(s, compose(A,B))
  apply(apply(s,A), invert(A)) == s
  rebase(A, empty) == A
  rebase(A, compose(B,C)) == rebase(rebase(A,B), C)
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Tuple

Mark = Dict[str, Any]
MarkList = List[Mark]


# --------------------------------------------------------------------------
# constructors / normalization
# --------------------------------------------------------------------------


def skip(n: int) -> Mark:
    return {"skip": n}


def insert(content: List[Any], tie: int = 0) -> Mark:
    return {"insert": list(content), "tie": tie}


def delete(n: int) -> Mark:
    return {"delete": n}


def move_out(n: int, move_id: Any) -> Mark:
    return {"moveOut": n, "id": move_id}


def move_in(move_id: Any) -> Mark:
    return {"moveIn": True, "id": move_id}


def _input_len(mark: Mark) -> int:
    """Input nodes the mark consumes."""
    if "skip" in mark:
        return mark["skip"]
    if "delete" in mark:
        return mark["delete"]
    if "moveOut" in mark:
        return mark["moveOut"]
    return 0


def _output_len(mark: Mark, moved: Optional[Dict[Any, List[Any]]] = None) -> int:
    """Output nodes the mark produces."""
    if "skip" in mark:
        return mark["skip"]
    if "insert" in mark:
        return len(mark["insert"])
    if "revive" in mark:
        return len(mark["revive"])
    if "moveIn" in mark:
        if moved is not None and mark["id"] in moved:
            return len(moved[mark["id"]])
        return mark.get("count", 0)
    return 0


def normalize(marks: MarkList) -> MarkList:
    """Merge adjacent same-kind marks, drop empties."""
    out: MarkList = []
    for m in marks:
        if ("skip" in m and m["skip"] == 0) or ("delete" in m and m["delete"] == 0):
            continue
        if "insert" in m and not m["insert"]:
            continue
        if "revive" in m and not m["revive"]:
            continue
        if out:
            p = out[-1]
            if "skip" in p and "skip" in m:
                p["skip"] += m["skip"]
                continue
            if "delete" in p and "delete" in m and "content" not in p and "content" not in m:
                p["delete"] += m["delete"]
                continue
        out.append(dict(m))
    # Trailing skips are identity.
    while out and "skip" in out[-1] and True:
        break
    return out


# --------------------------------------------------------------------------
# apply
# --------------------------------------------------------------------------


def apply_marks(seq: List[Any], marks: MarkList,
                capture: bool = True) -> List[Any]:
    """Apply a mark stream to a sequence. With `capture`, delete and
    moveOut marks record the content they detach (in place) so the
    stream becomes invertible — the reference captures repair data the
    same way (delta application feeds repair stores)."""
    out: List[Any] = []
    moved: Dict[Any, List[Any]] = {}
    i = 0
    # First pass: collect moved-out content so moveIn can land even if
    # it appears before its moveOut in the stream.
    j = 0
    for m in marks:
        n = _input_len(m)
        if "moveOut" in m:
            moved[m["id"]] = seq[j: j + n]
        j += n
    if j > len(seq):
        raise ValueError(f"marks consume {j} nodes; sequence has {len(seq)}")
    for m in marks:
        if "skip" in m:
            out.extend(seq[i: i + m["skip"]])
            i += m["skip"]
        elif "insert" in m:
            out.extend(copy.deepcopy(m["insert"]))
        elif "revive" in m:
            out.extend(copy.deepcopy(m["revive"]))
        elif "delete" in m:
            if capture:
                m["content"] = copy.deepcopy(seq[i: i + m["delete"]])
            i += m["delete"]
        elif "moveOut" in m:
            if capture:
                m["count"] = m["moveOut"]
            i += m["moveOut"]
        elif "moveIn" in m:
            content = moved.get(m["id"], [])
            if capture:
                m["count"] = len(content)  # fuels invert (moveIn→moveOut)
            out.extend(copy.deepcopy(content))
    out.extend(seq[i:])
    return out


# --------------------------------------------------------------------------
# invert
# --------------------------------------------------------------------------


def invert_marks(marks: MarkList) -> MarkList:
    """Invert an APPLIED mark stream (delete marks carry captured
    content). Walks the OUTPUT space of `marks`, producing a stream
    that undoes it (invert.ts)."""
    out: MarkList = []
    for m in marks:
        if "skip" in m:
            out.append(skip(m["skip"]))
        elif "insert" in m:
            out.append(delete(len(m["insert"])))
        elif "revive" in m:
            out.append(delete(len(m["revive"])))
        elif "delete" in m:
            if "content" not in m:
                raise ValueError("invert of an unapplied delete (no capture)")
            out.append({"revive": copy.deepcopy(m["content"])})
        elif "moveOut" in m:
            out.append({"moveIn": True, "id": m["id"],
                        "count": m.get("count", 0)})
        elif "moveIn" in m:
            out.append({"moveOut": m.get("count", 0), "id": m["id"]})
    return normalize(out)


# --------------------------------------------------------------------------
# compose
# --------------------------------------------------------------------------


def _split(mark: Mark, n: int, by_input: bool) -> Tuple[Mark, Mark]:
    """Split a mark so the first part covers n input (or output) nodes."""
    m = dict(mark)
    if "skip" in m:
        return skip(n), skip(m["skip"] - n)
    if "delete" in m:
        a, b = delete(n), delete(m["delete"] - n)
        if "content" in m:
            a["content"] = m["content"][:n]
            b["content"] = m["content"][n:]
        return a, b
    if "insert" in m:
        return (
            {"insert": m["insert"][:n], "tie": m.get("tie", 0)},
            {"insert": m["insert"][n:], "tie": m.get("tie", 0)},
        )
    if "revive" in m:
        return {"revive": m["revive"][:n]}, {"revive": m["revive"][n:]}
    raise ValueError(f"cannot split mark {m}")  # moves split unsupported


def compose_marks(a: MarkList, b: MarkList) -> MarkList:
    """compose(A, B): one stream equivalent to applying A then B
    (compose.ts). B is walked in A's OUTPUT space. Move marks are kept
    only when untouched by the other stream (the reference composes
    moves through a cross-field move table; this field-local calculus
    requires non-overlapping moves, which normalize() preserves)."""
    a = [dict(m) for m in normalize(a)]
    b = [dict(m) for m in normalize(b)]
    out: MarkList = []
    ai = 0

    def take_a(n: int) -> List[Mark]:
        """Consume n OUTPUT nodes worth of A-marks. Zero-output marks
        (deletes/moveOuts, invisible to B) ride along IN ORDER — they
        must keep their position between the visible marks."""
        nonlocal ai
        got: List[Mark] = []
        need = n
        while need > 0:
            if ai >= len(a):
                got.append(skip(need))  # implicit trailing skip
                return got
            m = a[ai]
            ol = _output_len(m)
            if ol == 0:
                got.append(m)  # delete/moveOut: invisible to B
                ai += 1
                continue
            if ol <= need:
                got.append(m)
                ai += 1
                need -= ol
            else:
                first, rest = _split(m, need, by_input=False)
                got.append(first)
                a[ai] = rest
                need = 0
        return got

    for bm in b:
        if "skip" in bm:
            out.extend(take_a(bm["skip"]))
        elif "insert" in bm or "revive" in bm or "moveIn" in bm:
            out.append(bm)
        elif "delete" in bm or "moveOut" in bm:
            n = _input_len(bm)
            covered = take_a(n)
            # B deletes nodes that A produced: inserts/revives by A
            # annihilate; A-skips become B-deletes of base content.
            for am in covered:
                if "skip" in am:
                    d = delete(am["skip"])
                    if "moveOut" in bm:
                        d = {"moveOut": am["skip"], "id": bm["id"]}
                    if "content" in bm:
                        d["content"] = None  # re-captured on apply
                    out.append(d)
                elif "insert" in am or "revive" in am:
                    pass  # created by A, destroyed by B: net nothing
                else:
                    out.append(am)
            if "moveOut" in bm:
                # mark id stays live for the paired moveIn
                pass
    # Remaining A-marks pass through.
    while ai < len(a):
        out.append(a[ai])
        ai += 1
    return normalize(out)


# --------------------------------------------------------------------------
# rebase
# --------------------------------------------------------------------------


def rebase_marks(a: MarkList, base: MarkList, base_first: bool = True) -> MarkList:
    """rebase(A over B): rewrite A (authored against state S) to apply
    after B (also authored against S) — rebase.ts. Walks both streams
    in S's input space:

    - base inserts/revives/moveIns shift A's positions (becoming skips
      in A's frame); at the same position, base content goes FIRST
      when `base_first` (the sequenced-earlier op wins the spot);
    - base deletes/moveOuts drop that input range from A's frame: A's
      edits of deleted nodes are MUTED (dropped), and A's inserts
      inside the range slide to the range start.
    """
    a = [dict(m) for m in normalize(a)]
    base = [dict(m) for m in normalize(base)]
    out: MarkList = []
    ai = 0
    a_rem = a[ai] if a else None

    def next_a():
        nonlocal ai, a_rem
        ai += 1
        a_rem = a[ai] if ai < len(a) else None

    def emit_zero_input_a():
        """Flush A-marks that consume no input (inserts at the current
        position) — called before base content claims the spot when A
        should go first."""
        nonlocal a_rem
        while a_rem is not None and _input_len(a_rem) == 0:
            out.append(a_rem)
            next_a()

    for bm in base:
        if "insert" in bm or "revive" in bm or "moveIn" in bm:
            if not base_first:
                emit_zero_input_a()
            out.append(skip(_output_len(bm, None) if "moveIn" not in bm
                            else bm.get("count", 0)))
            continue
        n = _input_len(bm)
        is_del = "delete" in bm or "moveOut" in bm
        # Walk n input nodes of A's stream against this base mark.
        while n > 0:
            if a_rem is None:
                if not is_del:
                    out.append(skip(n))
                n = 0
                break
            al = _input_len(a_rem)
            if al == 0:
                # A-insert inside the range: survives (slides to the
                # current position).
                out.append(a_rem)
                next_a()
                continue
            step = min(al, n)
            if al > step:
                first, rest = _split(a_rem, step, by_input=True)
                cur = first
                a[ai] = rest
                a_rem = rest
            else:
                cur = a_rem
                next_a()
            if is_del:
                pass  # muted: the nodes A touched no longer exist
            else:
                out.append(cur)
            n -= step
    # Remaining A-marks apply beyond base's touched prefix.
    while a_rem is not None:
        out.append(a_rem)
        next_a()
    return normalize(out)
