"""Changesets: the edit algebra (compose / invert / rebase).

The role of the reference's change families
(packages/dds/tree/src/core/rebase/changeRebaser.ts — the
compose/invert/rebase contract — and
feature-libraries/sequence-field/{rebase,compose,invert}.ts — the
list-merge logic).

A *change* is a list of primitive ops applied in order. Each op
addresses a node by `path` — a list of [field, index] steps from the
root — and edits one of its fields:

- {"type": "insert", "path": P, "field": f, "index": i, "content": [trees]}
- {"type": "remove", "path": P, "field": f, "index": i, "count": n,
   "content": [trees]?}           (content captured on apply, for invert)
- {"type": "setValue", "path": P, "value": v, "prev": u?}

Rebase rules (sequence-field semantics):
- an insert by the earlier op at/before your index shifts you right;
- a remove overlapping your position slides you to its start;
- edits under a removed subtree are dropped (the reference's
  "muted"/detached marks);
- two inserts at the same index: the earlier-sequenced op's content
  lands first (ties shift the later op right) — deterministic because
  every replica rebases in total-order.

Tested against the rebase laws (the verifyChangeRebaser contract,
core/rebase/verifyChangeRebaser.ts) and multi-client convergence fuzz.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional

Change = List[dict]


# --------------------------------------------------------------------------
# constructors
# --------------------------------------------------------------------------


def insert_op(path: List[list], field: str, index: int, content: List[dict]) -> dict:
    return {"type": "insert", "path": list(path), "field": field,
            "index": index, "content": content}


def remove_op(path: List[list], field: str, index: int, count: int = 1) -> dict:
    return {"type": "remove", "path": list(path), "field": field,
            "index": index, "count": count}


def set_value_op(path: List[list], value: Any) -> dict:
    return {"type": "setValue", "path": list(path), "value": value}


# --------------------------------------------------------------------------
# compose / invert
# --------------------------------------------------------------------------


def compose(changes: List[Change]) -> Change:
    """Sequential composition (changeRebaser.compose). Changes are op
    lists, so composition is concatenation — associativity and the
    compose laws hold definitionally."""
    out: Change = []
    for c in changes:
        out.extend(copy.deepcopy(c))
    return out


def invert(change: Change) -> Change:
    """Inverse change (changeRebaser.invert): reversed list of per-op
    inverses. Remove inverts to insert of the captured content;
    setValue inverts to setValue of the captured prior value — both
    captured by Forest.apply."""
    out: Change = []
    for op in reversed(change):
        t = op["type"]
        if t == "insert":
            out.append(
                {"type": "remove", "path": op["path"], "field": op["field"],
                 "index": op["index"], "count": len(op["content"]),
                 "content": copy.deepcopy(op["content"])}
            )
        elif t == "remove":
            assert "content" in op, "invert needs an applied remove (content captured)"
            out.append(
                {"type": "insert", "path": op["path"], "field": op["field"],
                 "index": op["index"], "content": copy.deepcopy(op["content"])}
            )
        elif t == "setValue":
            assert "prev" in op, "invert needs an applied setValue (prev captured)"
            out.append(
                {"type": "setValue", "path": op["path"], "value": op["prev"]}
            )
    return out


# --------------------------------------------------------------------------
# rebase
# --------------------------------------------------------------------------


def _adjust_index(
    index: int, base: dict, is_insert_at: bool, base_first: bool = True
) -> Optional[int]:
    """New index for a position `index` in the same field after `base`
    applied. `is_insert_at`: position denotes an insertion gap (can sit
    at either side of existing content) vs an existing-node reference.
    `base_first`: for gap-vs-gap ties (two inserts at the same index),
    whether base's content lands first — True when base sequenced
    earlier (the caller's rebase direction decides). Returns None if
    the referenced node was removed."""
    if base["type"] == "insert":
        b = base["index"]
        n = len(base["content"])
        if is_insert_at:
            if b < index or (b == index and base_first):
                return index + n
            return index
        # A node reference: content inserted at/before the node's slot
        # lands before the node (pure position semantics, no tie).
        return index + n if b <= index else index
    if base["type"] == "remove":
        b = base["index"]
        n = base["count"]
        if index < b:
            return index
        if is_insert_at:
            return max(b, index - n)
        if index < b + n:
            return None  # the node itself was removed
        return index - n
    return index


def _rebase_path(path: List[list], base: dict) -> Optional[List[list]]:
    """Adjust a node path for `base`; None if an ancestor was removed."""
    if base["type"] == "setValue":
        return path
    bpath = base["path"]
    bfield = base["field"]
    # Does base edit a field that is an ancestor step of `path`?
    if len(path) <= len(bpath):
        return path
    for i, step in enumerate(bpath):
        if path[i] != step:
            return path  # divergent ancestry: unaffected
    # path[len(bpath)] descends through the edited node's subtree iff
    # its field matches.
    field, index = path[len(bpath)]
    if field != bfield:
        return path
    new_index = _adjust_index(index, base, is_insert_at=False)
    if new_index is None:
        return None  # ancestor removed: op is muted
    if new_index == index:
        return path
    new_path = [list(s) for s in path]
    new_path[len(bpath)] = [field, new_index]
    return new_path


def rebase_op(op: dict, base: dict, base_first: bool = True) -> Optional[dict]:
    """Rebase one op over one base op (both relative to the same start
    state); returns the adjusted op relative to post-base state, or
    None if muted (its target no longer exists). `base_first` resolves
    same-index insert ties (True when base sequenced earlier)."""
    new_path = _rebase_path(op["path"], base)
    if new_path is None:
        return None
    op = {**op, "path": new_path}
    if op["type"] == "setValue":
        # Concurrent setValue on the same node: last-sequenced wins —
        # the earlier write mutes when rebased over the later one.
        if (
            base["type"] == "setValue"
            and base["path"] == op["path"]
            and not base_first
        ):
            return None
        return op
    # Same-field index adjustment.
    if (
        base["type"] != "setValue"
        and base["path"] == op["path"]
        and base["field"] == op["field"]
    ):
        if op["type"] == "insert":
            idx = _adjust_index(
                op["index"], base, is_insert_at=True, base_first=base_first
            )
            return {**op, "index": idx}
        # remove: adjust both ends against the base edit.
        start, count = op["index"], op["count"]
        if base["type"] == "insert":
            b, n = base["index"], len(base["content"])
            if b <= start:
                return {**op, "index": start + n}
            if b < start + count:
                # Base inserted strictly inside our removed range: the
                # inserted content is kept — split into two removes
                # (after-part first so the before-part's index stays
                # valid when they apply sequentially).
                left = b - start
                return {
                    "type": "multi",
                    "ops": [
                        {**op, "index": b + n, "count": count - left},
                        {**op, "index": start, "count": left},
                    ],
                }
            return op
        else:  # base remove
            b, n = base["index"], base["count"]
            o_start, o_end = start, start + count
            b_start, b_end = b, b + n
            lost = max(0, min(o_end, b_end) - max(o_start, b_start))
            new_count = count - lost
            if new_count <= 0:
                return None
            new_start = o_start if o_start < b_start else max(b_start, o_start - n)
            return {**op, "index": new_start, "count": new_count}
    return op


def _flatten_one(op: Optional[dict]) -> Change:
    if op is None:
        return []
    if op.get("type") == "multi":
        return list(op["ops"])
    return [op]


def rebase_change(change: Change, over: Change, over_first: bool = True) -> Change:
    """Rebase `change` over `over` (changeRebaser.rebase): both start
    from the same state; the result applies after `over`.

    `over_first` resolves same-index insert ties: True when `over`
    sequenced earlier than `change` (the normal direction); False when
    rebasing an earlier-sequenced change over later local ops (e.g.
    transforming a remote commit over the unsequenced local branch for
    forest application).

    Uses the transform ladder: each op of `change` is rebased over the
    advancing base, and the base is advanced over each rebased-past op
    (with the dual tie-break), so later ops of `change` — whose
    coordinates assume their predecessors applied — transform against
    a correctly shifted base.
    """
    current = [copy.deepcopy(op) for op in change]
    for base0 in over:
        bases = [base0]
        nxt: Change = []
        for op in current:
            transformed: List[Optional[dict]] = [op]
            new_bases: Change = []
            for b in bases:
                step: List[Optional[dict]] = []
                for t in transformed:
                    if t is None:
                        continue
                    step.append(rebase_op(t, b, base_first=over_first))
                transformed = step
                # Advance this base past the ORIGINAL op (dual tie).
                adv = rebase_op(b, op, base_first=not over_first)
                new_bases.extend(_flatten_one(adv))
            bases = new_bases
            for t in transformed:
                nxt.extend(_flatten_one(t))
        current = nxt
    return current
