"""Changesets: the edit algebra (compose / invert / rebase).

The role of the reference's change families
(packages/dds/tree/src/core/rebase/changeRebaser.ts — the
compose/invert/rebase contract — and
feature-libraries/sequence-field/{rebase,compose,invert}.ts — the
list-merge logic).

A *change* is a list of primitive ops applied in order. Each op
addresses a node by `path` — a list of [field, index] steps from the
root — and edits one of its fields:

- {"type": "insert", "path": P, "field": f, "index": i, "content": [trees]}
- {"type": "remove", "path": P, "field": f, "index": i, "count": n,
   "content": [trees]?}           (content captured on apply, for invert)
- {"type": "setValue", "path": P, "value": v, "prev": u?}

Rebase rules (sequence-field semantics):
- an insert by the earlier op at/before your index shifts you right;
- a remove overlapping your position slides you to its start;
- edits under a removed subtree are dropped (the reference's
  "muted"/detached marks);
- two inserts at the same index: the earlier-sequenced op's content
  lands first (ties shift the later op right) — deterministic because
  every replica rebases in total-order.

Tested against the rebase laws (the verifyChangeRebaser contract,
core/rebase/verifyChangeRebaser.ts) and multi-client convergence fuzz.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Tuple

Change = List[dict]


# --------------------------------------------------------------------------
# constructors
# --------------------------------------------------------------------------


def insert_op(path: List[list], field: str, index: int, content: List[dict]) -> dict:
    return {"type": "insert", "path": list(path), "field": field,
            "index": index, "content": content}


def remove_op(path: List[list], field: str, index: int, count: int = 1) -> dict:
    return {"type": "remove", "path": list(path), "field": field,
            "index": index, "count": count}


def set_value_op(path: List[list], value: Any) -> dict:
    return {"type": "setValue", "path": list(path), "value": value}


def move_op(path: List[list], field: str, index: int, count: int,
            dst_path: List[list], dst_field: str, dst_index: int) -> dict:
    """Move `count` nodes from (path, field)[index:index+count] to
    (dst_path, dst_field) at gap `dst_index`. Cross-field and
    cross-parent moves are first-class (the role of the reference's
    cross-field move-effect table,
    feature-libraries/sequence-field/compose.ts + moveEffectTable.ts).

    ALL coordinates — source and destination — are in the op's input
    (pre-op) frame; `Forest.apply` performs the detach-then-attach
    conversion itself (a destination gap inside the moved range clamps
    to its start). One uniform frame keeps the rebase arithmetic's
    gap-tie comparisons exact."""
    return {
        "type": "move", "path": list(path), "field": field,
        "index": index, "count": count, "dst_path": list(dst_path),
        "dst_field": dst_field, "dst_index": dst_index,
    }


# --------------------------------------------------------------------------
# compose / invert
# --------------------------------------------------------------------------


def compose(changes: List[Change]) -> Change:
    """Sequential composition (changeRebaser.compose). Changes are op
    lists, so composition is concatenation — associativity and the
    compose laws hold definitionally."""
    out: Change = []
    for c in changes:
        out.extend(copy.deepcopy(c))
    return out


def invert(change: Change) -> Change:
    """Inverse change (changeRebaser.invert): reversed list of per-op
    inverses. Remove inverts to insert of the captured content;
    setValue inverts to setValue of the captured prior value — both
    captured by Forest.apply."""
    out: Change = []
    for op in reversed(change):
        t = op["type"]
        if t == "insert":
            out.append(
                {"type": "remove", "path": op["path"], "field": op["field"],
                 "index": op["index"], "count": len(op["content"]),
                 "content": copy.deepcopy(op["content"])}
            )
        elif t == "remove":
            assert "content" in op, "invert needs an applied remove (content captured)"
            out.append(
                {"type": "insert", "path": op["path"], "field": op["field"],
                 "index": op["index"], "content": copy.deepcopy(op["content"])}
            )
        elif t == "setValue":
            assert "prev" in op, "invert needs an applied setValue (prev captured)"
            out.append(
                {"type": "setValue", "path": op["path"], "value": op["prev"]}
            )
        elif t == "move":
            if op.get("muted"):
                continue  # applied as a no-op (cycle guard): nothing to undo
            assert "inverse" in op, "invert needs an applied move"
            out.append(copy.deepcopy(op["inverse"]))
    return out


# --------------------------------------------------------------------------
# rebase
# --------------------------------------------------------------------------


def _adjust_index(
    index: int, base: dict, is_insert_at: bool, base_first: bool = True
) -> Optional[int]:
    """New index for a position `index` in the same field after `base`
    applied. `is_insert_at`: position denotes an insertion gap (can sit
    at either side of existing content) vs an existing-node reference.
    `base_first`: for gap-vs-gap ties (two inserts at the same index),
    whether base's content lands first — True when base sequenced
    earlier (the caller's rebase direction decides). Returns None if
    the referenced node was removed."""
    if base["type"] == "insert":
        b = base["index"]
        n = len(base["content"])
        if is_insert_at:
            if b < index or (b == index and base_first):
                return index + n
            return index
        # A node reference: content inserted at/before the node's slot
        # lands before the node (pure position semantics, no tie).
        return index + n if b <= index else index
    if base["type"] == "remove":
        b = base["index"]
        n = base["count"]
        if index < b:
            return index
        if is_insert_at:
            return max(b, index - n)
        if index < b + n:
            return None  # the node itself was removed
        return index - n
    return index


def _attach_gap(base: dict) -> int:
    """A move's attach position in its POST-DETACH frame (the frame
    adjustments operate in after applying the detach half). Gaps
    inside the moved range clamp to its start."""
    j = base["dst_index"]
    if (base["dst_path"] == base["path"]
            and base["dst_field"] == base["field"]):
        i, n = base["index"], base["count"]
        if j >= i + n:
            return j - n
        if j > i:
            return i
    return j


def _dst_path_post(base: dict) -> List[list]:
    """A move's destination path converted to the POST-BASE frame:
    steps through the base's own source field shift when they sit
    past the detached range (the conversion Forest.apply performs;
    rebased follower ops must use the same frame)."""
    dp = [list(s) for s in base["dst_path"]]
    plen = len(base["path"])
    if (len(dp) > plen and dp[:plen] == [list(s) for s in base["path"]]
            and dp[plen][0] == base["field"]
            and dp[plen][1] >= base["index"] + base["count"]):
        dp[plen][1] -= base["count"]
    return dp


def _move_parts(base: dict) -> Tuple[dict, dict]:
    """A move base as its detach (remove-like) and attach
    (insert-like) halves. The attach half's index AND path are
    converted to the post-detach frame so adjustments apply
    detach-then-attach consistently."""
    rm = {"type": "remove", "path": base["path"], "field": base["field"],
          "index": base["index"], "count": base["count"]}
    ins = {"type": "insert", "path": _dst_path_post(base),
           "field": base["dst_field"], "index": _attach_gap(base),
           "content": [None] * base["count"], "from_move": True}
    return rm, ins


def _rebase_path(path: List[list], base: dict,
                 base_first: bool = True) -> Optional[List[list]]:
    """Adjust a node path for `base`; None if an ancestor was removed.
    A path descending through nodes a base MOVE carried away is
    RE-ROOTED at the destination — edits follow moves (the reference's
    move-effect semantics, sequence-field/moveEffectTable.ts)."""
    if base["type"] == "setValue":
        return path
    if base["type"] == "move":
        rm, ins = _move_parts(base)
        bpath, bfield = base["path"], base["field"]
        lo, n = base["index"], base["count"]
        if len(path) > len(bpath) and path[: len(bpath)] == bpath:
            field, index = path[len(bpath)]
            if field == bfield and lo <= index < lo + n:
                # Follow the move: re-root under the destination (in
                # the POST-BASE frame).
                new_step = [base["dst_field"], _attach_gap(base) + (index - lo)]
                return (
                    _dst_path_post(base)
                    + [new_step]
                    + [list(s) for s in path[len(bpath) + 1:]]
                )
        p = _rebase_path(path, rm, base_first)
        if p is None:
            return None  # unreachable: in-range refs follow above
        return _rebase_path(p, ins, base_first)
    bpath = base["path"]
    bfield = base["field"]
    # Does base edit a field that is an ancestor step of `path`?
    if len(path) <= len(bpath):
        return path
    for i, step in enumerate(bpath):
        if path[i] != step:
            return path  # divergent ancestry: unaffected
    # path[len(bpath)] descends through the edited node's subtree iff
    # its field matches.
    field, index = path[len(bpath)]
    if field != bfield:
        return path
    new_index = _adjust_index(index, base, is_insert_at=False)
    if new_index is None:
        return None  # ancestor removed: op is muted
    if new_index == index:
        return path
    new_path = [list(s) for s in path]
    new_path[len(bpath)] = [field, new_index]
    return new_path


def _same_field(a_path, a_field, b: dict) -> bool:
    return b["path"] == a_path and b["field"] == a_field


def _sequentialize(parts: List[dict]) -> Optional[dict]:
    """Convert range-op parts expressed in ONE common frame (and in
    source-node order) into a sequentially-applicable op list: each
    part rebases over its predecessors (a part CAN re-split over a
    previous part — e.g. the previous part's attach landing inside
    its range under the mutual arbitration — so parts advance as op
    lists; shared destination gaps resolve earlier-part-first,
    preserving source order)."""
    out: List[dict] = []
    for p in parts:
        # mute_noop=False: a part can be identity-SHAPED in the
        # common frame by coincidence (its source block adjacent
        # to the shared destination gap) while still carrying
        # reorder meaning through the parts' shared-gap tie
        # resolution — only user-level ops canonicalize away.
        # A part CAN re-split over a previous part (e.g. the previous
        # part's attach landing inside its range under the mutual
        # arbitration), so each part advances as an op LIST.
        queue: Change = [copy.deepcopy(p)]
        for prev in out:
            if not queue:
                break
            queue, _ = _xform(
                queue, [copy.deepcopy(prev)], True, mute_noop=False
            )
        out.extend(queue)
    if not out:
        return None
    if len(out) == 1:
        return out[0]
    return {"type": "multi", "ops": out}


def _range_over_base(op: dict, base: dict, base_first: bool,
                     absorb_attach: bool = True,
                     dst_traveled: bool = False) -> Optional[dict]:
    """Adjust a RANGE op (remove, or the source end of a move) whose
    (path, field) equals the base edit's. Returns op / multi / None.
    `absorb_attach=False` (the MUTUAL-containment arbitration): a
    move-attach landing inside our moved range splits us instead of
    being absorbed — see rebase_op's mutual check. `dst_traveled`:
    the op's own destination gap traveled with base's moved block
    (it sat strictly inside), so an op-move losing a claim
    competition to a later base still re-moves the nodes WITHIN the
    landed block instead of muting (a block-internal rearrangement)."""
    start, count = op["index"], op["count"]
    if base["type"] == "insert":
        b, n = base["index"], len(base["content"])
        if b <= start:
            return {**op, "index": start + n}
        if b < start + count:
            if op["type"] == "move" and (
                absorb_attach or not base.get("from_move")
            ):
                # Content inserted strictly inside a moved block
                # TRAVELS with it (the block is one unit; the dual
                # gap rule sends inserts inside a moved range to the
                # destination) — absorb it.
                return {**op, "count": count + n}
            # A remove (or an earlier move losing the mutual-
            # containment arbitration) must not consume content it
            # never saw: split around it (parts in the common
            # post-base frame, then sequentialized).
            left = b - start
            return _sequentialize([
                {**op, "index": start, "count": left},
                {**op, "index": b + n, "count": count - left},
            ])
        return op
    if base["type"] == "remove":
        b, n = base["index"], base["count"]
        o_start, o_end = start, start + count
        lost = max(0, min(o_end, b + n) - max(o_start, b))
        new_count = count - lost
        if new_count <= 0:
            return None  # fully consumed: removed content wins
        new_start = o_start if o_start < b else max(b, o_start - n)
        return {**op, "index": new_start, "count": new_count}
    if base["type"] == "move":
        rm, ins = _move_parts(base)
        if not _same_field(op["path"], op["field"], rm):
            # Our range holds no moved-out nodes; only the attach side
            # can shift or split it.
            if _same_field(op["path"], op["field"], ins):
                return _range_over_base(op, ins, base_first, absorb_attach)
            return op
        b, n = base["index"], base["count"]
        o_start, o_end = start, start + count
        ov_lo, ov_hi = max(o_start, b), min(o_end, b + n)
        if ov_lo >= ov_hi:
            # No node overlap: source-field detach, then the full
            # attach treatment (which splits a remove around — or has
            # a move absorb — a same-field re-attach landing inside
            # the adjusted range).
            p = _range_over_base(op, rm, base_first)
            return _multi_map(
                p,
                lambda q: (
                    _range_over_base(q, ins, base_first, absorb_attach)
                    if _same_field(q["path"], q.get("field"), ins)
                    else q
                ),
            )
        # Overlapping nodes were carried to base's destination. The
        # remainder sub-ranges (outside the overlap) adjust first so
        # we can tell whether base's attach was ABSORBED into one of
        # them — if it was, the moved nodes (overlap included) are
        # already re-claimed inside the absorbing range, and a follow
        # part would DOUBLE-claim them (the base rearranged nodes
        # within our block; no chase needed).
        absorbed = False

        def _remainder(lo: int, hi: int) -> List[dict]:
            nonlocal absorbed
            part = _range_over_base(
                {**op, "index": lo, "count": hi - lo}, rm, base_first
            )

            def fix(q: dict) -> Optional[dict]:
                nonlocal absorbed
                if _same_field(q["path"], q.get("field"), ins):
                    r = _range_over_base(q, ins, base_first, absorb_attach)
                    if (
                        r is not None
                        and r.get("type") != "multi"
                        and r.get("count", 0) > q["count"]
                    ):
                        absorbed = True
                    return r
                return q

            return _flatten_one(_multi_map(part, fix))

        pre_parts = _remainder(o_start, ov_lo) if o_start < ov_lo else []
        post_parts = _remainder(ov_hi, o_end) if ov_hi < o_end else []
        follow_parts: List[dict] = []
        muted_claim = (
            op["type"] == "move" and not base_first and not dst_traveled
        )
        if not muted_claim and not absorbed:
            # Follow: the nodes now live at base's destination.
            follow_parts = [{
                **op,
                "path": _dst_path_post(base),
                "field": base["dst_field"],
                "index": _attach_gap(base) + (ov_lo - b),
                "count": ov_hi - ov_lo,
            }]
        parts = pre_parts + follow_parts + post_parts
        if not parts:
            return None
        # Parts were built in source-node order in the common
        # post-base frame; sequentialize for application.
        return _sequentialize(parts)
    return op


def _multi_map(op: Optional[dict], fn) -> Optional[dict]:
    if op is None:
        return None
    if op.get("type") == "multi":
        ops = []
        for q in op["ops"]:
            r = fn(q)
            ops.extend(_flatten_one(r))
        if not ops:
            return None
        return {"type": "multi", "ops": ops} if len(ops) > 1 else ops[0]
    return fn(op)


def _gap_over_base(index: int, path, field, base: dict,
                   base_first: bool, travel: bool = True):
    """Adjust an insertion GAP (insert index, or a move's destination
    gap) in (path, field) over `base`. Returns ``(index, path,
    field)`` — a gap strictly inside a base-moved block TRAVELS with
    it to the destination field. `travel=False` (the
    mutual-containment arbitration for a LATER move that will absorb
    base's block — see rebase_op): the gap slides to the detach start
    instead, since traveling would land it inside its own absorbed
    range (a self-cycle)."""
    if base["type"] == "setValue":
        return index, path, field
    if base["type"] == "move":
        rm, ins = _move_parts(base)
        idx = index
        # The gap's ORIGINAL adjacency to the moved block: a gap
        # hugging the block keeps its side when the attach lands on
        # it (in particular, a same-field no-op move shifts nothing);
        # only coincidental ties fall back to sequencing order.
        adjacency = None
        if _same_field(path, field, rm):
            b, n = base["index"], base["count"]
            if travel and b < idx < b + n:
                # A gap strictly inside the moved block travels with
                # it to the destination (content is one unit; the
                # dual: the move absorbs content inserted there).
                return (
                    _attach_gap(base) + (idx - b),
                    _dst_path_post(base),
                    base["dst_field"],
                )
            if idx == b:
                adjacency = "before"
            elif idx == b + n:
                adjacency = "after"
            idx = _adjust_index(idx, rm, is_insert_at=True,
                                base_first=base_first)
        if _same_field(path, field, ins):
            # Both gaps are now in the post-detach frame (ins.index is
            # the converted attach gap), so ties compare exactly.
            b, n = ins["index"], base["count"]
            if b < idx:
                idx = idx + n
            elif b == idx:
                if adjacency == "after":
                    idx = idx + n
                elif adjacency is None and base_first:
                    idx = idx + n
        return idx, path, field
    if _same_field(path, field, base):
        return (
            _adjust_index(index, base, is_insert_at=True,
                          base_first=base_first),
            path, field,
        )
    return index, path, field


def _is_noop_move(m: dict) -> bool:
    """A move that applies as a no-op on every replica: a self-cycle
    (destination inside its own moved nodes), or a same-field identity
    (destination gap touching or inside its own source range — detach
    + reattach at the same spot). Canonicalizing these matters for
    convergence: an identity move's numeric gap would otherwise
    tie-break against concurrent attaches direction-dependently."""
    if m.get("type") != "move":
        return False
    if (
        m["dst_path"] == m["path"]
        and m["dst_field"] == m["field"]
        and m["index"] <= m["dst_index"] <= m["index"] + m["count"]
    ):
        return True
    plen = len(m["path"])
    dp = m["dst_path"]
    if len(dp) <= plen or dp[:plen] != m["path"]:
        return False
    f, k = dp[plen]
    return f == m["field"] and m["index"] <= k < m["index"] + m["count"]


def _src_inside_removed(rm_op: dict, descendant_path: List[list]) -> bool:
    """Does `descendant_path` pass through a node `rm_op` removes?"""
    plen = len(rm_op["path"])
    if len(descendant_path) <= plen:
        return False
    if descendant_path[:plen] != rm_op["path"]:
        return False
    f, k = descendant_path[plen]
    return f == rm_op["field"] and (
        rm_op["index"] <= k < rm_op["index"] + rm_op["count"]
    )


def rebase_op(op: dict, base: dict, base_first: bool = True,
              mute_noop: bool = True) -> Optional[dict]:
    """Rebase one op over one base op (both relative to the same start
    state); returns the adjusted op (possibly a {"type": "multi"}
    bundle) relative to post-base state, or None if muted (its target
    no longer exists). `base_first` resolves same-position ties (True
    when base sequenced earlier).

    Move semantics (the cross-field move-effect rules,
    sequence-field/moveEffectTable.ts):
    - edits whose path descends through moved nodes FOLLOW the move
      (path re-rooted at the destination, _rebase_path);
    - a remove overlapping moved nodes follows them, and a SUBTREE
      remove chases nodes concurrently moved out of it (removal wins
      over movement, in both rebase directions); a move into a
      concurrently-removed destination kills its source nodes;
    - two moves competing for the same nodes: the LATER-sequenced move
      wins (it re-moves from the earlier move's destination; the
      earlier move's claim mutes when rebased over the later one);
    - content inserted strictly inside a moved block travels with it
      (the move absorbs it); inserted inside a REMOVED range it stays,
      sliding to the range start (removes split around it).

    Overlapping/competing block claims (the reference's per-move-id
    move-effect table, sequence-field/moveEffectTable.ts) resolve via
    three arbitration rules, exhaustively verified convergent
    (tests/test_tree_moves.py sweeps: 2916 + 11025 pairs, zero
    divergence):
    - parts sequentialize in ONE post-base frame (destination gap
      converts before the source range splits);
    - MUTUAL containment (each move's gap strictly inside the other's
      block): the later move absorbs but its gap slides instead of
      traveling (self-cycle guard); the earlier splits around the
      later's attach instead of absorbing;
    - a losing earlier move whose destination traveled with the
      winner's block re-moves nodes WITHIN the landed block instead
      of muting (block-internal rearrangement).
    """
    if _is_noop_move(base):
        return op  # no-op base: nothing to adjust for
    if mute_noop and _is_noop_move(op):
        return None  # an identity move rebases to nothing
    orig = op
    new_path = _rebase_path(op["path"], base, base_first)
    if new_path is None:
        return None  # ancestor removed: muted (removal wins over all)
    op = {**op, "path": new_path}
    if op["type"] == "move":
        nd = _rebase_path(op["dst_path"], base, base_first)
        if nd is None:
            # Destination subtree removed: the move proceeds into the
            # void — its nodes die with the destination (removal wins;
            # the dual direction removes them inside the subtree).
            return rebase_op(
                {"type": "remove", "path": orig["path"],
                 "field": orig["field"], "index": orig["index"],
                 "count": orig["count"]},
                base, base_first,
            )
        op = {**op, "dst_path": nd}
    if op["type"] == "setValue":
        # Concurrent setValue on the same node: last-sequenced wins —
        # the earlier write mutes when rebased over the later one.
        if (
            base["type"] == "setValue"
            and base["path"] == op["path"]
            and not base_first
        ):
            return None
        return op
    if base["type"] == "setValue":
        return op

    if op["type"] == "insert":
        if _same_field(op["path"], op["field"], base) or (
            base["type"] == "move"
            and (_same_field(op["path"], op["field"], _move_parts(base)[0])
                 or _same_field(op["path"], op["field"], _move_parts(base)[1]))
        ):
            idx, npath, nfield = _gap_over_base(
                op["index"], op["path"], op["field"], base, base_first
            )
            return {**op, "index": idx, "path": npath, "field": nfield}
        return op

    if op["type"] == "remove":
        if base["type"] == "move" and _src_inside_removed(op, base["path"]):
            # Base moved nodes OUT of a subtree our remove covers:
            # removal wins — chase the moved nodes to their
            # destination (the dual of the muted move-out; both
            # directions end with the nodes gone). The chase part is
            # in the post-base frame; its destination coordinates
            # survive only if the destination itself survives.
            rm, ins = _move_parts(base)
            adj = op
            if _same_field(op["path"], op["field"], rm):
                adj = _range_over_base(op, base, base_first)
            elif _same_field(op["path"], op["field"], ins):
                adj = _range_over_base(op, ins, base_first)
            chase_path = _rebase_path(
                [list(s) for s in base["dst_path"]], rm, base_first
            )
            parts = _flatten_one(adj)
            if chase_path is not None and not _src_inside_removed(
                op, base["dst_path"]
            ):
                parts = parts + [{
                    "type": "remove", "path": chase_path,
                    "field": base["dst_field"],
                    "index": _attach_gap(base),
                    "count": base["count"],
                }]
            return _sequentialize(parts)
        if _same_field(op["path"], op["field"], base):
            return _range_over_base(op, base, base_first)
        if base["type"] == "move":
            rm, ins = _move_parts(base)
            if _same_field(op["path"], op["field"], rm):
                return _range_over_base(op, base, base_first)
            if _same_field(op["path"], op["field"], ins):
                # Foreign content attached into our field: split around
                # it like an insert.
                return _range_over_base(op, ins, base_first)
        return op

    if op["type"] == "move":
        # MUTUAL containment (in the common frame, using the ORIGINAL
        # coordinates): our gap sits strictly inside base's moved
        # block AND base's gap sits strictly inside ours — cyclic
        # block claims, which absorbed each other into a
        # direction-dependent identity. Arbitrate by sequencing: the
        # LATER move absorbs as usual; the EARLIER one (rebasing over
        # a later base) splits around the base's attach instead
        # (reference: per-move-id move-effect table,
        # sequence-field/moveEffectTable.ts).
        gap_in_base_block = (
            base["type"] == "move"
            and orig["dst_path"] == base["path"]
            and orig["dst_field"] == base["field"]
            and base["index"] < orig["dst_index"]
            < base["index"] + base["count"]
        )
        mutual = (
            gap_in_base_block
            and base["dst_path"] == orig["path"]
            and base["dst_field"] == orig["field"]
            and orig["index"] < base["dst_index"]
            < orig["index"] + orig["count"]
        )
        # Destination end FIRST: the gap converts to the post-base
        # frame, so the source parts built below — and their
        # sequentialization, whose per-part rebases adjust this gap
        # over earlier parts — all share ONE frame. (Adjusting the gap
        # after sequentialization composed the base- and
        # preceding-part shifts in the wrong order, the former 52-pair
        # same-field divergence class.)
        # Did our destination gap travel with base's moved block (it
        # sat strictly inside base's source range)? A losing earlier
        # move whose destination traveled still rearranges WITHIN the
        # landed block instead of muting.
        traveled = gap_in_base_block and not (mutual and base_first)
        d, dp, df = _gap_over_base(
            op["dst_index"], op["dst_path"], op["dst_field"], base,
            base_first, travel=not (mutual and base_first),
        )
        op = {**op, "dst_index": d, "dst_path": dp, "dst_field": df}
        # Source end: a range, like remove (follow/mute rules apply).
        if _same_field(op["path"], op["field"], base) or base["type"] == "move":
            if base["type"] == "move":
                affected = (
                    _same_field(op["path"], op["field"], _move_parts(base)[0])
                    or _same_field(op["path"], op["field"],
                                   _move_parts(base)[1])
                )
            else:
                affected = True
            if affected:
                return _range_over_base(
                    op, base, base_first,
                    absorb_attach=not (mutual and not base_first),
                    dst_traveled=traveled,
                )
        return op

    return op


def _flatten_one(op: Optional[dict]) -> Change:
    if op is None:
        return []
    if op.get("type") == "multi":
        return list(op["ops"])
    return [op]


def rebase_change(change: Change, over: Change, over_first: bool = True) -> Change:
    """Rebase `change` over `over` (changeRebaser.rebase): both start
    from the same state; the result applies after `over`.

    `over_first` resolves same-index insert ties: True when `over`
    sequenced earlier than `change` (the normal direction); False when
    rebasing an earlier-sequenced change over later local ops (e.g.
    transforming a remote commit over the unsequenced local branch for
    forest application).

    Implemented as an inclusion transform over op LISTS (the
    operational-transform ladder in its general form): transforming
    one op past another may split it into several sequential parts
    (multi), and the dual side advances symmetrically, so both sides
    are op lists throughout. The walk over `over` is an explicit loop
    (each base op's successors are already expressed in its output
    frame, so no advancement of later base ops over `change` is
    needed at this level) — recursion depth stays bounded by the
    CHANGE's length, not the rebase window's.
    """
    a = [copy.deepcopy(op) for op in change]
    for b in over:
        a, _ = _xform(a, [copy.deepcopy(b)], over_first)
    return a


def _xform(A: Change, B: Change, flag: bool,
           mute_noop: bool = True) -> Tuple[Change, Change]:
    """Inclusion transform of sequential op lists sharing one start
    state: returns ``(A', B')`` with A' applying after B, and B'
    after A. `flag`: B's content wins position ties (B sequenced
    earlier). Recursion depth is O(len(A) + len(B) + splits)."""
    if not A or not B:
        return list(A), list(B)
    if len(A) == 1 and len(B) == 1:
        a_p = _flatten_one(
            rebase_op(A[0], B[0], base_first=flag, mute_noop=mute_noop)
        )
        b_p = _flatten_one(
            rebase_op(B[0], A[0], base_first=not flag,
                      mute_noop=mute_noop)
        )
        return a_p, b_p
    if len(A) > 1:
        A1p, Bp = _xform(A[:1], B, flag, mute_noop)
        A2p, Bpp = _xform(A[1:], Bp, flag, mute_noop)
        return A1p + A2p, Bpp
    Ap, B1p = _xform(A, B[:1], flag, mute_noop)
    App, B2p = _xform(Ap, B[1:], flag, mute_noop)
    return App, B1p + B2p
