"""IdCompressor: session-space ↔ final-space compact ids.

The role of the reference IdCompressor
(packages/dds/tree/src/id-compressor/idCompressor.ts:272): sessions
generate ids locally without coordination (negative *local* ids);
when the ops carrying them are sequenced, ranges are *finalized* into
compact non-negative final ids allocated in per-session clusters (so a
session's consecutive ids stay contiguous — cheap range encoding).
`normalize_to_op_space` translates local ids for the wire;
`normalize_to_session_space` translates received final ids back.

Every replica finalizes the same ranges in the same total order, so
the local→final mapping is identical everywhere — the property the
reference's compressed-id equality relies on.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

DEFAULT_CLUSTER_CAPACITY = 512


@dataclass
class _Cluster:
    base_final: int
    base_local: int  # first local ordinal (1-based count) covered
    capacity: int
    count: int = 0


class IdCompressor:
    def __init__(self, session_id: str,
                 cluster_capacity: int = DEFAULT_CLUSTER_CAPACITY):
        self.session_id = session_id
        self.cluster_capacity = cluster_capacity
        self._local_count = 0  # ids this session has generated
        self._next_final = 0  # next unallocated final id (global)
        # session -> clusters (in allocation order)
        self._clusters: Dict[str, List[_Cluster]] = {}
        # how many of each session's locals have been finalized
        self._finalized: Dict[str, int] = {}

    # ---------------------------------------------------------- generate

    def generate_compressed_id(self) -> int:
        """A new session-local id: -1, -2, ... (idCompressor
        generateCompressedId)."""
        self._local_count += 1
        return -self._local_count

    # ---------------------------------------------------------- finalize

    def finalize_range(self, session: str, count: int) -> None:
        """Finalize the next `count` locals of `session` (called in
        total order on every replica as the carrying ops sequence)."""
        done = self._finalized.get(session, 0)
        clusters = self._clusters.setdefault(session, [])
        remaining = count
        while remaining > 0:
            tail = clusters[-1] if clusters else None
            if tail is None or tail.count == tail.capacity:
                tail = _Cluster(
                    base_final=self._next_final,
                    base_local=done + 1,
                    capacity=max(self.cluster_capacity, remaining),
                )
                self._next_final += tail.capacity
                clusters.append(tail)
            take = min(remaining, tail.capacity - tail.count)
            tail.count += take
            done += take
            remaining -= take
        self._finalized[session] = done

    # --------------------------------------------------------- translate

    def _local_to_final(self, session: str, local: int) -> Optional[int]:
        ordinal = -local  # 1-based
        for cl in self._clusters.get(session, []):
            if cl.base_local <= ordinal < cl.base_local + cl.count:
                return cl.base_final + (ordinal - cl.base_local)
        return None

    def normalize_to_op_space(self, local_id: int) -> int:
        """Own local id → final (if finalized) or the local itself
        (receivers resolve via the carrying op's session)."""
        if local_id >= 0:
            return local_id
        final = self._local_to_final(self.session_id, local_id)
        return final if final is not None else local_id

    def normalize_to_session_space(self, op_id: int, originator: str) -> int:
        """An id from the wire → this session's space: finals pass
        through; a foreign local id maps via the originator's clusters
        (it must have been finalized by the time we see it... unless it
        is ours)."""
        if op_id >= 0:
            return op_id
        if originator == self.session_id:
            return op_id  # our own local: still usable locally
        final = self._local_to_final(originator, op_id)
        if final is None:
            raise KeyError(
                f"unfinalized foreign id {op_id} from session {originator}"
            )
        return final

    def decompress(self, final_id: int) -> Tuple[str, int]:
        """final id → (session, 1-based ordinal) (stable UUID-like
        identity in the reference; the pair plays that role here)."""
        for session, clusters in self._clusters.items():
            for cl in clusters:
                if cl.base_final <= final_id < cl.base_final + cl.count:
                    return session, cl.base_local + (final_id - cl.base_final)
        raise KeyError(f"unknown final id {final_id}")

    # --------------------------------------------------------- serialize

    def serialize(self) -> dict:
        return {
            "sessionId": self.session_id,
            "clusterCapacity": self.cluster_capacity,
            "localCount": self._local_count,
            "nextFinal": self._next_final,
            "finalized": dict(self._finalized),
            "clusters": {
                s: [[c.base_final, c.base_local, c.capacity, c.count] for c in cs]
                for s, cs in self._clusters.items()
            },
        }

    @classmethod
    def deserialize(cls, data: dict, session_id: Optional[str] = None) -> "IdCompressor":
        out = cls(session_id or data["sessionId"], data["clusterCapacity"])
        out._local_count = data["localCount"] if session_id in (None, data["sessionId"]) else 0
        out._next_final = data["nextFinal"]
        out._finalized = dict(data["finalized"])
        out._clusters = {
            s: [_Cluster(a, b, c, d) for a, b, c, d in cs]
            for s, cs in data["clusters"].items()
        }
        return out
