"""IdCompressor: session-space <-> final-space compact ids.

The role of the reference IdCompressor
(packages/dds/tree/src/id-compressor/idCompressor.ts:272): sessions
generate ids locally without coordination (negative *local* ids);
when the ops carrying them are sequenced, ranges are *finalized* into
compact non-negative final ids allocated in per-session clusters (so a
session's consecutive ids stay contiguous — cheap range encoding).
`normalize_to_op_space` translates local ids for the wire;
`normalize_to_session_space` translates received final ids back.

Cluster machinery (the reference's scale features, idCompressor.ts):

- **Cluster expansion**: when a session exhausts its tail cluster and
  that cluster is still the newest allocation in final space, it
  EXPANDS in place instead of allocating a new cluster — a dominant
  writer occupies one ever-growing cluster rather than many.
- **Eager finals**: once a session owns a cluster with spare
  capacity, freshly generated ids map into it IMMEDIATELY (non-
  negative ids straight from `generate_compressed_id`), skipping the
  local->final translation on every later use.
- **O(log n) translation**: lookups bisect over cluster bases instead
  of scanning (1M-id scale, tests/test_tree_depth.py).

Every replica finalizes the same ranges in the same total order, so
the local->final mapping is identical everywhere — the property the
reference's compressed-id equality relies on.
"""

from __future__ import annotations

import uuid
from bisect import bisect_right
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

DEFAULT_CLUSTER_CAPACITY = 512

# uuid5 namespace for sessions whose ids are not themselves UUIDs.
_SESSION_NS = uuid.UUID("7d0efc6f-6a66-4b6c-9f3c-0d7f1f3a0000")


def session_uuid(session_id: str) -> uuid.UUID:
    """The session's STABLE-ID base UUID (the reference requires UUID
    session ids; non-UUID ids derive one deterministically)."""
    try:
        return uuid.UUID(session_id)
    except ValueError:
        return uuid.uuid5(_SESSION_NS, session_id)


def _uuid_value(b: int) -> int:
    """The 122 VALUE bits of a 128-bit UUID int — everything except
    the v4 version nibble (bits 76-79) and variant bits (62-63), which
    the reference's numericUuid.ts treats as immutable. All stable-id
    offset arithmetic happens in this value space."""
    low = b & ((1 << 62) - 1)                  # bits 0-61
    mid = (b >> 64) & ((1 << 12) - 1)          # bits 64-75
    high = b >> 80                             # bits 80-127
    return (high << 74) | (mid << 62) | low


def _value_to_uuid_int(v: int) -> int:
    """Inverse of `_uuid_value`, re-inserting version 4 and the RFC
    variant — every generated stable id is a valid v4 UUID."""
    low = v & ((1 << 62) - 1)
    mid = (v >> 62) & ((1 << 12) - 1)
    high = v >> 74
    return (high << 80) | (0x4 << 76) | (mid << 64) | (0b10 << 62) | low


def _uuid_add(base: uuid.UUID, offset: int) -> str:
    """Numeric UUID arithmetic (the reference's
    stableIdFromNumericUuid, id-compressor numericUuid.ts): stable ids
    within a session are the session UUID plus the id's ordinal
    offset, carried AROUND the immutable version/variant bits — adds
    crossing a region boundary still yield valid v4 UUIDs (raw
    128-bit addition would corrupt the reserved bits)."""
    v = (_uuid_value(base.int) + offset) & ((1 << 122) - 1)
    return str(uuid.UUID(int=_value_to_uuid_int(v)))


@dataclass
class _Cluster:
    base_final: int
    base_local: int  # first local ordinal (1-based count) covered
    capacity: int
    count: int = 0


class IdCompressor:
    def __init__(self, session_id: str,
                 cluster_capacity: int = DEFAULT_CLUSTER_CAPACITY):
        self.session_id = session_id
        self.cluster_capacity = cluster_capacity
        self._local_count = 0  # ids this session has generated
        self._next_final = 0  # next unallocated final id (global)
        # session -> clusters (in allocation order; base_local ascending)
        self._clusters: Dict[str, List[_Cluster]] = {}
        # how many of each session's locals have been finalized
        self._finalized: Dict[str, int] = {}
        # global final-space index: sorted cluster base_finals + refs
        self._final_bases: List[int] = []
        self._final_refs: List[Tuple[str, _Cluster]] = []

    # ---------------------------------------------------------- generate

    def generate_compressed_id(self) -> int:
        """A new id: an EAGER FINAL when this session's tail cluster
        already has reserved capacity for it, else a session-local id
        -1, -2, ... (idCompressor generateCompressedId)."""
        self._local_count += 1
        ordinal = self._local_count
        clusters = self._clusters.get(self.session_id)
        if clusters:
            tail = clusters[-1]
            if tail.base_local <= ordinal < tail.base_local + tail.capacity:
                return tail.base_final + (ordinal - tail.base_local)
        return -ordinal

    # ---------------------------------------------------------- finalize

    def _add_cluster(self, session: str, base_local: int,
                     capacity: int) -> _Cluster:
        cl = _Cluster(
            base_final=self._next_final, base_local=base_local,
            capacity=capacity,
        )
        self._next_final += capacity
        self._clusters.setdefault(session, []).append(cl)
        self._final_bases.append(cl.base_final)
        self._final_refs.append((session, cl))
        return cl

    def finalize_range(self, session: str, count: int) -> None:
        """Finalize the next `count` locals of `session` (called in
        total order on every replica as the carrying ops sequence).
        A zero count is a true no-op (no session registration)."""
        if count <= 0:
            return
        done = self._finalized.get(session, 0)
        clusters = self._clusters.setdefault(session, [])
        remaining = count
        while remaining > 0:
            tail = clusters[-1] if clusters else None
            if tail is not None and tail.count < tail.capacity:
                take = min(remaining, tail.capacity - tail.count)
                tail.count += take
                done += take
                remaining -= take
                continue
            if (
                tail is not None
                and tail.base_final + tail.capacity == self._next_final
            ):
                # Tail is the newest allocation in final space: expand
                # in place (idCompressor cluster expansion) — the
                # session keeps one contiguous block.
                # Reserve headroom beyond the immediate need so the
                # session's NEXT ids are eager finals.
                grow = remaining + self.cluster_capacity
                tail.capacity += grow
                self._next_final += grow
                continue
            self._add_cluster(
                session, done + 1, remaining + self.cluster_capacity
            )
        self._finalized[session] = done

    # --------------------------------------------------------- translate

    def _local_to_final(self, session: str, local: int) -> Optional[int]:
        ordinal = -local  # 1-based
        clusters = self._clusters.get(session)
        if not clusters:
            return None
        i = bisect_right(clusters, ordinal, key=lambda c: c.base_local) - 1
        if i < 0:
            return None
        cl = clusters[i]
        if ordinal < cl.base_local + cl.count:
            return cl.base_final + (ordinal - cl.base_local)
        return None

    def normalize_to_op_space(self, local_id: int) -> int:
        """Own local id -> final (if finalized) or the local itself
        (receivers resolve via the carrying op's session)."""
        if local_id >= 0:
            return local_id
        final = self._local_to_final(self.session_id, local_id)
        return final if final is not None else local_id

    def normalize_to_session_space(self, op_id: int, originator: str) -> int:
        """An id from the wire -> this session's space: finals pass
        through; a foreign local id maps via the originator's clusters
        (it must have been finalized by the time we see it... unless it
        is ours)."""
        if op_id >= 0:
            return op_id
        if originator == self.session_id:
            return op_id  # our own local: still usable locally
        final = self._local_to_final(originator, op_id)
        if final is None:
            raise KeyError(
                f"unfinalized foreign id {op_id} from session {originator}"
            )
        return final

    def decompress(self, final_id: int) -> Tuple[str, int]:
        """final id -> (session, 1-based ordinal) (stable UUID-like
        identity in the reference; the pair plays that role here)."""
        i = bisect_right(self._final_bases, final_id) - 1
        if i >= 0:
            session, cl = self._final_refs[i]
            if final_id < cl.base_final + cl.capacity:
                # Identity is fixed at cluster allocation (capacity
                # reservation), so eager finals decompress before
                # their range's own finalize catches count up.
                return session, cl.base_local + (final_id - cl.base_final)
        raise KeyError(f"unknown final id {final_id}")

    def cluster_count(self) -> int:
        return len(self._final_refs)

    # --------------------------------------------------------- stable ids

    def stable_id_of(self, id_: int, originator: Optional[str] = None) -> str:
        """The permanent UUID identity of a compressed id (the
        reference's decompress -> StableId): session base UUID +
        ordinal offset, so a session's consecutive ids are consecutive
        UUIDs (the property cluster allocation exploits)."""
        if id_ >= 0:
            session, ordinal = self.decompress(id_)
        else:
            session = originator or self.session_id
            ordinal = -id_
        return _uuid_add(session_uuid(session), ordinal - 1)

    def _session_base(self, session: str) -> int:
        cache = getattr(self, "_base_cache", None)
        if cache is None:
            cache = self._base_cache = {}
        base = cache.get(session)
        if base is None:
            base = cache[session] = _uuid_value(
                session_uuid(session).int
            )
        return base

    def _ordinal_to_final_reserved(
        self, session: str, ordinal: int
    ) -> Optional[int]:
        """Ordinal -> final over RESERVED capacity (identity is fixed
        at cluster allocation, so eager finals resolve before their
        range's finalize catches the count up — mirrors decompress)."""
        clusters = self._clusters.get(session)
        if not clusters:
            return None
        i = bisect_right(clusters, ordinal, key=lambda c: c.base_local) - 1
        if i < 0:
            return None
        cl = clusters[i]
        if ordinal < cl.base_local + cl.capacity:
            return cl.base_final + (ordinal - cl.base_local)
        return None

    def recompress(self, stable: str) -> int:
        """StableId -> compressed id in THIS session's space (the
        reference's recompress): reserved finals (including eager
        finals whose finalize hasn't caught up) resolve to finals,
        our own others to locals, KeyError for unknown ids."""
        target = _uuid_value(uuid.UUID(stable).int)
        mask = (1 << 122) - 1
        best: Optional[Tuple[str, int]] = None
        for session in self._clusters:
            # Offsets wrap modulo the 122-bit value space (as
            # _uuid_add does), so a session base near the top still
            # resolves its ids.
            off = (target - self._session_base(session)) & mask
            if 0 <= off < (1 << 64):
                if best is None or off < best[1]:
                    best = (session, off)
        own_off = (target - self._session_base(self.session_id)) & mask
        if 0 <= own_off < self._local_count and (
            best is None or own_off < best[1]
        ):
            best = (self.session_id, own_off)
        if best is None:
            raise KeyError(f"unknown stable id {stable}")
        session, off = best
        ordinal = off + 1
        final = self._ordinal_to_final_reserved(session, ordinal)
        if final is not None:
            return final
        if session == self.session_id and ordinal <= self._local_count:
            return -ordinal
        raise KeyError(f"stable id {stable} not finalized here")

    # --------------------------------------------------------- serialize

    def serialize(self) -> dict:
        return {
            "sessionId": self.session_id,
            "clusterCapacity": self.cluster_capacity,
            "localCount": self._local_count,
            "nextFinal": self._next_final,
            "finalized": dict(self._finalized),
            "clusters": {
                s: [[c.base_final, c.base_local, c.capacity, c.count] for c in cs]
                for s, cs in self._clusters.items()
            },
        }

    # The reference persists a compact binary form
    # (idCompressor.ts serialize: version + session table + packed
    # cluster rows), not a JSON object graph. Layout (all integers
    # LEB128 varints unless noted):
    #   header:  magic "IDC2", clusterCapacity, localCount, nextFinal,
    #            nSessions, nClusters, serializerSessionIdx
    #   session: idLen, utf8 id bytes, finalizedCount   (per session)
    #   cluster: sessionIdx, baseFinalDelta (from previous cluster's
    #            base), baseLocal, capacity, count  (final-space order)
    def serialize_binary(self) -> bytes:
        sessions = sorted(
            set(self._clusters) | set(self._finalized)
            | {self.session_id}
        )
        sidx = {s: i for i, s in enumerate(sessions)}
        out = [b"IDC2"]

        def put(v: int) -> None:
            while True:
                b = v & 0x7F
                v >>= 7
                out.append(bytes([b | (0x80 if v else 0)]))
                if not v:
                    return

        put(self.cluster_capacity)
        put(self._local_count)
        put(self._next_final)
        put(len(sessions))
        put(len(self._final_refs))
        put(sidx[self.session_id])
        for sess in sessions:
            raw = sess.encode()
            put(len(raw))
            out.append(raw)
            put(self._finalized.get(sess, 0))
        prev_base = 0
        for sess, cl in self._final_refs:
            put(sidx[sess])
            put(cl.base_final - prev_base)
            prev_base = cl.base_final
            put(cl.base_local)
            put(cl.capacity)
            put(cl.count)
        return b"".join(out)

    @classmethod
    def deserialize_binary(
        cls, blob: bytes, session_id: Optional[str] = None
    ) -> "IdCompressor":
        if blob[:4] != b"IDC2":
            raise ValueError("bad id-compressor blob")
        try:
            return cls._parse_binary(blob, session_id)
        except (IndexError, UnicodeDecodeError) as exc:
            raise ValueError(
                f"truncated/corrupt id-compressor blob: {exc}"
            ) from None

    @classmethod
    def _parse_binary(
        cls, blob: bytes, session_id: Optional[str]
    ) -> "IdCompressor":
        pos = [4]

        def get() -> int:
            v, shift = 0, 0
            while True:
                b = blob[pos[0]]
                pos[0] += 1
                v |= (b & 0x7F) << shift
                if not b & 0x80:
                    return v
                shift += 7

        cap = get()
        local_count = get()
        next_final = get()
        n_sessions = get()
        n_clusters = get()
        ser_idx = get()
        sessions: List[str] = []
        finalized: Dict[str, int] = {}
        for _ in range(n_sessions):
            ln = get()
            sess = blob[pos[0]: pos[0] + ln].decode()
            pos[0] += ln
            sessions.append(sess)
            finalized[sess] = get()
        serial_session = sessions[ser_idx] if sessions else ""
        out = cls(session_id or serial_session, cap)
        out._next_final = next_final
        out._finalized = finalized
        out._local_count = (
            local_count
            if session_id in (None, serial_session) else 0
        )
        prev_base = 0
        for _ in range(n_clusters):
            si = get()
            prev_base += get()
            cl = _Cluster(prev_base, get(), get(), get())
            out._clusters.setdefault(sessions[si], []).append(cl)
            out._final_bases.append(cl.base_final)
            out._final_refs.append((sessions[si], cl))
        return out

    @classmethod
    def deserialize(cls, data: dict, session_id: Optional[str] = None) -> "IdCompressor":
        out = cls(session_id or data["sessionId"], data["clusterCapacity"])
        out._local_count = data["localCount"] if session_id in (None, data["sessionId"]) else 0
        out._next_final = data["nextFinal"]
        out._finalized = dict(data["finalized"])
        out._clusters = {
            s: [_Cluster(a, b, c, d) for a, b, c, d in cs]
            for s, cs in data["clusters"].items()
        }
        refs = [
            (cl.base_final, s, cl)
            for s, cs in out._clusters.items() for cl in cs
        ]
        refs.sort(key=lambda x: x[0])
        out._final_bases = [r[0] for r in refs]
        out._final_refs = [(r[1], r[2]) for r in refs]
        return out
