"""Branch object API: isolated lines of development over a SharedTree.

Reference `SharedTreeBranch`
(packages/dds/tree/src/shared-tree-core/branch.ts:50-210): `fork()`
captures an isolated view; edits apply to the branch only;
`rebase_onto` replays the branch's commits on top of everything the
main line has since sequenced; `merge_into` lands the (rebased)
branch commits on the main tree as ordinary edits. Branch state is
purely local — nothing rides the wire until merge.
"""

from __future__ import annotations

import copy
from typing import Any, List

from .changeset import (
    Change,
    compose,
    insert_op,
    invert,
    move_op,
    rebase_change,
    remove_op,
    set_value_op,
)
from .forest import Forest


class SharedTreeBranch:
    def __init__(self, tree):
        self.tree = tree
        self.forest: Forest = tree.forest.clone()
        self.base_seq: int = tree.edits.trunk_seq
        # Local-to-the-tree commits present at fork time are part of
        # the captured view: when they later sequence into the trunk
        # they must NOT rebase under us a second time. Strong refs are
        # held so commit-object identity (which ack_local preserves)
        # stays unambiguous — a bare id() set could alias a recycled
        # address after the commit is evicted and freed.
        self._fork_local = list(tree.edits.local)
        self.commits: List[Change] = []
        self.merged = False
        # Transaction stack (branch.ts:95 startTransaction backed by
        # transactionStack.ts:12): each open transaction marks the
        # commit-list length at its start. Commit squashes the marked
        # suffix into ONE composed commit; abort unwinds it through
        # the repair data the forest captured at apply time (removed
        # content / prior values / move inverses).
        self._tx_marks: List[int] = []

    # ------------------------------------------------------------ editing

    def view(self) -> dict:
        return self.forest.to_json()

    def edit(self, change: Change) -> None:
        assert not self.merged, "branch already merged"
        self.forest.apply(change)
        self.commits.append(copy.deepcopy(change))

    def insert_node(self, path, field, index, content) -> None:
        self.edit([insert_op(path, field, index, content)])

    def remove_node(self, path, field, index, count=1) -> None:
        self.edit([remove_op(path, field, index, count)])

    def set_value(self, path, value) -> None:
        self.edit([set_value_op(path, value)])

    def move_node(self, path, field, index, count, dst_path, dst_field,
                  dst_index) -> None:
        self.edit([
            move_op(path, field, index, count, dst_path, dst_field,
                    dst_index)
        ])

    # ------------------------------------------------------- transactions

    @property
    def in_transaction(self) -> bool:
        return bool(self._tx_marks)

    def start_transaction(self) -> None:
        """Open a (nestable) transaction (branch.ts:95
        startTransaction): subsequent edits group until commit/abort."""
        assert not self.merged, "branch already merged"
        self._tx_marks.append(len(self.commits))

    def commit_transaction(self) -> Change:
        """Squash the transaction's commits into ONE composed commit
        (branch.ts commitTransaction: the transaction lands as a
        single atomic change). Returns the squashed change."""
        assert self._tx_marks, "no open transaction"
        mark = self._tx_marks.pop()
        squashed = compose(self.commits[mark:])
        self.commits[mark:] = [squashed] if squashed else []
        return squashed

    def abort_transaction(self) -> None:
        """Unwind the transaction via repair data (branch.ts
        abortTransaction): every commit since the mark inverts —
        removed content re-inserts, prior values restore, moves
        reverse — newest first."""
        assert self._tx_marks, "no open transaction"
        mark = self._tx_marks.pop()
        for change in reversed(self.commits[mark:]):
            self.forest.apply(invert(change))
        del self.commits[mark:]

    # ------------------------------------------------------------- rebase

    def _changes_since_fork(self) -> Change:
        """Everything the tree applied since the fork that the branch
        has not rebased over: trunk commits sequenced after base_seq
        PLUS the tree's unacked local commits — the fork's forest view
        rebuilds from tree.forest, which contains both."""
        fork_ids = {id(c) for c in self._fork_local}
        trunk = [
            op
            for c in self.tree.edits.trunk
            if c.seq > self.base_seq and id(c) not in fork_ids
            for op in c.change
        ]
        local = [
            op
            for c in self.tree.edits.local
            if id(c) not in fork_ids
            for op in c.change
        ]
        return trunk + local

    def rebase_onto(self) -> None:
        """Rebase this branch onto the tree's CURRENT state
        (branch.ts rebaseOnto): every branch commit rewrites over the
        trunk commits sequenced since the fork (earlier branch commits
        carrying through, later ones rebasing over the carried base),
        then the branch view rebuilds from the tree's current forest."""
        assert not self._tx_marks, "commit/abort open transactions first"
        evicted = getattr(self.tree.edits, "evicted_seq", 0)
        if self.base_seq < evicted:
            raise RuntimeError(
                f"branch too old to rebase: trunk evicted to seq "
                f"{evicted}, branch forked at {self.base_seq}"
            )
        carried = self._changes_since_fork()
        rebased: List[Change] = []
        for commit in self.commits:
            rebased.append(rebase_change(commit, carried, over_first=True))
            carried = rebase_change(carried, commit, over_first=False)
        self.commits = rebased
        self.forest = self.tree.forest.clone()
        for c in self.commits:
            self.forest.apply(c)
        self.base_seq = self.tree.edits.trunk_seq
        self._fork_local = list(self.tree.edits.local)

    # -------------------------------------------------------------- merge

    def merge_into(self, id_count: int = 0) -> None:
        """Land the branch on the main tree (branch.ts merge): rebase
        up to date, then submit each commit as a normal tree edit (the
        tree's optimistic-local + op-stream path takes over).
        `id_count`: ids allocated on behalf of this branch's commits
        (a squashed transaction's accumulated allocation), carried by
        the first non-empty landed commit."""
        self.rebase_onto()
        self.land(id_count)

    def land(self, id_count: int = 0) -> None:
        """Submit the (already-rebased) commits and close the branch.
        Split from merge_into so callers can scope retryable failures
        to the rebase alone — once landing starts, commits are on the
        wire and the branch must not be replayed."""
        for c in self.commits:
            if c:
                self.tree.edit(copy.deepcopy(c), id_count)
                id_count = 0
        self.commits = []
        self.merged = True
