"""Branch object API: isolated lines of development over a SharedTree.

Reference `SharedTreeBranch`
(packages/dds/tree/src/shared-tree-core/branch.ts:50-210): `fork()`
captures an isolated view; edits apply to the branch only;
`rebase_onto` replays the branch's commits on top of everything the
main line has since sequenced; `merge_into` lands the (rebased)
branch commits on the main tree as ordinary edits. Branch state is
purely local — nothing rides the wire until merge.
"""

from __future__ import annotations

import copy
from typing import Any, List

from .changeset import (
    Change,
    insert_op,
    rebase_change,
    remove_op,
    set_value_op,
)
from .forest import Forest


class SharedTreeBranch:
    def __init__(self, tree):
        self.tree = tree
        self.forest: Forest = tree.forest.clone()
        self.base_seq: int = tree.edits.trunk_seq
        # Local-to-the-tree commits present at fork time are part of
        # the captured view: when they later sequence into the trunk
        # they must NOT rebase under us a second time. Strong refs are
        # held so commit-object identity (which ack_local preserves)
        # stays unambiguous — a bare id() set could alias a recycled
        # address after the commit is evicted and freed.
        self._fork_local = list(tree.edits.local)
        self.commits: List[Change] = []
        self.merged = False

    # ------------------------------------------------------------ editing

    def view(self) -> dict:
        return self.forest.to_json()

    def edit(self, change: Change) -> None:
        assert not self.merged, "branch already merged"
        self.forest.apply(change)
        self.commits.append(copy.deepcopy(change))

    def insert_node(self, path, field, index, content) -> None:
        self.edit([insert_op(path, field, index, content)])

    def remove_node(self, path, field, index, count=1) -> None:
        self.edit([remove_op(path, field, index, count)])

    def set_value(self, path, value) -> None:
        self.edit([set_value_op(path, value)])

    # ------------------------------------------------------------- rebase

    def _changes_since_fork(self) -> Change:
        """Everything the tree applied since the fork that the branch
        has not rebased over: trunk commits sequenced after base_seq
        PLUS the tree's unacked local commits — the fork's forest view
        rebuilds from tree.forest, which contains both."""
        fork_ids = {id(c) for c in self._fork_local}
        trunk = [
            op
            for c in self.tree.edits.trunk
            if c.seq > self.base_seq and id(c) not in fork_ids
            for op in c.change
        ]
        local = [
            op
            for c in self.tree.edits.local
            if id(c) not in fork_ids
            for op in c.change
        ]
        return trunk + local

    def rebase_onto(self) -> None:
        """Rebase this branch onto the tree's CURRENT state
        (branch.ts rebaseOnto): every branch commit rewrites over the
        trunk commits sequenced since the fork (earlier branch commits
        carrying through, later ones rebasing over the carried base),
        then the branch view rebuilds from the tree's current forest."""
        evicted = getattr(self.tree.edits, "evicted_seq", 0)
        if self.base_seq < evicted:
            raise RuntimeError(
                f"branch too old to rebase: trunk evicted to seq "
                f"{evicted}, branch forked at {self.base_seq}"
            )
        carried = self._changes_since_fork()
        rebased: List[Change] = []
        for commit in self.commits:
            rebased.append(rebase_change(commit, carried, over_first=True))
            carried = rebase_change(carried, commit, over_first=False)
        self.commits = rebased
        self.forest = self.tree.forest.clone()
        for c in self.commits:
            self.forest.apply(c)
        self.base_seq = self.tree.edits.trunk_seq
        self._fork_local = list(self.tree.edits.local)

    # -------------------------------------------------------------- merge

    def merge_into(self) -> None:
        """Land the branch on the main tree (branch.ts merge): rebase
        up to date, then submit each commit as a normal tree edit (the
        tree's optimistic-local + op-stream path takes over)."""
        self.rebase_onto()
        for c in self.commits:
            if c:
                self.tree.edit(copy.deepcopy(c))
        self.commits = []
        self.merged = True
