"""Batched changeset rebase: the config-4 TPU kernel.

The reference rebases commits one at a time through the change-family
code (core/edit-manager/editManager.ts:47 trunk rebase;
feature-libraries/sequence-field/rebase.ts index arithmetic +
moveEffectTable.ts move arbitration). For the bulk case — rebase a
large pending branch over a trunk window, the BASELINE.json config-4
shape — the index arithmetic is data-parallel across the pending ops:
each trunk op adjusts EVERY pending op's (index, count, dst) with the
same closed-form rules. This module runs that as a `lax.scan` over the
trunk window with all pending ops as vector state (one XLA dispatch
for the whole rebase).

Semantics mirror changeset._adjust_index / _gap_over_base / rebase_op
for single-field insert/remove/MOVE streams exactly (differential
test: tests/test_tree_depth.py), including: insert-over-insert shifts
with the sequenced-earlier tie, insert sliding to a removed range's
start, removes clipping against base removes, gap TRAVEL with a base
move's block, attach-adjacency ties (a gap hugging a moved block keeps
its side), move-absorb of content attached strictly inside, and full
mutes dropping the op (count -> 0).

Ops are (kind, index, count, dst): dst is a move's attach gap in the
op's own pre-frame, ignored for insert/remove. Rare structures beyond
the vector budget FLAG for the scalar changeset path: a second split
of the same remove, a remove PARTIALLY overlapping a base move's block
(pre+follow+post = 3 pieces), and two moves with competing node claims
or mutual containment (the move-effect arbitration cases).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

K_INSERT = 0
K_REMOVE = 1
K_MOVE = 2


def _attach_gap(bi, bn, bj):
    """A base move's attach gap in its own POST-DETACH frame
    (changeset._attach_gap, single field)."""
    return jnp.where(
        bj >= bi + bn, bj - bn, jnp.where(bj > bi, bi, bj)
    )


def _gap_over(g, bk, bi, bn, bg):
    """Adjust an insertion GAP over one base op (base sequenced
    earlier: ties shift). Mirrors changeset._gap_over_base with
    base_first=True; `bg` is the base move's post-detach attach gap
    (ignored unless bk == K_MOVE)."""
    g_ins = jnp.where(bi <= g, g + bn, g)
    g_rem = jnp.where(g < bi, g, jnp.maximum(bi, g - bn))
    # base move: strictly-inside gaps TRAVEL with the block; boundary
    # gaps keep their adjacency side on attach ties.
    inside = (bi < g) & (g < bi + bn)
    travel = bg + (g - bi)
    before = g == bi
    g1 = g_rem  # detach slide: same rule as a base remove
    shift_attach = (bg < g1) | ((bg == g1) & ~before)
    g_mv = jnp.where(inside, travel, jnp.where(shift_attach, g1 + bn, g1))
    return jnp.where(
        bk == K_INSERT, g_ins, jnp.where(bk == K_REMOVE, g_rem, g_mv)
    )


def _remove_over_rm(idx, cnt, bi, bn):
    """Clip a range against a base REMOVE [bi, bi+bn) — the overlap is
    already gone (changeset._range_over_base remove branch)."""
    lo = jnp.maximum(idx, bi)
    hi = jnp.minimum(idx + cnt, bi + bn)
    overlap = jnp.maximum(0, hi - lo)
    new_cnt = cnt - overlap
    new_idx = jnp.where(idx < bi, idx, jnp.maximum(bi, idx - bn))
    return new_idx, new_cnt


def _rebase_step(state, base):
    """Adjust all pending ops over ONE base op. state: (kind[N],
    index[N], count[N], dst[N], spare_idx[N], spare_cnt[N],
    spare_act[N], flag[N]); base: (kind, index, count, dst_gap).
    Muted ops end with count 0.

    Split budget: a base attach (insert, or a move's re-attach)
    strictly INSIDE a pending remove's range splits that remove; the
    head keeps the primary slot and the tail occupies the op's
    PREALLOCATED SPARE slot — one native split per op. Anything
    beyond the budget (second split, 3-piece move overlap, competing
    move claims, mutual containment) FLAGS for the scalar path."""
    kind, idx, cnt, dst, s_idx, s_cnt, s_act, flag = state
    bk, bi, bn, bj = base
    bg = _attach_gap(bi, bn, bj)
    # An identity base move applies as a no-op and adjusts nothing
    # (changeset._is_noop_move base rule).
    base_noop = (bk == K_MOVE) & (bi <= bj) & (bj <= bi + bn)

    is_ins = kind == K_INSERT
    is_rem = kind == K_REMOVE
    is_mv = kind == K_MOVE
    live = cnt > 0

    # A pending identity move rebases to nothing (the op-side
    # canonicalization): mute on its first live rebase step.
    op_noop = is_mv & (idx <= dst) & (dst <= idx + cnt)

    # ---------------- pending INSERT: a pure gap.
    ins_idx = _gap_over(idx, bk, bi, bn, bg)

    # ---------------- pending REMOVE range [idx, idx+cnt).
    # base insert: shift, or split around content landing strictly
    # inside (the attach of a base MOVE with no node overlap behaves
    # identically — both are an insert of bn at a gap).
    # base remove: clip.
    # base move: relocate on full containment; flag partial overlap.
    rm_ins_idx = jnp.where(bi <= idx, idx + bn, idx)
    # One clip of the primary range against a base remove serves both
    # the pending-remove and pending-move selections below.
    clip_idx, clip_cnt = _remove_over_rm(idx, cnt, bi, bn)

    ov_lo = jnp.maximum(idx, bi)
    ov_hi = jnp.minimum(idx + cnt, bi + bn)
    mv_overlap = jnp.maximum(0, ov_hi - ov_lo) > 0
    full_inside = (idx >= bi) & (idx + cnt <= bi + bn)
    # no-overlap: detach slide, then the attach handled below as an
    # insert at bg.
    rm_mv_idx0 = jnp.where(idx >= bi + bn, idx - bn, idx)
    rm_mv_idx = jnp.where(full_inside, bg + (idx - bi), rm_mv_idx0)

    new_idx = jnp.where(
        bk == K_INSERT, rm_ins_idx,
        jnp.where(bk == K_REMOVE, clip_idx, rm_mv_idx),
    )
    new_cnt = jnp.where(bk == K_REMOVE, clip_cnt, cnt)

    # ---------------- pending MOVE: src range + dst gap.
    # base insert strictly inside the block ABSORBS (travels with it);
    # at/before shifts. base remove clips. base move with node overlap
    # or mutual containment flags; otherwise detach slide + attach
    # absorb/shift.
    mv_ins_absorb = (bi > idx) & (bi < idx + cnt)
    mv_ins_idx = jnp.where(bi <= idx, idx + bn, idx)
    mv_ins_cnt = jnp.where(mv_ins_absorb, cnt + bn, cnt)
    mv_mv_idx0 = jnp.where(idx >= bi + bn, idx - bn, idx)
    mv_mv_absorb = (bg > mv_mv_idx0) & (bg < mv_mv_idx0 + cnt)
    mv_mv_idx = jnp.where(bg <= mv_mv_idx0, mv_mv_idx0 + bn, mv_mv_idx0)
    mv_mv_cnt = jnp.where(mv_mv_absorb, cnt + bn, cnt)

    mv_idx = jnp.where(
        bk == K_INSERT, mv_ins_idx,
        jnp.where(bk == K_REMOVE, clip_idx, mv_mv_idx),
    )
    mv_cnt = jnp.where(
        bk == K_INSERT, mv_ins_cnt,
        jnp.where(bk == K_REMOVE, clip_cnt, mv_mv_cnt),
    )
    new_dst = _gap_over(dst, bk, bi, bn, bg)

    # ---------------- flags (beyond the vector budget).
    # remove PARTIALLY overlapping a base move's block: pre + follow +
    # post pieces (the scalar path's parts machinery).
    flag_rm_partial = (
        (bk == K_MOVE) & is_rem & live & mv_overlap & ~full_inside
    )
    # two moves with competing node claims, or mutual containment
    # (the per-move-id move-effect arbitration).
    mv_src_overlap = (
        (bk == K_MOVE) & is_mv & live
        & (jnp.maximum(idx, bi) < jnp.minimum(idx + cnt, bi + bn))
    )
    mutual = (
        (bk == K_MOVE) & is_mv & live
        & (bi < dst) & (dst < bi + bn)
        & (idx < bj) & (bj < idx + cnt)
    )

    # ---------------- splits of a pending remove around an attach.
    # The attach position: a base insert's bi, or a base move's bg in
    # the post-detach frame (only when no node overlap).
    att = jnp.where(bk == K_INSERT, bi, bg)
    att_base = jnp.where(bk == K_INSERT, idx, rm_mv_idx0)
    splittable = is_rem & live & (
        (bk == K_INSERT)
        | ((bk == K_MOVE) & ~mv_overlap & ~base_noop)
    )
    split_p = splittable & (att > att_base) & (att < att_base + cnt)
    # spare pieces are always removes; same rules, same split risk.
    sp_att_base = jnp.where(
        bk == K_INSERT, s_idx,
        jnp.where(s_idx >= bi + bn, s_idx - bn, s_idx),
    )
    split_s = (
        s_act & (s_cnt > 0)
        & ((bk == K_INSERT) | ((bk == K_MOVE) & ~base_noop))
        & (att > sp_att_base) & (att < sp_att_base + s_cnt)
    )
    # spare overlapping a base move's node claim at all -> flag (no
    # second-piece machinery for relocation).
    sp_mv_overlap = (
        s_act & (s_cnt > 0) & (bk == K_MOVE) & ~base_noop
        & (jnp.maximum(s_idx, bi) < jnp.minimum(s_idx + s_cnt, bi + bn))
    )
    use_spare = split_p & ~s_act
    new_flag = flag | (split_p & s_act) | split_s | sp_mv_overlap \
        | flag_rm_partial | mv_src_overlap | mutual

    # remove with no node overlap vs base MOVE: attach shift when at
    # or before the slid range (split handled above; base-insert
    # shifts are already in rm_ins_idx).
    rm_att_shift = (
        is_rem & live & (bk == K_MOVE) & ~mv_overlap & ~base_noop
        & (att <= att_base)
    )
    new_idx = jnp.where(rm_att_shift, new_idx + bn, new_idx)

    # ---------------- spare piece adjustment (a remove).
    sp_clip_idx, sp_clip_cnt = _remove_over_rm(s_idx, s_cnt, bi, bn)
    sp_idx1 = jnp.where(
        bk == K_INSERT, jnp.where(bi <= s_idx, s_idx + bn, s_idx),
        jnp.where(
            bk == K_REMOVE, sp_clip_idx,
            jnp.where(att <= sp_att_base, sp_att_base + bn, sp_att_base),
        ),
    )
    sp_cnt1 = jnp.where(bk == K_REMOVE, sp_clip_cnt, s_cnt)

    # ---------------- select per pending kind.
    out_idx = jnp.where(is_ins, ins_idx, jnp.where(is_mv, mv_idx, new_idx))
    out_cnt = jnp.where(is_ins, cnt, jnp.where(is_mv, mv_cnt, new_cnt))
    out_dst = jnp.where(is_mv, new_dst, dst)

    # Tail of a fresh split, in post-base coordinates.
    tail_idx = att + bn
    tail_cnt = (att_base + cnt) - att
    out_cnt = jnp.where(use_spare, att - att_base, out_cnt)
    out_idx = jnp.where(use_spare, att_base, out_idx)
    sp_idx1 = jnp.where(use_spare, tail_idx, sp_idx1)
    sp_cnt1 = jnp.where(use_spare, tail_cnt, sp_cnt1)
    s_act = s_act | use_spare

    # A pending identity move rebases to nothing (mutes); an identity
    # BASE op leaves everything untouched — and the scalar path checks
    # the base first, so a noop base protects even a noop pending op.
    out_cnt = jnp.where(op_noop, 0, out_cnt)
    keep = base_noop
    out_idx = jnp.where(keep, idx, out_idx)
    out_cnt = jnp.where(keep, cnt, out_cnt)
    out_dst = jnp.where(keep, dst, out_dst)
    sp_idx1 = jnp.where(keep, s_idx, sp_idx1)
    sp_cnt1 = jnp.where(keep, s_cnt, sp_cnt1)
    s_act = jnp.where(keep, state[6], s_act)
    new_flag = jnp.where(keep, flag, new_flag)

    return (kind, out_idx, out_cnt, out_dst, sp_idx1, sp_cnt1, s_act,
            new_flag), None


@jax.jit
def rebase_batch(kinds: jnp.ndarray, idxs: jnp.ndarray, cnts: jnp.ndarray,
                 dsts: jnp.ndarray,
                 base_kinds: jnp.ndarray, base_idxs: jnp.ndarray,
                 base_cnts: jnp.ndarray, base_dsts: jnp.ndarray):
    """Rebase N pending ops over M base ops (applied in order) in one
    XLA computation: lax.scan over the base window, every pending op
    adjusted in parallel per step. Returns ``(kind, idx, cnt, dst,
    spare_idx, spare_cnt, spare_active, flagged)`` — a split remove
    occupies its primary slot (head) plus its spare slot (tail);
    `flagged` marks ops needing the scalar changeset path (double
    splits, 3-piece move overlaps, competing/mutual move claims)."""
    zeros = jnp.zeros(kinds.shape, jnp.int32)
    (k, i, c, d, si, sc, sa, f), _ = jax.lax.scan(
        _rebase_step,
        (kinds, idxs, cnts, dsts, zeros, zeros,
         jnp.zeros(kinds.shape, bool), jnp.zeros(kinds.shape, bool)),
        (base_kinds, base_idxs, base_cnts, base_dsts),
    )
    return k, i, c, d, si, sc, sa, f


def rebase_ops_columnar(ops: np.ndarray, base: np.ndarray):
    """numpy convenience: ops is [N, 3-or-4] and base is [M, 3-or-4] —
    rows of (kind, index, count[, dst]); dst is a move's attach gap,
    padded 0 when the 3-column form is passed. Returns (rebased [N,4], spares [N,3] with count 0 for
    unsplit ops, flagged [N]) — flagged ops reroute through the scalar
    changeset path (count 0 = muted). Spare pieces are SEQUENTIALIZED
    like the scalar path's multi bundles: a split remove's tail index
    assumes its head applied first."""
    def _pad(a):
        a = np.asarray(a, np.int32)
        if a.shape[1] == 3:
            a = np.concatenate(
                [a, np.zeros((a.shape[0], 1), np.int32)], axis=1
            )
        return a

    ops = _pad(ops)
    base = _pad(base)
    k, i, c, d, si, sc, sa, f = rebase_batch(
        jnp.asarray(ops[:, 0]), jnp.asarray(ops[:, 1]),
        jnp.asarray(ops[:, 2]), jnp.asarray(ops[:, 3]),
        jnp.asarray(base[:, 0]), jnp.asarray(base[:, 1]),
        jnp.asarray(base[:, 2]), jnp.asarray(base[:, 3]),
    )
    out = np.stack(
        [np.asarray(k), np.asarray(i), np.asarray(c), np.asarray(d)],
        axis=1,
    )
    act = np.asarray(sa)
    # Sequentialize: the tail applies AFTER the head, so it shifts
    # down by the head's count — but only while it still sits at or
    # past the head (a later base move can relocate the head above
    # the tail, e.g. a full-containment follow).
    si_np = np.asarray(si)
    sp_idx = np.where(
        act, np.where(si_np >= out[:, 1], si_np - out[:, 2], si_np), 0
    )
    spares = np.stack(
        [np.full(out.shape[0], K_REMOVE, np.int32), sp_idx,
         np.where(act, np.asarray(sc), 0)],
        axis=1,
    )
    return out, spares, np.asarray(f)
