"""Batched changeset rebase: the config-4 TPU kernel.

The reference rebases commits one at a time through the change-family
code (core/edit-manager/editManager.ts:47 trunk rebase;
feature-libraries/sequence-field/rebase.ts index arithmetic). For the
bulk case — rebase a large pending branch over a trunk window, the
BASELINE.json config-4 shape — the index arithmetic is data-parallel
across the pending ops: each trunk op adjusts EVERY pending op's
(index, count) with the same closed-form rules. This module runs that
as a `lax.scan` over the trunk window with all pending ops as vector
state (one XLA dispatch for the whole rebase).

Semantics mirror changeset._adjust_index / rebase_op for single-field
insert/remove streams exactly (differential test:
tests/test_tree_depth.py), including: insert-over-insert
shifts with the sequenced-earlier tie, insert sliding to a removed
range's start, removes clipping against base removes (overlap is
muted), and full mutes dropping the op (count -> 0).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

K_INSERT = 0
K_REMOVE = 1


def _piece_over_base(kind, idx, cnt, bk, bi, bn):
    """Adjust ONE (kind, idx, cnt) piece over one base op — the
    _adjust_index rules, vectorized and split-free."""
    is_ins = kind == K_INSERT

    # ---- base insert: positions at/after shift right.
    idx_after_ins = idx + jnp.where(idx >= bi, bn, 0)

    # ---- base remove [bi, bi+bn): inserts inside slide to bi;
    # removes clip: the overlap with the base range is already gone.
    lo = jnp.maximum(idx, bi)
    hi = jnp.minimum(idx + cnt, bi + bn)
    overlap = jnp.maximum(0, hi - lo)
    new_cnt_rem = cnt - overlap
    start_rem = jnp.where(
        idx < bi, idx, jnp.where(idx < bi + bn, bi, idx - bn)
    )
    start_rem = jnp.where(
        (kind == K_REMOVE) & (idx >= bi) & (idx < bi + bn),
        bi,
        start_rem,
    )
    idx_after_rem = jnp.where(
        is_ins,
        jnp.where(idx < bi, idx, jnp.maximum(bi, idx - bn)),
        start_rem,
    )
    cnt_after_rem = jnp.where(is_ins, cnt, new_cnt_rem)

    new_idx = jnp.where(bk == K_INSERT, idx_after_ins, idx_after_rem)
    new_cnt = jnp.where(bk == K_INSERT, cnt, cnt_after_rem)
    return new_idx, new_cnt


def _rebase_step(state, base):
    """Adjust all pending ops over ONE base op. state: (kind[N],
    index[N], count[N], spare_idx[N], spare_cnt[N], spare_act[N],
    flag[N]); base: (kind, index, count). Muted ops end with count 0.

    A base insert strictly INSIDE a pending remove's range splits that
    remove (changeset.rebase_op "multi"): the head keeps the primary
    slot and the tail occupies the op's PREALLOCATED SPARE slot — one
    split per pending op is handled natively (the overwhelmingly
    common case; config-4's 'flagged_for_scalar_path' was exactly
    these). A SECOND split on the same op (base insert inside either
    live piece again) exceeds the two-slot budget and FLAGS the op for
    the scalar path."""
    kind, idx, cnt, s_idx, s_cnt, s_act, flag = state
    bk, bi, bn = base

    split_p = (
        (bk == K_INSERT) & (kind == K_REMOVE) & (cnt > 0)
        & (bi > idx) & (bi < idx + cnt)
    )
    split_s = (
        (bk == K_INSERT) & s_act & (s_cnt > 0)
        & (bi > s_idx) & (bi < s_idx + s_cnt)
    )
    # One native split per op: a primary split uses the spare; any
    # split beyond that (primary again, or the spare itself) flags.
    use_spare = split_p & ~s_act
    flag = flag | (split_p & s_act) | split_s

    # Tail of a fresh split, in post-base coordinates.
    tail_idx = bi + bn
    tail_cnt = (idx + cnt) - bi

    new_idx, new_cnt = _piece_over_base(kind, idx, cnt, bk, bi, bn)
    sp_idx, sp_cnt = _piece_over_base(kind, s_idx, s_cnt, bk, bi, bn)

    # Apply the split AFTER the generic adjust: the head clips to the
    # base insert's position, the tail starts past the inserted run.
    new_cnt = jnp.where(use_spare, bi - idx, new_cnt)
    new_idx = jnp.where(use_spare, idx, new_idx)
    sp_idx = jnp.where(use_spare, tail_idx, sp_idx)
    sp_cnt = jnp.where(use_spare, tail_cnt, sp_cnt)
    s_act = s_act | use_spare

    return (kind, new_idx, new_cnt, sp_idx, sp_cnt, s_act, flag), None


@jax.jit
def rebase_batch(kinds: jnp.ndarray, idxs: jnp.ndarray, cnts: jnp.ndarray,
                 base_kinds: jnp.ndarray, base_idxs: jnp.ndarray,
                 base_cnts: jnp.ndarray):
    """Rebase N pending ops over M base ops (applied in order) in one
    XLA computation: lax.scan over the base window, every pending op
    adjusted in parallel per step. Returns
    ``(kind, idx, cnt, spare_idx, spare_cnt, spare_active, flagged)``
    — a split remove occupies its primary slot (head) plus its spare
    slot (tail); `flagged` marks the rare double-split ops that must
    reroute through the scalar changeset path."""
    zeros = jnp.zeros(kinds.shape, jnp.int32)
    (k, i, c, si, sc, sa, f), _ = jax.lax.scan(
        _rebase_step,
        (kinds, idxs, cnts, zeros, zeros,
         jnp.zeros(kinds.shape, bool), jnp.zeros(kinds.shape, bool)),
        (base_kinds, base_idxs, base_cnts),
    )
    return k, i, c, si, sc, sa, f


def rebase_ops_columnar(ops: np.ndarray, base: np.ndarray):
    """numpy convenience: ops/base are [N,3]/[M,3] arrays of
    (kind, index, count). Returns (rebased [N,3], spares [N,3] with
    count 0 for unsplit ops, flagged [N]) — flagged ops double-split
    and must reroute through the scalar changeset path (count 0 =
    muted). Spare pieces are SEQUENTIALIZED like the scalar path's
    multi bundles: a split remove's tail index assumes its head
    applied first."""
    k, i, c, si, sc, sa, f = rebase_batch(
        jnp.asarray(ops[:, 0]), jnp.asarray(ops[:, 1]), jnp.asarray(ops[:, 2]),
        jnp.asarray(base[:, 0]), jnp.asarray(base[:, 1]), jnp.asarray(base[:, 2]),
    )
    out = np.stack([np.asarray(k), np.asarray(i), np.asarray(c)], axis=1)
    act = np.asarray(sa)
    sp_idx = np.where(act, np.asarray(si) - out[:, 2], 0)
    spares = np.stack(
        [out[:, 0], sp_idx, np.where(act, np.asarray(sc), 0)], axis=1
    )
    return out, spares, np.asarray(f)

