"""Batched changeset rebase: the config-4 TPU kernel.

The reference rebases commits one at a time through the change-family
code (core/edit-manager/editManager.ts:47 trunk rebase;
feature-libraries/sequence-field/rebase.ts index arithmetic). For the
bulk case — rebase a large pending branch over a trunk window, the
BASELINE.json config-4 shape — the index arithmetic is data-parallel
across the pending ops: each trunk op adjusts EVERY pending op's
(index, count) with the same closed-form rules. This module runs that
as a `lax.scan` over the trunk window with all pending ops as vector
state (one XLA dispatch for the whole rebase).

Semantics mirror changeset._adjust_index / rebase_op for single-field
insert/remove streams exactly (differential test:
tests/test_tree_depth.py), including: insert-over-insert
shifts with the sequenced-earlier tie, insert sliding to a removed
range's start, removes clipping against base removes (overlap is
muted), and full mutes dropping the op (count -> 0).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

K_INSERT = 0
K_REMOVE = 1


def _rebase_step(state, base):
    """Adjust all pending ops over ONE base op (the _adjust_index
    rules, vectorized). state: (kind[N], index[N], count[N],
    needs_split[N]); base: (kind, index, count). Muted ops end with
    count 0. A base insert strictly INSIDE a pending remove's range
    splits that remove in two (changeset.rebase_op "multi") — an
    output-expanding case no fixed columnar row can hold, so the op is
    FLAGGED and the caller reroutes it through the scalar path (the
    kernel result for a flagged op is unspecified)."""
    kind, idx, cnt, flag = state
    bk, bi, bn = base
    is_ins = kind == K_INSERT
    flag = flag | (
        (bk == K_INSERT) & (kind == K_REMOVE) & (bi > idx) & (bi < idx + cnt)
    )

    # ---- base insert: positions at/after shift right.
    # insertion gaps: strict >, ties go to base (sequenced earlier);
    # node references: >= (content before the node shifts it).
    shift_ins = jnp.where(
        is_ins,
        jnp.where(idx >= bi, bn, 0),  # gap: bi < idx or tie -> shift
        jnp.where(idx >= bi, bn, 0),  # node ref: bi <= idx -> shift
    )
    idx_after_ins = idx + shift_ins

    # ---- base remove [bi, bi+bn): inserts inside slide to bi;
    # removes clip: the overlap with the base range is already gone.
    lo = jnp.maximum(idx, bi)
    hi = jnp.minimum(idx + cnt, bi + bn)
    overlap = jnp.maximum(0, hi - lo)
    new_cnt_rem = cnt - overlap
    # Surviving range start: nodes before bi keep their index; nodes
    # at/inside the range slide to bi; nodes after subtract bn.
    start_rem = jnp.where(
        idx < bi, idx, jnp.where(idx < bi + bn, bi, idx - bn)
    )
    # If the head of the removed range was clipped, the survivors
    # begin at the base-range start.
    start_rem = jnp.where(
        (kind == K_REMOVE) & (idx >= bi) & (idx < bi + bn),
        bi,
        start_rem,
    )
    idx_after_rem = jnp.where(
        is_ins,
        jnp.where(idx < bi, idx, jnp.maximum(bi, idx - bn)),
        start_rem,
    )
    cnt_after_rem = jnp.where(is_ins, cnt, new_cnt_rem)

    new_idx = jnp.where(bk == K_INSERT, idx_after_ins, idx_after_rem)
    new_cnt = jnp.where(bk == K_INSERT, cnt, cnt_after_rem)
    return (kind, new_idx, new_cnt, flag), None


@jax.jit
def rebase_batch(kinds: jnp.ndarray, idxs: jnp.ndarray, cnts: jnp.ndarray,
                 base_kinds: jnp.ndarray, base_idxs: jnp.ndarray,
                 base_cnts: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Rebase N pending ops over M base ops (applied in order) in one
    XLA computation: lax.scan over the base window, every pending op
    adjusted in parallel per step."""
    (k, i, c, f), _ = jax.lax.scan(
        _rebase_step,
        (kinds, idxs, cnts, jnp.zeros(kinds.shape, bool)),
        (base_kinds, base_idxs, base_cnts),
    )
    return k, i, c, f


def rebase_ops_columnar(ops: np.ndarray, base: np.ndarray):
    """numpy convenience: ops/base are [N,3]/[M,3] arrays of
    (kind, index, count). Returns (rebased [N,3], flagged [N]) —
    flagged ops hit the split case and must reroute through the
    scalar changeset path (count 0 = muted)."""
    k, i, c, f = rebase_batch(
        jnp.asarray(ops[:, 0]), jnp.asarray(ops[:, 1]), jnp.asarray(ops[:, 2]),
        jnp.asarray(base[:, 0]), jnp.asarray(base[:, 1]), jnp.asarray(base[:, 2]),
    )
    out = np.stack([np.asarray(k), np.asarray(i), np.asarray(c)], axis=1)
    return out, np.asarray(f)


@functools.partial(jax.jit, static_argnums=())
def rebase_commit_range(kinds, idxs, cnts, commit_ids, base_kinds,
                        base_idxs, base_cnts):
    """Config-4 shape: a RANGE of commits (ops tagged by commit id,
    already concatenated columnar) rebases over a trunk window — same
    scan, the commit structure rides along untouched."""
    k, i, c, f = rebase_batch(kinds, idxs, cnts, base_kinds, base_idxs, base_cnts)
    return k, i, c, f, commit_ids
