"""Chunked forest: columnar uniform-chunk tree storage.

The role of the reference's chunked-forest
(packages/dds/tree/src/feature-libraries/chunked-forest/): tree
content is stored in CHUNKS, and runs of same-shaped nodes share one
compact representation instead of per-node objects. The TPU-idiomatic
form of "uniform chunk" is COLUMNAR: a run of same-type leaf nodes is
one numpy value array — bulk loads of tabular data cost one array, and
`column()` exposes whole fields to numpy/JAX analytics without ever
materializing node objects (the chunked-forest's cursor-over-chunks
idea, re-pointed at array programs).

`ChunkedForest` implements the SAME `apply(change)` contract as
`forest.Forest` (inserts/removes/setValue/move with capture-for-
invert enrichment) and is differentially fuzzed against it
(tests/test_chunked_forest.py). Structure:

- every field is a list of chunks;
- `UniformChunk`: N same-type leaves, values in one numpy object
  array (no per-node dicts);
- `ObjectChunk`: one ordinary node dict (arbitrary subtree).

Edits split uniform chunks copy-on-write at touch points; bulk
same-type leaf inserts re-form uniform chunks.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .forest import FieldOps, apply_move_op, canon_json, make_node

Change = List[dict]


class UniformChunk:
    """A run of same-type, field-less leaf nodes, stored columnar."""

    __slots__ = ("type", "values")

    def __init__(self, type_: Optional[str], values: np.ndarray):
        self.type = type_
        self.values = values  # object ndarray

    def __len__(self) -> int:
        return len(self.values)

    def materialize(self, i: int) -> dict:
        return make_node(self.type, self.values[i])

    def to_nodes(self) -> List[dict]:
        return [self.materialize(i) for i in range(len(self.values))]

    def slice(self, lo: int, hi: int) -> "UniformChunk":
        return UniformChunk(self.type, self.values[lo:hi].copy())


class ObjectChunk:
    __slots__ = ("node",)

    def __init__(self, node: dict):
        self.node = node

    def __len__(self) -> int:
        return 1


def _leafable(node: dict) -> bool:
    return not any(node.get("fields", {}).values())


def _chunk_nodes(nodes: List[dict]) -> List[Any]:
    """Pack a node list into chunks: maximal same-type leaf runs
    become uniform chunks (>= 2 nodes), everything else object
    chunks."""
    out: List[Any] = []
    run: List[dict] = []

    def flush():
        if not run:
            return
        if len(run) >= 2:
            out.append(UniformChunk(
                run[0].get("type"),
                np.array([n.get("value") for n in run], dtype=object),
            ))
        else:
            out.extend(ObjectChunk(n) for n in run)
        run.clear()

    for n in nodes:
        if _leafable(n):
            if run and run[0].get("type") != n.get("type"):
                flush()
            run.append(n)
        else:
            flush()
            out.append(ObjectChunk(n))
    flush()
    return out


class ChunkedField:
    """One field's children as a chunk list."""

    __slots__ = ("chunks",)

    def __init__(self, chunks: Optional[List[Any]] = None):
        self.chunks = chunks or []

    def __len__(self) -> int:
        return sum(len(c) for c in self.chunks)

    # ------------------------------------------------------- navigation

    def _locate(self, index: int) -> Tuple[int, int]:
        """(chunk index, offset) of node `index`; chunk index may be
        len(chunks) with offset 0 for the end position."""
        pos = 0
        for ci, c in enumerate(self.chunks):
            if index < pos + len(c):
                return ci, index - pos
            pos += len(c)
        return len(self.chunks), 0

    def _split_at(self, index: int) -> int:
        """Split chunks so node boundary `index` falls between chunks;
        returns the chunk index of the boundary."""
        pos = 0
        for ci, c in enumerate(self.chunks):
            if index == pos:
                return ci
            if index < pos + len(c):
                off = index - pos
                if isinstance(c, UniformChunk):
                    self.chunks[ci: ci + 1] = [
                        c.slice(0, off), c.slice(off, len(c))
                    ]
                    return ci + 1
                return ci  # object chunk: boundary can't be inside
            pos += len(c)
        return len(self.chunks)

    def node_ref(self, index: int):
        """(kind, ...) addressing node `index`: ("obj", node_dict) or
        ("leaf", chunk, offset)."""
        ci, off = self._locate(index)
        if ci >= len(self.chunks):
            return None
        c = self.chunks[ci]
        if isinstance(c, ObjectChunk):
            return ("obj", c.node)
        return ("leaf", c, off)

    def get_node(self, index: int) -> Optional[dict]:
        ref = self.node_ref(index)
        if ref is None:
            return None
        if ref[0] == "obj":
            return ref[1]
        return ref[1].materialize(ref[2])

    # -------------------------------------------------------- mutation

    def insert(self, index: int, nodes: List[dict]) -> None:
        ci = self._split_at(min(index, len(self)))
        self.chunks[ci:ci] = _chunk_nodes(copy.deepcopy(nodes))

    def detach(self, index: int, count: int) -> List[dict]:
        lo = self._split_at(min(index, len(self)))
        hi = self._split_at(min(index + count, len(self)))
        taken = self.chunks[lo:hi]
        del self.chunks[lo:hi]
        out: List[dict] = []
        for c in taken:
            if isinstance(c, ObjectChunk):
                out.append(c.node)
            else:
                out.extend(c.to_nodes())
        return out

    def set_value(self, index: int, value: Any) -> Tuple[bool, Any]:
        """Set node's value in place; returns (ok, previous)."""
        ref = self.node_ref(index)
        if ref is None:
            return False, None
        if ref[0] == "obj":
            node = ref[1]
            prev = node.get("value")
            if value is None:
                node.pop("value", None)
            else:
                node["value"] = value
            return True, prev
        _, chunk, off = ref
        prev = chunk.values[off]
        chunk.values[off] = value
        return True, prev

    def to_nodes(self) -> List[dict]:
        out: List[dict] = []
        for c in self.chunks:
            if isinstance(c, ObjectChunk):
                out.append(c.node)
            else:
                out.extend(c.to_nodes())
        return out

    def column(self) -> np.ndarray:
        """All child values as one array (uniform chunks contribute
        their arrays directly; object nodes their value slot)."""
        parts = []
        for c in self.chunks:
            if isinstance(c, UniformChunk):
                parts.append(c.values)
            else:
                parts.append(np.array([c.node.get("value")], dtype=object))
        if not parts:
            return np.array([], dtype=object)
        return np.concatenate(parts)

    def uniform_ratio(self) -> float:
        n = len(self)
        if n == 0:
            return 0.0
        u = sum(len(c) for c in self.chunks if isinstance(c, UniformChunk))
        return u / n


class ChunkedForest:
    """Forest with chunked field storage; `apply` contract identical
    to `forest.Forest` (differential gate: tests/test_chunked_forest
    .py fuzz)."""

    def __init__(self, root: Optional[dict] = None):
        self.root = root if root is not None else make_node("root")
        # Chunked fields are stored per-NODE as a shadow dict on
        # object nodes: node["fields"][f] is replaced lazily by a
        # ChunkedField under this wrapper's management.

    # ---------------------------------------------------------- fields

    def _field_of(self, node: dict, field: str,
                  create: bool = False) -> Optional[ChunkedField]:
        fields = node.setdefault("fields", {})
        cur = fields.get(field)
        if isinstance(cur, ChunkedField):
            return cur
        if cur is None:
            if not create:
                return None
            cf = ChunkedField()
            fields[field] = cf
            return cf
        cf = ChunkedField(_chunk_nodes(cur))
        fields[field] = cf
        return cf

    def node_at(self, path: List[list],
                for_mutation: bool = False) -> Optional[dict]:
        """Resolve a path. Reads return a materialized COPY for leaf
        chunks (reads must not erode uniform chunks); MUTATION paths
        pass ``for_mutation=True`` so a targeted leaf splits out of
        its chunk in place and edits (e.g. creating a field under a
        leaf) land in the real tree."""
        node = self.root
        for field, index in path:
            cf = self._field_of(node, field)
            if cf is None:
                return None
            ref = cf.node_ref(index)
            if ref is None:
                return None
            if ref[0] == "leaf":
                _, chunk, off = ref
                if for_mutation:
                    node_d = chunk.materialize(off)
                    cf.detach(index, 1)
                    cf.insert(index, [node_d])
                    ref2 = cf.node_ref(index)
                    node = ref2[1] if ref2[0] == "obj" else node_d
                else:
                    node = chunk.materialize(off)
            else:
                node = ref[1]
        return node

    def _field(self, path: List[list], field: str) -> Optional[ChunkedField]:
        node = self.node_at(path, for_mutation=True)
        if node is None:
            return None
        return self._field_of(node, field, create=True)

    # ------------------------------------------------------------ apply

    def apply(self, change: Change) -> None:
        for op in change:
            t = op["type"]
            if t == "insert":
                cf = self._field(op["path"], op["field"])
                if cf is None:
                    continue
                cf.insert(min(op["index"], len(cf)), op["content"])
            elif t == "remove":
                cf = self._field(op["path"], op["field"])
                if cf is None:
                    continue
                index = op["index"]
                end = min(index + op["count"], len(cf))
                nodes = cf.detach(index, max(end - index, 0))
                op["content"] = [self._deep_json(n) for n in nodes]
            elif t == "setValue":
                path = op["path"]
                if not path:
                    # Root value (same semantics as Forest.apply).
                    op["prev"] = self.root.get("value")
                    if op["value"] is None:
                        self.root.pop("value", None)
                    else:
                        self.root["value"] = op["value"]
                    continue
                parent = self.node_at(path[:-1], for_mutation=True)
                if parent is None:
                    continue
                f, i = path[-1]
                cf = self._field_of(parent, f)
                if cf is None:
                    continue
                ok, prev = cf.set_value(i, op["value"])
                if ok:
                    op["prev"] = prev
            elif t == "move":
                self._apply_move(op)

    def _apply_move(self, op: dict) -> None:
        apply_move_op(op, self._resolve_field_ops)

    def _resolve_field_ops(self, path, field) -> Optional[FieldOps]:
        cf = self._field(path, field)
        if cf is None:
            return None
        return FieldOps(cf, lambda: len(cf), cf.detach, cf.insert)

    # ------------------------------------------------------------ export

    def _deep_json(self, node: dict) -> dict:
        return canon_json(node)

    def to_json(self) -> dict:
        return canon_json(self.root)

    def clone(self) -> "ChunkedForest":
        return ChunkedForest(copy.deepcopy(self.to_json()))

    def node_count(self) -> int:
        def count(node: dict) -> int:
            total = 1
            for f, cs in node.get("fields", {}).items():
                kids = cs.to_nodes() if isinstance(cs, ChunkedField) else cs
                total += sum(count(c) for c in kids)
            return total

        return count(self.root)

    # --------------------------------------------------------- analytics

    def column(self, path: List[list], field: str) -> np.ndarray:
        """Bulk value read of one field — uniform chunks feed their
        arrays straight through (zero node materialization)."""
        node = self.node_at(path)
        if node is None:
            return np.array([], dtype=object)
        cf = self._field_of(node, field)
        if cf is None:
            return np.array([], dtype=object)
        return cf.column()

    def uniform_ratio(self, path: List[list], field: str) -> float:
        node = self.node_at(path)
        cf = self._field_of(node, field) if node else None
        return cf.uniform_ratio() if cf else 0.0
